from .api import ApiError, ApiServer, CookApi  # noqa: F401
