"""REST API server.

Re-implements the behavior-bearing endpoint surface of the reference's REST
layer (reference: scheduler/src/cook/rest/api.clj:3640-4019 main-handler)
over the stdlib threading HTTP server:

  POST   /jobs                batch submit (validation, plugins, queue limits,
                              submission rate limit, commit-latch atomicity)
  GET    /jobs?uuid=&user=&state=   query jobs
  GET    /jobs/<uuid>         one job with instances
  DELETE /jobs?uuid=...       kill jobs
  POST   /retry               {"job": uuid, "retries": n}
  GET    /instances/<task-id>
  GET    /queue               per-pool ranked queue (leader only)
  GET    /running             running instances
  GET    /usage?user=         aggregate running usage per pool
  GET/POST/DELETE /share      fair-share admin
  GET/POST/DELETE /quota      quota admin
  GET    /pools
  GET    /unscheduled_jobs?job=uuid
  GET    /failure_reasons
  GET    /stats/instances
  GET    /settings, /info, /debug, /metrics
  GET    /debug/cycles?limit=       flight-recorder CycleRecords
  GET    /debug/trace?trace_id=     Chrome/Perfetto trace-event export
  POST   /progress/<task-id>  sidecar progress callback

AuthN is the reference's composable scheme reduced to HTTP basic / an
X-Cook-User header ("open" mode), with admin checks and impersonation via
X-Cook-Impersonate (reference: rest/authorization.clj, impersonation.clj).
"""

from __future__ import annotations

import base64
import copy
import hmac
import json
import re
import socket
import threading
import time
import urllib.parse
import uuid as uuidlib
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional

from ..config import Config
from ..utils import tracing
from . import instrument
from ..policy import PluginRegistry, QueueLimits, RateLimits
from ..sched.scheduler import Scheduler
from ..sched.unscheduled import job_reasons
# re-exported on the REST surface; the store-derived status itself is
# domain logic and lives in the state layer
from ..state.machines import gang_status  # noqa: F401
from ..state.schema import (
    GANG_POLICIES,
    GANG_POLICY_REQUEUE,
    Application,
    Constraint,
    Group,
    GroupPlacementType,
    InstanceStatus,
    Job,
    JobState,
    Reasons,
    Resources,
    new_uuid,
    to_json,
)
from ..state.store import (AbortTransaction, ReplicationIndeterminate,
                           StorageFullError, Store)
from . import task_stats


# (method, path, summary, leader_only) — the documented API surface served
# by _Handler._dispatch; /swagger-docs and /swagger-ui render this table
# (reference: the compojure-api Swagger surface, rest/api.clj:3640-4019).
API_ROUTES = [
    ("GET", "/jobs/{uuid}", "one job with instances", False),
    ("GET", "/jobs", "batch job query by uuid params", False),
    ("POST", "/jobs", "submit a batch of jobs (atomic)", False),
    ("DELETE", "/jobs", "kill jobs by uuid", False),
    ("GET", "/rawscheduler", "deprecated job CRUD (query)", False),
    ("POST", "/rawscheduler", "deprecated job CRUD (submit)", False),
    ("DELETE", "/rawscheduler", "deprecated job CRUD (kill)", False),
    ("GET", "/instances/{task_id}", "one instance", False),
    ("DELETE", "/instances", "kill instances by task id", False),
    ("GET", "/share", "fair-share weights for a user", False),
    ("POST", "/share", "set shares (admin)", False),
    ("DELETE", "/share", "retract shares (admin)", False),
    ("GET", "/quota", "hard caps for a user", False),
    ("POST", "/quota", "set quotas (admin)", False),
    ("DELETE", "/quota", "retract quotas (admin)", False),
    ("GET", "/usage", "a user's running usage per pool", False),
    ("POST", "/retry", "raise retries / requeue a job", False),
    ("GET", "/group", "job group status", False),
    ("DELETE", "/group", "kill a job group", False),
    ("GET", "/list", "query jobs by user/state/time window", False),
    ("GET", "/queue", "ranked pending queues (admin)", True),
    ("GET", "/running", "running instances", False),
    ("GET", "/unscheduled_jobs", "why-unscheduled explanations", True),
    ("GET", "/failure_reasons", "failure reason table", False),
    ("GET", "/stats/instances", "instance statistics", False),
    ("GET", "/settings", "effective scheduler settings", False),
    ("POST", "/settings/rebalancer",
     "update rebalancer params, no restart (admin)", True),
    ("GET", "/pools", "pool listing", False),
    ("GET", "/info", "version + leadership", False),
    ("GET", "/debug", "health + recent tracing spans", False),
    ("GET", "/debug/cycles", "flight-recorder cycle records", False),
    ("GET", "/debug/trace", "Chrome/Perfetto trace-event export", False),
    ("GET", "/debug/faults",
     "active fault points, breaker states, open launch intents", False),
    ("GET", "/debug/replication",
     "replication/failover panel: follower offsets, min_acked, synced "
     "set, candidate positions", False),
    ("GET", "/debug/job/{uuid}/timeline",
     "per-job scheduling audit timeline (why isn't my job running)",
     False),
    ("GET", "/debug/requests",
     "recent + slow REST requests with per-phase breakdown "
     "(redacted params)", False),
    ("GET", "/debug/health",
     "one-shot health roll-up: SLO burn rates, breakers, replication "
     "lag, pipeline depth, repack counters, audit queue depth", False),
    ("GET", "/debug/storage",
     "persistence-integrity panel: per-partition scrub progress, "
     "corruption/repair counters, checkpoint manifest status, mirror "
     "poison state", False),
    ("GET", "/debug/optimizer",
     "goodput optimizer panel: last per-pool decisions, cycle "
     "counts/errors, elastic resize plane state", False),
    ("GET", "/debug/trace/spans",
     "raw local span-ring docs for one trace id — the fleet trace "
     "collector's per-member stitch source", False),
    ("GET", "/debug/fleet",
     "federated fleet panel: per-member health, staleness, burn, "
     "saturation hot-spots, last-scrape age", False),
    ("GET", "/debug/federation/summary",
     "this cell's bounded per-user summary table + host inventory for "
     "a federation front door's global fair-share merge and goodput "
     "routing (never job state)", False),
    ("GET", "/metrics", "Prometheus metrics", False),
    ("GET", "/metrics/fleet",
     "merged fleet exposition: every member's /metrics re-labeled "
     "with instance/role", False),
    ("POST", "/progress/{task_id}", "sidecar progress frames", True),
    ("POST", "/shutdown-leader", "resign leadership (admin)", True),
    ("GET", "/compute-clusters", "dynamic cluster configs", False),
    ("POST", "/compute-clusters/{name}", "create/update/drain a cluster",
     True),
    ("GET", "/incremental-config", "gradual-rollout config values", False),
    ("POST", "/incremental-config", "set rollout portions (admin)", True),
    ("GET", "/swagger-docs", "this API description (OpenAPI)", False),
    ("GET", "/swagger-ui", "human-readable API listing", False),
]


class ApiError(Exception):
    def __init__(self, status: int, message: str,
                 headers: Optional[Dict[str, str]] = None,
                 extra: Optional[Dict[str, Any]] = None):
        super().__init__(message)
        self.status = status
        self.message = message
        self.headers = headers or {}
        # merged into the JSON error body (e.g. the indeterminate-commit
        # contract: {"error": ..., "indeterminate": true, "jobs": [...]})
        self.extra = extra or {}


class RequestUser(str):
    """A resolved request identity: a plain str plus the fact that it was
    reached via X-Cook-Impersonate (admin gating refuses those)."""

    impersonated: bool

    def __new__(cls, name: str, impersonated: bool = False):
        self = super().__new__(cls, name)
        self.impersonated = impersonated
        return self


class _Redirect(Exception):
    def __init__(self, location: str):
        super().__init__(location)
        self.location = location


def job_state_string(store: Store, job: Job,
                     instances: Optional[List] = None) -> str:
    """waiting | running | success | failed — the reference resolves a
    completed job to success/failed from its instances (tools.clj:310-321
    job-ent->state); ``status`` keeps the raw tri-state.  Pass already-
    fetched ``instances`` to avoid re-reading them from the store."""
    if job.state is not JobState.COMPLETED:
        return job.state.value
    if instances is None:
        instances = [i for t in job.instances
                     if (i := store.instance(t)) is not None]
    for inst in instances:
        if inst.status is InstanceStatus.SUCCESS:
            return "success"
    return "failed"


def job_to_json(store: Store, job: Job, include_instances=True,
                gang_cache: Optional[Dict[str, Dict]] = None) -> Dict:
    # fetched once, shared by the state resolution and the instances block;
    # skipped entirely for waiting/running summaries (no reader needs them)
    instances = ([i for t in job.instances
                  if (i := store.instance(t)) is not None]
                 if include_instances or job.state is JobState.COMPLETED
                 else [])
    out = {
        "uuid": job.uuid, "name": job.name, "command": job.command,
        "user": job.user, "priority": job.priority, "pool": job.pool,
        "state": job_state_string(store, job, instances),
        "status": {"waiting": "waiting", "running": "running",
                   "completed": "completed"}[job.state.value],
        "cpus": job.resources.cpus, "mem": job.resources.mem,
        "gpus": job.resources.gpus, "disk": job.resources.disk,
        "max_retries": job.max_retries, "max_runtime": job.max_runtime_ms,
        "submit_time": job.submit_time_ms, "labels": job.labels,
        "env": job.env, "ports": job.ports,
        "container": job.container,
        "groups": [job.group] if job.group else [],
        "constraints": [[c.attribute, c.operator, c.pattern]
                        for c in job.constraints],
        "disable_mea_culpa_retries": job.disable_mea_culpa_retries,
        "uris": job.uris,
        "executor": job.executor,
        "expected_runtime": job.expected_runtime_ms,
        "progress_output_file": job.progress_output_file,
        "progress_regex_string": job.progress_regex_string,
        "datasets": job.datasets,
        "application": ({"name": job.application.name,
                         "version": job.application.version,
                         "workload-class": job.application.workload_class,
                         "workload-id": job.application.workload_id,
                         "workload-details":
                             job.application.workload_details}
                        if job.application else None),
    }
    if job.group is not None:
        if gang_cache is not None and job.group in gang_cache:
            cached = gang_cache[job.group]
            if cached:  # {} marks a known non-gang group
                out["gang"] = {"group": job.group, **cached}
        else:
            group = store.group(job.group)
            if group is not None and group.gang:
                out["gang"] = {"group": group.uuid,
                               **gang_status(store, group,
                                             cache=gang_cache)}
            elif gang_cache is not None:
                gang_cache[job.group] = {}
    if include_instances:
        out["instances"] = [instance_to_json(i) for i in instances]
    return out




def instance_to_json(inst) -> Dict:
    reason = Reasons.by_code(inst.reason_code) if inst.reason_code is not None \
        else None
    return {
        "task_id": inst.task_id, "job_uuid": inst.job_uuid,
        "status": inst.status.value, "hostname": inst.hostname,
        "slave_id": inst.slave_id, "compute_cluster": inst.compute_cluster,
        "start_time": inst.start_time_ms, "end_time": inst.end_time_ms,
        "preempted": inst.preempted, "progress": inst.progress,
        "progress_message": inst.progress_message,
        "exit_code": inst.exit_code, "ports": inst.ports,
        "reason_code": inst.reason_code,
        "reason_string": reason.name if reason else None,
        "mea_culpa": reason.mea_culpa if reason else None,
        "sandbox_directory": inst.sandbox_directory,
        "output_url": inst.output_url,
        "queue_time": inst.queue_time_ms,
    }


# docker parameters forwarded to the container runtime without an operator
# allowlist configured: benign task-shape flags only.  Anything else
# (privileged, volume, cap-add, device, ...) reaches the runtime argv and
# is host-privilege-bearing, so it is DENIED unless explicitly allowlisted
# via TaskConstraints.docker_parameters_allowed.
DEFAULT_DOCKER_PARAMETERS_ALLOWED = (
    "env", "workdir", "label", "user", "entrypoint", "name")


# C0 control characters (plus DEL) in docker parameter keys/values: the
# agent wire format joins key=value pairs with \x1e and the agent splits on
# it, so an embedded \x1e in an ALLOWLISTED parameter's value would inject
# arbitrary extra runtime flags (e.g. ``privileged=``) past the allowlist.
# No legitimate docker flag or value contains control characters.
_CTRL_CHARS = re.compile(r"[\x00-\x1f\x7f]")


def validate_docker_parameters(job: Job, tc) -> None:
    """Docker parameters are validated for EVERY submission (unlike the
    other task constraints, which an operator opts into): they compile to
    container-runtime flags on the agent, so an unvalidated key like
    ``privileged`` would be a privilege escalation.  Both the flat
    ``container.parameters`` and nested ``container.docker.parameters``
    forms are validated (backends read the flat form today, but an
    unvalidated nested list must never sit in the store).  The operator's
    allowlist (tc.docker_parameters_allowed) replaces the conservative
    default when configured (reference: :docker-parameters-allowed,
    rest/api.clj + integration test_disallowed_docker_parameters)."""
    if not isinstance(job.container, dict):
        return
    # the control-character rule (keys reject ALL control chars, values
    # the wire-breaking bytes) has ONE home, check_container_wire_bytes —
    # delegated here so a direct caller of this validator still gets it
    check_container_wire_bytes(job.container)
    flat = job.container.get("parameters") or []
    docker = job.container.get("docker")
    nested = (docker.get("parameters") or []) \
        if isinstance(docker, dict) else []
    # normalize_container aliases the nested list into the flat slot when
    # only the nested form was submitted — skip the alias, validate both
    # lists when they really are distinct
    params = list(flat) + ([] if nested is flat else list(nested))
    allowed = set(tc.docker_parameters_allowed
                  if tc is not None and tc.docker_parameters_allowed
                  is not None else DEFAULT_DOCKER_PARAMETERS_ALLOWED)
    if "*" not in allowed:
        # ["*"] is the explicit allow-all opt-out restoring the reference's
        # unconfigured behavior (rest/api.clj:1097 allows everything when
        # no allowlist is set; here unset means the conservative default —
        # see docs/DEPLOY.md).  Control characters stay rejected above.
        bad = [p.get("key") for p in params
               if isinstance(p, dict) and p.get("key") not in allowed]
        if bad:
            raise ApiError(400, "The following parameters are not "
                                f"supported: {bad}")
    unvalued = [p.get("key") for p in params
                if isinstance(p, dict) and p.get("key")
                and not p.get("value")]
    if unvalued:
        # a bare "--key" would make the runtime consume the IMAGE as the
        # flag's value — reject instead of launching the wrong container
        raise ApiError(400, f"docker parameters {unvalued} require a value")


def validate_task_constraints(job: Job, tc) -> None:
    """Submission-time task-constraint checks, messages mirroring the
    reference (rest/api.clj:1070-1103 validate-and-munge-job)."""
    validate_docker_parameters(job, tc)
    if tc is None:
        return
    if tc.cpus is not None and job.resources.cpus > tc.cpus:
        raise ApiError(400, f"Requested {job.resources.cpus} cpus, but only "
                            f"allowed to use {tc.cpus}")
    if tc.memory_gb is not None and job.resources.mem > 1024 * tc.memory_gb:
        raise ApiError(400, f"Requested {job.resources.mem}mb memory, but "
                            f"only allowed to use {1024 * tc.memory_gb}")
    if tc.max_ports is not None and job.ports > tc.max_ports:
        raise ApiError(400, f"Requested {job.ports} ports, but only allowed "
                            f"to use {tc.max_ports}")
    if tc.retry_limit is not None and job.max_retries > tc.retry_limit:
        raise ApiError(400, f"Requested {job.max_retries} exceeds the "
                            f"maximum retry limit")
    if tc.command_length_limit is not None \
            and len(job.command) > tc.command_length_limit:
        raise ApiError(400, f"Job command length of {len(job.command)} is "
                            f"greater than the maximum command length "
                            f"({tc.command_length_limit})")


def normalize_container(raw) -> Optional[Dict]:
    """Container spec -> the canonical flat form backends consume.

    Accepts both the flat form ({"image", "volumes", "parameters"}) and
    the reference's nested Mesos form ({"type": "docker", "docker":
    {"image", "network", "force-pull-image", "parameters"}, "volumes"},
    rest/api.clj Container/DockerInfo schemas).  The nested ``docker``
    subdict is preserved so validators and clients see what was
    submitted."""
    if not isinstance(raw, dict):
        return raw
    docker = raw.get("docker")
    if not isinstance(docker, dict):
        return raw
    norm = dict(raw)
    norm.setdefault("image", docker.get("image", ""))
    norm.setdefault("parameters", docker.get("parameters", []))
    if docker.get("network") is not None:
        norm.setdefault("network", docker.get("network"))
    return norm


# NUL truncates at the native transport's C-string boundary (everything
# after it in the marshaled channel is silently dropped) and \x1e is that
# transport's intra-channel delimiter (an embedded one injects extra
# env/volume entries).  Neither byte has a legitimate use in a job spec,
# so they are rejected at submission with a 400 instead of surfacing as
# an opaque launch failure per attempt.
_WIRE_BREAKING = re.compile(r"[\x00\x1e]")


def check_env_wire_bytes(env, what: str = "env variable") -> None:
    """Shared by submitted env, operator pool-default env (at boot and at
    merge), and any other KEY=VALUE channel that reaches the wire."""
    for k, v in (env.items() if isinstance(env, dict) else ()):
        if _WIRE_BREAKING.search(str(k)) or _WIRE_BREAKING.search(str(v)):
            raise ApiError(400, f"{what} {k!r} contains NUL or "
                                "field-separator control characters")


def check_container_wire_bytes(container) -> None:
    """Volumes, image, and docker parameters reach the \\x1e/NUL-sensitive
    wire; used for both submitted containers and operator pool-default
    containers (the latter attach after the per-spec pass).  Malformed
    shapes are skipped here — the parse path's own type errors surface as
    400 malformed-spec."""
    if not isinstance(container, dict):
        return
    params = [*(container.get("parameters") or []),
              *((container.get("docker") or {}).get("parameters") or []
                if isinstance(container.get("docker"), dict) else [])]
    for p in params:
        # same rule validate_docker_parameters applies: keys reject ALL
        # control characters (they compile to --key flags), values the
        # wire-breaking bytes — so an operator default that would 400 a
        # submitter is caught here (at boot / as a 500) first
        if isinstance(p, dict) and (
                _CTRL_CHARS.search(str(p.get("key") or ""))
                or _WIRE_BREAKING.search(str(p.get("value") or ""))):
            raise ApiError(400, "docker parameters must not contain "
                                "control characters")
    vols = container.get("volumes", [])
    for v in (vols if isinstance(vols, (list, tuple)) else []):
        # dict form ({"host-path", "container-path"}) is checked value by
        # value — serializing it would escape the raw bytes out of reach
        parts = [v] if isinstance(v, str) else \
            [str(x) for x in v.values()] if isinstance(v, dict) else \
            [str(v)]
        if any(_WIRE_BREAKING.search(p) for p in parts):
            raise ApiError(400, "container volumes must not contain NUL "
                                "or field-separator control characters")
    images = [container.get("image", ""),
              (container.get("docker") or {}).get("image", "")
              if isinstance(container.get("docker"), dict) else ""]
    if any(_WIRE_BREAKING.search(str(i)) for i in images if i):
        raise ApiError(400, "container image must not contain NUL or "
                            "field-separator control characters")


def _reject_wire_breaking_bytes(spec: Dict) -> None:
    check_env_wire_bytes(spec.get("env"))
    check_container_wire_bytes(spec.get("container"))
    if _WIRE_BREAKING.search(str(spec.get("command", ""))):
        raise ApiError(400, "command must not contain NUL or "
                            "field-separator control characters")
    for fld in ("uuid", "group", "name"):
        # exported into the wire env (COOK_JOB_UUID/COOK_JOB_GROUP_UUID)
        if _WIRE_BREAKING.search(str(spec.get(fld) or "")):
            raise ApiError(400, f"{fld} must not contain NUL or "
                                "field-separator control characters")
    uris = spec.get("uris")
    for u in (uris if isinstance(uris, (list, tuple)) else []):
        # uri values splice into the wire command as the fetch prelude
        val = u.get("value", "") if isinstance(u, dict) else u
        if _WIRE_BREAKING.search(str(val)):
            raise ApiError(400, "uri values must not contain NUL or "
                                "field-separator control characters")
    for fld in ("progress_output_file", "progress_regex_string"):
        # exported into the wire env for the progress-tracking executor
        if _WIRE_BREAKING.search(str(spec.get(fld) or "")):
            raise ApiError(400, f"{fld} must not contain NUL or "
                                "field-separator control characters")


def parse_job_spec(spec: Dict, user: str, default_pool: str) -> Job:
    """Submission schema -> Job (reference: make-job-txn rest/api.clj:750)."""
    if "command" not in spec:
        raise ApiError(400, "job is missing command")
    _reject_wire_breaking_bytes(spec)
    priority = int(spec.get("priority", 50))
    if not 0 <= priority <= 100:
        raise ApiError(400, "priority must be in [0, 100]")
    constraints = []
    for c in spec.get("constraints", []):
        if len(c) != 3:
            raise ApiError(400, f"malformed constraint {c}")
        constraints.append(Constraint(c[0], c[1], c[2]))
    try:
        return Job(
            uuid=spec.get("uuid") or new_uuid(),
            user=user,
            command=spec["command"],
            name=spec.get("name", "cookjob"),
            resources=Resources(
                cpus=float(spec.get("cpus", 1.0)),
                mem=float(spec.get("mem", 128.0)),
                gpus=float(spec.get("gpus", 0.0)),
                disk=float(spec.get("disk", 0.0))),
            priority=priority,
            max_retries=int(spec.get("max_retries", 1)),
            max_runtime_ms=int(spec.get("max_runtime", 2**53)),
            pool=spec.get("pool", default_pool),
            labels=dict(spec.get("labels", {})),
            env=dict(spec.get("env", {})),
            container=normalize_container(spec.get("container")),
            ports=int(spec.get("ports", 0)),
            uris=[u if isinstance(u, dict) else {"value": u}
                  for u in spec.get("uris", [])],
            executor=spec.get("executor", ""),
            expected_runtime_ms=(int(spec["expected_runtime"])
                                 if spec.get("expected_runtime") is not None
                                 else None),
            progress_output_file=spec.get("progress_output_file", ""),
            progress_regex_string=spec.get("progress_regex_string", ""),
            datasets=list(spec.get("datasets", [])),
            application=(Application(
                name=spec["application"].get("name", ""),
                version=spec["application"].get("version", ""),
                workload_class=spec["application"].get("workload-class", ""),
                workload_id=spec["application"].get("workload-id", ""),
                workload_details=spec["application"].get(
                    "workload-details", ""))
                if isinstance(spec.get("application"), dict) else None),
            constraints=constraints,
            group=spec.get("group"),
            disable_mea_culpa_retries=bool(
                spec.get("disable_mea_culpa_retries", False)),
        )
    except (TypeError, ValueError) as e:
        raise ApiError(400, f"malformed job spec: {e}")


def parse_group_spec(gspec: Dict, job_uuids: List[str]) -> Group:
    """Group submission schema -> Group, including host-placement,
    straggler-handling (reference: rest/api.clj:489-514 HostPlacement/
    StragglerHandling schemas + :925 make-group-txn), and the gang block
    (docs/GANG.md): ``{"gang": {"size": N, "topology": attr?,
    "policy": "requeue"|"kill", "min": M?, "max": X?}}`` declares an
    all-or-nothing multi-host slice job; ``min``/``max`` relax it to an
    ELASTIC gang legal at any member count in ``[min, max]``
    (docs/GANG.md elasticity; ``1 <= min <= max <= size``, both default
    to ``size`` — the rigid contract).  Malformed gang specs are a
    clear 400."""
    try:
        group = Group(uuid=gspec["uuid"],
                      name=gspec.get("name", "defaultgroup"),
                      jobs=job_uuids)
        gang = gspec.get("gang")
        if gang is not None:
            if not isinstance(gang, dict):
                raise ApiError(400, "gang must be an object like "
                                    '{"size": N}')
            size = gang.get("size")
            if not isinstance(size, int) or isinstance(size, bool) \
                    or size < 1:
                raise ApiError(400, "gang.size must be an integer >= 1")
            topology = gang.get("topology")
            if topology is not None and (
                    not isinstance(topology, str) or not topology):
                raise ApiError(400, "gang.topology must be a non-empty "
                                    "host attribute name")
            policy = gang.get("policy", GANG_POLICY_REQUEUE)
            if policy not in GANG_POLICIES:
                raise ApiError(
                    400, f"gang.policy must be one of {GANG_POLICIES}")
            unknown = set(gang) - {"size", "topology", "policy",
                                   "min", "max"}
            if unknown:
                raise ApiError(400, "unknown gang spec key(s): "
                                    f"{sorted(unknown)}")
            # elastic bounds (docs/GANG.md elasticity): unset = rigid
            lo = gang.get("min", 0)
            hi = gang.get("max", 0)
            for key, v in (("min", lo), ("max", hi)):
                if key in gang and (not isinstance(v, int)
                                    or isinstance(v, bool) or v < 1):
                    raise ApiError(400, f"gang.{key} must be an integer "
                                        ">= 1 (or omitted)")
            if (lo or size) > (hi or size):
                raise ApiError(400, "gang.min must be <= gang.max")
            if lo > size or hi > size:
                raise ApiError(
                    400, "gang.min/gang.max cannot exceed gang.size — "
                         "the co-submitted members ARE the maximum "
                         "membership (docs/GANG.md elasticity)")
            group.gang = True
            group.gang_size = size
            group.gang_topology = topology
            group.gang_policy = policy
            group.gang_min = lo
            group.gang_max = hi
        hp = gspec.get("host-placement") or gspec.get("host_placement")
        if hp:
            try:
                group.placement_type = GroupPlacementType(
                    hp.get("type", "all"))
            except ValueError:
                raise ApiError(
                    400, f"unknown host-placement type {hp.get('type')}")
            params = hp.get("parameters") or {}
            group.placement_attribute = params.get("attribute")
            if group.placement_type is GroupPlacementType.ATTRIBUTE_EQUALS \
                    and not group.placement_attribute:
                raise ApiError(400, "attribute-equals host-placement "
                                    "requires parameters.attribute")
            if params.get("minimum") is not None:
                group.placement_minimum = int(params["minimum"])
        sh = gspec.get("straggler-handling") or gspec.get("straggler_handling")
        if sh:
            if sh.get("type") not in (None, "none", "quantile-deviation"):
                raise ApiError(
                    400,
                    f"unknown straggler-handling type {sh.get('type')}")
            if sh.get("type") == "quantile-deviation":
                params = sh.get("parameters") or {}
                quantile = float(params.get("quantile", 0.5))
                multiplier = float(params.get("multiplier", 2.0))
                if not 0.0 < quantile < 1.0:
                    raise ApiError(400,
                                   "straggler quantile must be in (0, 1)")
                if multiplier < 1.0:
                    raise ApiError(400, "straggler multiplier must be >= 1")
                group.straggler_quantile = quantile
                group.straggler_multiplier = multiplier
        return group
    except (TypeError, ValueError, AttributeError, KeyError) as e:
        raise ApiError(400, f"malformed group spec: {e}")


class CookApi:
    """Request-handling core, separable from the HTTP plumbing for tests."""

    def __init__(self, store: Store, scheduler: Optional[Scheduler] = None,
                 config: Optional[Config] = None,
                 plugins: Optional[PluginRegistry] = None,
                 rate_limits: Optional[RateLimits] = None,
                 queue_limits: Optional[QueueLimits] = None,
                 admins: Optional[List[str]] = None,
                 impersonators: Optional[List[str]] = None,
                 elector=None, node_url: str = "",
                 basic_auth_users: Optional[Dict[str, str]] = None,
                 cors_origins: Optional[List[str]] = None,
                 authenticators: Optional[List] = None,
                 ip_requests_per_minute: Optional[float] = None):
        from ..policy.incremental import IncrementalConfig
        self.store = store
        self.scheduler = scheduler
        self.config = config or (scheduler.config if scheduler else Config())
        self.plugins = plugins or (scheduler.plugins if scheduler
                                   else PluginRegistry())
        self.rate_limits = rate_limits or (
            scheduler.rate_limits if scheduler else RateLimits())
        self.queue_limits = queue_limits
        self.admins = set(admins or [])
        self.impersonators = set(impersonators or [])
        # HA: api-only nodes redirect leader-only requests (307) to the
        # elected leader (reference: leader-redirect, api-only? config.clj:692)
        self.elector = elector
        self.node_url = node_url
        # socket-replication surfaces (set by the daemon): the leader's
        # ReplicationServer / a standby's ReplicationFollower, and the
        # fence guard that flips the write path to 503/redirect the
        # moment a successor mints a higher election epoch
        self.repl_server = None
        self.repl_follower = None
        #: per-partition ReplicationServers on a partitioned leader
        #: (each partition replicates its own journal to its own
        #: synced-standby set; surfaced on /debug/replication and as
        #: partition-labeled cook_replication_lag_bytes series)
        self.partition_repl_servers: List = []
        self.repl_dir: Optional[str] = None
        self.fence_guard: Optional[Callable[[], bool]] = None
        # follower read fleet (state/read_replica.py, set by the daemon
        # on replication standbys): a live journal-applied store this
        # node serves bounded-staleness GETs from instead of
        # 307-redirecting them to the leader (docs/DEPLOY.md)
        self.read_view = None
        self.follower_reads = 0
        # fleet observability plane (sched/fleet.py, set by the daemon):
        # the FleetScraper behind /metrics/fleet + /debug/fleet and the
        # stitched /debug/trace fan-out, and this node's span identity
        # (every request's spans record under it — the per-process
        # track key of the fleet Perfetto export)
        self.fleet = None
        self.instance: Optional[str] = None
        # HTTP-level per-client-IP throttle (reference: ip-rate-limit
        # middleware wrapping the handler, components.clj:214-221);
        # None = unlimited
        self.ip_limiter = None
        if ip_requests_per_minute:
            from ..policy.rate_limit import TokenBucketRateLimiter
            self.ip_limiter = TokenBucketRateLimiter(
                tokens_per_minute=float(ip_requests_per_minute),
                bucket_size=float(ip_requests_per_minute))
        # layered admission front door (config.AdmissionConfig +
        # sched/admission.py): the admission section can supply both the
        # per-IP bucket (when the daemon-level knob is absent) and the
        # per-user submission bucket; the scheduler's AdmissionController
        # — when one exists — gets handles to BOTH so the adaptive level
        # scales their refill rates under pressure
        ac = self.config.admission
        if ac.enabled:
            from ..policy.rate_limit import (TokenBucketRateLimiter,
                                             submission_limiter)
            if self.ip_limiter is None and ac.ip_requests_per_minute > 0:
                self.ip_limiter = TokenBucketRateLimiter(
                    tokens_per_minute=ac.ip_requests_per_minute,
                    bucket_size=ac.ip_requests_per_minute)
            if not getattr(self.rate_limits.job_submission, "enforce",
                           False):
                self.rate_limits.job_submission = submission_limiter(ac)
        ctrl = scheduler.admission if scheduler is not None else None
        if ctrl is not None:
            ctrl.rate_limits = self.rate_limits
            if self.ip_limiter is not None:
                ctrl.ip_limiter = self.ip_limiter
            ctrl._apply_level()
        self.incremental = IncrementalConfig()
        # HTTP-basic verification (reference: basic_auth.clj). None = "open"
        # mode: the username is taken from Basic/X-Cook-User unverified.
        self.basic_auth_users = basic_auth_users
        # Pluggable scheme chain (reference: spnego/basic/open composition,
        # components.clj:266-284). When set, authentication is mandatory.
        # basic_auth_users is sugar for a single-Basic chain so verified
        # basic auth has exactly one code path.
        from .auth import AuthChain, BasicAuthenticator
        if authenticators:
            self.auth_chain = AuthChain(authenticators)
        elif basic_auth_users is not None:
            self.auth_chain = AuthChain(
                [BasicAuthenticator(dict(basic_auth_users))])
        else:
            self.auth_chain = None
        # CORS allowed-origin regexes (reference: cors.clj; same-origin
        # requests are always allowed, cross-origin must match a pattern)
        self.cors_origins = [re.compile(p) for p in (cors_origins or [])]
        # serving-plane request observability (rest/instrument.py): the
        # module singleton, sized/armed from the "http" config section
        self.request_obs = instrument.request_log
        self.request_obs.configure(self.config.http)

    def origin_allowed(self, origin: str) -> bool:
        return any(rx.fullmatch(origin) for rx in self.cors_origins)

    def check_basic_auth(self, user: str, password: str) -> bool:
        want = (self.basic_auth_users or {}).get(user)
        return want is not None and hmac.compare_digest(want, password)

    def leader_redirect_target(self) -> Optional[str]:
        """Non-None when this node must redirect scheduler-state requests."""
        if self.scheduler is not None or self.elector is None:
            return None
        url = self.elector.leader_url()
        if url and url != self.node_url:
            return url
        return None

    # ------------------------------------------------------- admission
    def admission_controller(self):
        return self.scheduler.admission if self.scheduler is not None \
            else None

    def brownout_stage(self) -> int:
        """The brownout stage this node acts on: the live controller on
        the leader; on followers, the journaled dynamic-config document
        replicated into the read view's mirror (stage flips ride
        ordinary ``"w"`` records, so standbys see them at replication
        latency)."""
        ctrl = self.admission_controller()
        if ctrl is not None:
            return ctrl.stage
        from ..sched.admission import stage_from_store
        rv = self.read_view
        rv_store = getattr(rv, "store", None) if rv is not None else None
        if rv_store is not None:
            return stage_from_store(rv_store)
        return stage_from_store(self.store)

    def admission_state(self) -> Dict:
        """The /debug/health "admission" block on ANY role."""
        ctrl = self.admission_controller()
        if ctrl is not None:
            return ctrl.state()
        from ..sched.admission import STAGE_NAMES
        stage = self.brownout_stage()
        return {"enabled": bool(self.config.admission.enabled),
                "level": None, "stage": stage,
                "stage_name": STAGE_NAMES[stage]}

    # ------------------------------------------------------------------ auth
    def require_admin(self, user: str, message: Optional[str] = None) -> None:
        # an impersonator acting AS an admin may not reach admin endpoints
        # (reference: impersonation.clj object-type->verb table admits no
        # admin verbs; integration test_cannot_impersonate_admin_endpoints)
        if getattr(user, "impersonated", False):
            raise ApiError(403, "impersonated requests may not use "
                                "admin endpoints")
        if self.admins and user not in self.admins:
            raise ApiError(403, message or f"{user} is not authorized")

    def resolve_user(self, auth_user: str, impersonate: Optional[str]) -> str:
        """The effective request identity (reference: impersonation.clj).

        Only configured impersonators may impersonate — being an admin
        grants nothing here (test_admin_cannot_impersonate), and
        self-impersonation is treated as a plain non-impersonated request
        (test_self_impersonate)."""
        if impersonate and impersonate != auth_user:
            if auth_user not in self.impersonators:
                raise ApiError(403, f"{auth_user} may not impersonate")
            return RequestUser(impersonate, impersonated=True)
        return auth_user

    # ---------------------------------------------------------------- routes
    def _admit_submission(self, specs: List[Dict], user: str,
                          idempotent: bool = False) -> None:
        """The submission front door (ISSUE 17 overload ladder): every
        rejection is a 429 with a machine-readable ``reason`` +
        ``scope`` in the body and an honest ``Retry-After`` header, so
        clients back off instead of stampeding.  Order: brownout write
        shed (cheapest, and the explicit overload gate) -> per-user
        token bucket (refill scaled by the admission level) -> GLOBAL
        per-user pending cap off the bounded summary exchange."""
        from ..utils.metrics import registry

        def _reject(reason: str, scope: str, message: str,
                    retry_s: float) -> None:
            registry.counter_inc("cook_admission_rejections", 1.0,
                                 {"scope": scope, "reason": reason})
            retry = max(1, min(int(retry_s) + 1, 3600))
            raise ApiError(429, message,
                           extra={"reason": reason, "scope": scope},
                           headers={"Retry-After": str(retry)})

        ac = self.config.admission
        if ac.enabled and self.brownout_stage() >= 3:
            # stage 3 sheds LOW-PRIORITY writes only; a batch with any
            # at-or-above-threshold job rides the committed-write path,
            # which never sheds
            if all(int(s.get("priority", 50)) < ac.shed_priority_below
                   for s in specs):
                _reject("brownout-shed", "user",
                        "the cluster is shedding low-priority writes "
                        "under overload (brownout stage 3); retry "
                        "later or raise job priority",
                        ac.stage_hold_seconds)
        rl = self.rate_limits.job_submission
        if rl.enforce and rl.get_token_count(user) < len(specs):
            _reject("rate-limited", "user",
                    "job submission rate limit exceeded",
                    rl.retry_after_s(user, len(specs)))
        if ac.enabled and ac.max_user_pending > 0 and not idempotent:
            # idempotent retries are exempt: their jobs may already be
            # journaled and counted by the summaries — charging them
            # again would strand a user at cap unable to heal an
            # ambiguous submission (same principle as the quota gate)
            # GLOBAL pending cap, partitions included: the bounded
            # per-user summary exchange (state/partition.py) is the only
            # cross-partition signal — counts, never job state.  A
            # single store answers from its own summary.
            summaries = getattr(self.store, "summaries", None)
            if summaries is not None:
                pending = summaries.user_totals(str(user))["pending"]
            else:
                u = self.store.user_summary().get(str(user))
                pending = u["pending"] if u else 0.0
            if pending + len(specs) > ac.max_user_pending:
                _reject("user-pending-cap", "global",
                        f"user {user} has {int(pending)} pending jobs; "
                        f"admitting {len(specs)} more would exceed the "
                        f"global cap of {ac.max_user_pending}",
                        ac.stage_hold_seconds)

    def submit_jobs(self, body: Dict, user: str) -> Dict:
        specs = body.get("jobs", [])
        if not specs:
            raise ApiError(400, "no jobs to submit")
        pool_override = body.get("pool")
        self._admit_submission(specs, user,
                               idempotent=bool(body.get("idempotent")))
        jobs = []
        # request trace context (the http.request ingress span, itself
        # parented under a client-sent traceparent): stamped on every job
        # so the submission request stays joinable to the job's audit
        # lifecycle and the launching cycle (docs/OBSERVABILITY.md)
        _cur = tracing.tracer.current()
        req_trace = _cur.trace_id if _cur is not None else None
        for spec in specs:
            job = parse_job_spec(spec, user, self.config.default_pool)
            if req_trace:
                job.trace_id = req_trace
            validate_task_constraints(job, self.config.task_constraints)
            for uri in job.uris:
                if uri.get("executable") and uri.get("extract"):
                    raise ApiError(
                        400, "Uri cannot set executable and extract")
            if pool_override:
                job.pool = pool_override
            job.pool = self.plugins.pool_selector.select(
                job, self.config.default_pool)
            # pool-regex planes, applied with the EFFECTIVE pool known
            # (reference: rest/api.clj:719-738 default container / gpu
            # model / default env resolution per pool)
            if job.container is None:
                default = self.config.default_container_for_pool(job.pool)
                if default:
                    job.container = normalize_container(
                        copy.deepcopy(default))
                    # the default was attached AFTER the per-spec
                    # validation pass — it must clear the same wire-byte
                    # and allowlist checks a direct submission would, but
                    # ANY violation here is the operator's plane, not the
                    # submitter's (clean) spec: surface every one as 500
                    try:
                        check_container_wire_bytes(job.container)
                        validate_docker_parameters(
                            job, self.config.task_constraints)
                    except ApiError as exc:
                        raise ApiError(
                            500, "pool default container is "
                                 f"misconfigured: {exc.message}")
            default_env = self.config.default_env_for_pool(job.pool)
            if default_env:
                # same wire-byte rule the submitted env already cleared.
                # Daemon boot refuses such config (_check_plane_wire_bytes);
                # this guards programmatic Config mutation, and it is a
                # SERVER error — the submitter's spec is clean
                try:
                    check_env_wire_bytes(default_env,
                                         what="pool default env variable")
                except ApiError as exc:
                    raise ApiError(
                        500, f"misconfigured: {exc.message}")
                job.env = {**default_env, **job.env}  # job's values win
            if job.resources.gpus:
                models = self.config.gpu_models_for_pool(job.pool)
                if models is not None:
                    model = job.labels.get("gpu-model", "")
                    if model not in models:
                        raise ApiError(
                            400, f"The following GPU model is not supported "
                                 f"in pool {job.pool}: {model or '(none)'}")
            deny = self.plugins.validate_submission(job)
            if deny:
                raise ApiError(400, f"job {job.uuid}: {deny}")
            jobs.append(self.plugins.modify_submission(job))
        by_pool: Dict[str, int] = {}
        for job in jobs:
            by_pool[job.pool] = by_pool.get(job.pool, 0) + 1
        if self.queue_limits is not None:
            for pool, n in by_pool.items():
                msg = self.queue_limits.check_submission(pool, user, n)
                if msg:
                    raise ApiError(422, msg)
        # cross-partition per-user quota (partitioned write plane,
        # state/partition.py): a finite count quota on the reserved
        # pool "*" caps the user's TOTAL footprint across every
        # partition, enforced off the bounded-staleness summary
        # exchange — never by shipping job state between partitions
        prior_jobs: Dict[str, Any] = {}
        if body.get("idempotent"):
            # an indeterminate-retry resubmits uuids that may already
            # be journaled; ONE membership pass feeds both the quota
            # gate here and the existing/to_create split below
            for j in jobs:
                prior = self.store.job(j.uuid)
                if prior is not None:
                    prior_jobs[j.uuid] = prior
        check_global = getattr(self.store, "check_user_quota", None)
        if check_global is not None:
            # only truly-new jobs consume quota headroom — the
            # already-journaled ones are counted by the summary
            # exchange, and charging them again would leave a user at
            # cap unable to heal their own ambiguous submission
            n_new = sum(1 for j in jobs if j.uuid not in prior_jobs)
            msg = check_global(str(user), n_new) if n_new else None
            if msg:
                raise ApiError(422, msg)
        groups = []
        for gspec in body.get("groups", []):
            guuid = gspec.get("uuid")
            if not guuid:
                raise ApiError(400, "groups must carry a uuid so jobs can "
                                    "reference them")
            group = parse_group_spec(
                gspec, [j.uuid for j in jobs if j.group == guuid])
            if group.gang:
                # a gang launches all-or-nothing, so its members must be
                # co-submitted: exactly gang_size jobs in this batch, and
                # never trickled onto an existing gang group
                if len(group.jobs) != group.gang_size:
                    raise ApiError(
                        400, f"gang group {guuid} declares size "
                             f"{group.gang_size} but the batch carries "
                             f"{len(group.jobs)} member job(s); gang "
                             "members must be submitted together")
                # all members must resolve to ONE pool (per-spec pool
                # overrides and the pool-selector plugin can split
                # them): each pool's queue would hold a strict subset,
                # so cohort admission defers the gang every cycle with
                # a misleading members-missing diagnosis
                member_pools = {j.pool for j in jobs
                                if j.group == guuid}
                if len(member_pools) > 1:
                    raise ApiError(
                        400, f"gang group {guuid} members resolve to "
                             f"multiple pools {sorted(member_pools)}; "
                             "a gang schedules within one pool")
                # an idempotent retry resends the SAME batch after an
                # indeterminate commit — the group legitimately exists
                # and its member set MATCHES, so it passes this check on
                # its own; the idempotent flag must not bypass it (a
                # "retry" carrying novel members would merge into the
                # group and grow the gang past gang_size)
                existing_group = self.store.group(guuid)
                if existing_group is not None and existing_group.jobs \
                        and set(existing_group.jobs) != set(group.jobs):
                    raise ApiError(
                        400, f"group {guuid} already exists; gang "
                             "members cannot be added incrementally")
            groups.append(group)
        # the no-incremental-members rule must also hold for jobs that
        # reference a PRE-EXISTING gang group without a groups entry in
        # this batch: such a job would skip every gang check above and
        # ride the gang's cohort as a phantom extra member (counted by
        # the reduction, invisible to the gang policy)
        batch_guuids = {g.uuid for g in groups}
        ref_cache: Dict[str, object] = {}
        for job in jobs:
            if not job.group or job.group in batch_guuids:
                continue
            if job.group not in ref_cache:
                ref_cache[job.group] = self.store.group(job.group)
            existing = ref_cache[job.group]
            if existing is not None and existing.gang \
                    and not (body.get("idempotent")
                             and job.uuid in (existing.jobs or [])):
                raise ApiError(
                    400, f"group {job.group} is a gang; gang members "
                         "cannot be added incrementally")
        all_uuids = [j.uuid for j in jobs]

        def _indeterminate(exc: Exception) -> ApiError:
            # HTTP 504 + ambiguous-outcome body: the batch is journaled
            # locally but unconfirmed on the mirror.  The uuids let the
            # client retry the SAME logical submission ("idempotent":
            # true) after a failover — neither losing nor duplicating.
            return ApiError(504, str(exc),
                            extra={"indeterminate": True,
                                   "jobs": all_uuids})

        to_create = jobs
        if body.get("idempotent"):
            # retry of an indeterminate submission: jobs that survived
            # (or were stranded mid-latch by the ambiguous commit) count
            # as successes and are made visible; only the rest are
            # created.  Keyed on job uuid — the issue's idempotency unit.
            existing, to_create = [], []
            for job in jobs:
                prior = prior_jobs.get(job.uuid)
                if prior is None:
                    to_create.append(job)
                elif prior.user != user:
                    raise ApiError(409, f"job {job.uuid} exists and "
                                        "belongs to another user")
                else:
                    existing.append(job.uuid)
            if existing:
                try:
                    self.store.commit_jobs(existing)
                except ReplicationIndeterminate as e:
                    raise _indeterminate(e)
        if to_create:
            # atomic batch visibility via commit latch (metatransaction)
            latch = new_uuid()
            try:
                self.store.create_jobs(to_create, groups=groups,
                                       latch=latch)
            except AbortTransaction as e:
                raise ApiError(409, e.reason)
            except ReplicationIndeterminate as e:
                # the jobs ARE installed locally (uncommitted); try to
                # finish the latch so they aren't stranded invisible —
                # a second indeterminate outcome changes nothing the
                # client's retry can't heal via the idempotent path
                try:
                    self.store.commit_latch(latch)
                except ReplicationIndeterminate:
                    pass
                raise _indeterminate(e)
            try:
                self.store.commit_latch(latch)
            except ReplicationIndeterminate as e:
                raise _indeterminate(e)
        self.rate_limits.job_submission.spend(user, len(specs))
        return {"jobs": all_uuids}

    def get_jobs(self, params: Dict) -> List[Dict]:
        uuids = params.get("uuid", [])
        if uuids:
            # partial=true: return the found subset as long as at least one
            # uuid resolves, instead of 404ing the whole query (reference:
            # rest/api.clj:1391-1415 retrieve-jobs allow-partial-results)
            partial = first(params.get("partial"), "false") == "true"
            out = []
            gang_cache: Dict[str, Dict] = {}
            for uuid in uuids:
                job = self.store.job(uuid)
                if job is None:
                    if partial:
                        continue
                    raise ApiError(404, f"no such job {uuid}")
                out.append(job_to_json(self.store, job,
                                       gang_cache=gang_cache))
            if not out:
                raise ApiError(404, f"no such jobs {uuids}")
            return out
        user = first(params.get("user"))
        states = parse_states(params)
        jobs = self.store.jobs_where(
            lambda j: (user is None or j.user == user)
            and job_matches_states(self.store, j, states))
        gang_cache: Dict[str, Dict] = {}
        return [job_to_json(self.store, j, include_instances=False,
                            gang_cache=gang_cache)
                for j in jobs]

    def kill_jobs(self, params: Dict, user: str) -> Dict:
        uuids = params.get("uuid", [])
        if not uuids:
            raise ApiError(400, "no uuids given")
        for uuid in uuids:
            job = self.store.job(uuid)
            if job is None:
                raise ApiError(404, f"no such job {uuid}")
            if job.user != user:
                self.require_admin(user)
        for uuid in uuids:
            self.store.kill_job(uuid)
        return {"killed": uuids}

    def retry(self, body: Dict, user: str, deprecated: bool = True) -> Dict:
        """POST (deprecated: job/jobs + retries/increment only) and PUT
        (adds groups + failed_only) /retry (reference: rest/api.clj:2470-2650
        UpdateRetriesRequest + validate-retries + check-jobs-exist).

        failed_only defaults to True when groups are given, False otherwise
        (api.clj:2569-2573's backwards-compatible default)."""
        if body.get("job") is not None and body.get("jobs") is not None:
            raise ApiError(400, 'Can\'t specify both "job" and "jobs".')
        uuids = body.get("jobs") or ([body["job"]] if body.get("job") else [])
        if deprecated and body.get("groups"):
            raise ApiError(400, 'POST /retry does not support "groups"; '
                                "use PUT.")
        groups = [] if deprecated else (body.get("groups") or [])
        if not uuids and not groups:
            raise ApiError(400, "Need to specify at least 1 job or group.")
        retries = body.get("retries")
        increment = body.get("increment")
        if retries is None and increment is None:
            raise ApiError(400, "Need to specify either retries or increment.")
        if retries is not None and increment is not None:
            raise ApiError(400, "Can't specify both retries and increment.")
        try:
            retries = int(retries) if retries is not None else None
            increment = int(increment) if increment is not None else None
        except (TypeError, ValueError):
            raise ApiError(400, "retries/increment must be integers")
        tc = self.config.task_constraints
        limit = tc.retry_limit if tc is not None else None
        if retries is not None and limit is not None and retries > limit:
            raise ApiError(400, f"'retries' exceeds the maximum retry limit "
                                f"of {limit}")

        failed_only = body.get("failed_only", body.get("failed-only"))
        if failed_only is None:
            failed_only = bool(groups)

        # resolve + authorize every named job/group before touching any
        all_jobs: List[Job] = []
        for uuid in uuids:
            job = self.store.job(uuid)
            if job is None:
                raise ApiError(404,
                               f"UUID {uuid} does not correspond to a job.")
            if job.user != user:
                self.require_admin(
                    user, f"You are not authorized to retry job {uuid}.")
            all_jobs.append(job)
        for guuid in groups:
            group = self.store.group(guuid)
            if group is None:
                raise ApiError(404,
                               f"UUID {guuid} does not correspond to a group.")
            gjobs = [j for j in (self.store.job(u) for u in group.jobs)
                     if j is not None]
            if any(j.user != user for j in gjobs):
                self.require_admin(
                    user, "You are not authorized to retry jobs from "
                          f"group {guuid}.")
            all_jobs.extend(gjobs)

        seen = set()
        targets = []
        for job in all_jobs:
            if job.uuid in seen:
                continue
            seen.add(job.uuid)
            if failed_only \
                    and job_state_string(self.store, job) != "failed":
                continue
            targets.append(job)

        if increment is not None:
            if limit is not None and any(j.max_retries + increment > limit
                                         for j in targets):
                raise ApiError(400, "Increment would exceed the maximum "
                                    f"retry limit of {limit}")
        else:
            for job in targets:
                insts = {t: i for t in job.instances
                         if (i := self.store.instance(t)) is not None}
                if job.attempts_used(insts) > retries:
                    raise ApiError(
                        400, "Retries would be less than attempts-consumed")
        for job in targets:
            new_retries = (job.max_retries + increment
                           if increment is not None else retries)
            self.store.retry_job(job.uuid, new_retries)
        out: Dict[str, Any] = {"jobs": [j.uuid for j in targets],
                               "retries": retries, "increment": increment}
        if body.get("job") is not None:
            # the deprecated single-job POST contract returned {job, retries}
            out["job"] = body["job"]
        return out

    def kill_instances(self, params: Dict, user: str) -> Dict:
        """DELETE /instances?uuid=task-id — kill individual instances
        without aborting the job (reference: rest/api.clj instance kill)."""
        task_ids = params.get("uuid", [])
        if not task_ids:
            raise ApiError(400, "no uuids given")
        for tid in task_ids:
            inst = self.store.instance(tid)
            if inst is None:
                raise ApiError(404, f"no such instance {tid}")
            job = self.store.job(inst.job_uuid)
            if job is not None and job.user != user:
                self.require_admin(user)
        killed = []
        for tid in task_ids:
            inst = self.store.instance(tid)
            if inst.status in (InstanceStatus.UNKNOWN, InstanceStatus.RUNNING):
                if self.scheduler is not None:
                    self.scheduler.kill_instance(
                        tid, Reasons.KILLED_BY_USER.code)
                else:
                    self.store.update_instance_status(
                        tid, InstanceStatus.FAILED,
                        reason_code=Reasons.KILLED_BY_USER.code)
                killed.append(tid)
        return {"killed": killed}

    def group_get(self, params: Dict) -> List[Dict]:
        """GET /group?uuid=...&detailed=true (reference: rest/api.clj
        read-groups-handler)."""
        uuids = params.get("uuid", [])
        if not uuids:
            raise ApiError(400, "no uuids given")
        detailed = first(params.get("detailed"), "false") == "true"
        partial = first(params.get("partial"), "false") == "true"
        out = []
        for uuid in uuids:
            group = self.store.group(uuid)
            if group is None:
                if partial:
                    continue
                raise ApiError(404, f"no such group {uuid}")
            entry: Dict[str, Any] = {
                "uuid": group.uuid, "name": group.name, "jobs": group.jobs,
                "host-placement": {
                    "type": group.placement_type.value,
                    "parameters": {
                        **({"attribute": group.placement_attribute}
                           if group.placement_attribute else {}),
                        **({"minimum": group.placement_minimum}
                           if group.placement_type is
                           GroupPlacementType.BALANCED else {})}},
                "straggler-handling": (
                    {"type": "quantile-deviation",
                     "parameters": {"quantile": group.straggler_quantile,
                                    "multiplier": group.straggler_multiplier}}
                    if group.straggler_quantile is not None
                    else {"type": "none", "parameters": {}})}
            if group.gang:
                entry["gang"] = gang_status(self.store, group)
            jobs = [j for j in (self.store.job(u) for u in group.jobs)
                    if j is not None]
            by_state = {"waiting": 0, "running": 0, "completed": 0}
            for job in jobs:
                by_state[job.state.value] += 1
            entry.update(by_state)
            if detailed:
                gang_cache: Dict[str, Dict] = {}
                entry["detailed"] = [
                    job_to_json(self.store, j, include_instances=False,
                                gang_cache=gang_cache)
                    for j in jobs]
            out.append(entry)
        if not out:
            raise ApiError(404, f"no such groups {uuids}")
        return out

    def group_kill(self, params: Dict, user: str) -> Dict:
        """DELETE /group?uuid=... — kill every job in the groups."""
        uuids = params.get("uuid", [])
        if not uuids:
            raise ApiError(400, "no uuids given")
        job_uuids = []
        for uuid in uuids:
            group = self.store.group(uuid)
            if group is None:
                raise ApiError(404, f"no such group {uuid}")
            for juuid in group.jobs:
                job = self.store.job(juuid)
                if job is None:
                    continue
                if job.user != user:
                    self.require_admin(user)
                job_uuids.append(juuid)
        for juuid in job_uuids:
            self.store.kill_job(juuid)
        return {"killed": job_uuids}

    def list_jobs(self, params: Dict) -> List[Dict]:
        """GET /list?user=&state=&start-ms=&end-ms=&limit=&name=&pool=
        (reference: rest/api.clj:3038 list-resource): jobs filtered by user,
        state set, submit-time window, name pattern (literal with ``*``
        wildcards, api.clj:1670-1675), and pool; newest first."""
        user = first(params.get("user"))
        if user is None:
            raise ApiError(400, "user parameter required")
        states = parse_states(params)
        try:
            start_ms = int(first(params.get("start-ms"), 0))
            end_ms = int(first(params.get("end-ms"), 2**62))
            limit = int(first(params.get("limit"), 150))
        except ValueError as e:
            raise ApiError(400, f"malformed query parameter: {e}")
        if limit <= 0:
            raise ApiError(400, "limit must be positive")
        name_filter = first(params.get("name"))
        name_rx = None
        if name_filter is not None:
            if not re.fullmatch(r"[\w.*\-]*", name_filter):
                raise ApiError(400, f"unsupported name filter {name_filter}")
            name_rx = re.compile(
                name_filter.replace(".", r"\.").replace("*", ".*") + "$")
        pool = first(params.get("pool"))
        jobs = self.store.jobs_where(
            lambda j: j.user == user
            and job_matches_states(self.store, j, states)
            and start_ms <= j.submit_time_ms < end_ms
            and (name_rx is None or name_rx.match(j.name))
            and (pool is None or j.pool == pool))
        jobs.sort(key=lambda j: j.submit_time_ms, reverse=True)
        gang_cache: Dict[str, Dict] = {}
        return [job_to_json(self.store, j, include_instances=False,
                            gang_cache=gang_cache)
                for j in jobs[:limit]]

    def shutdown_leader(self, user: str) -> Dict:
        """POST /shutdown-leader — admin-only; the leader resigns so a
        follower takes over (reference: the leader deliberately exits and
        the supervisor restarts it, mesos.clj:296-313)."""
        self.require_admin(user)
        if self.scheduler is None:
            raise ApiError(503, "this node is not the leader")
        self.scheduler.shutdown()
        if self.elector is not None:
            try:
                self.elector.resign()
            except Exception:
                pass
        return {"shutdown": True}

    def queue(self, user: str) -> Dict:
        self.require_admin(user)
        if self.scheduler is not None:
            return {pool: [job_to_json(self.store, j,
                                       include_instances=False)
                           for j in jobs[:200]]
                    for pool, jobs in self.scheduler.pending_queues.items()}
        if self.read_view is not None:
            # follower approximation of the ranked queue: the true DRU
            # order is leader state, so serve the pending set in
            # (priority, submit-time) order from the live mirror —
            # honestly stale, labeled by the replication headers the
            # follower read path attaches (docs/DEPLOY.md)
            out: Dict[str, List] = {}
            for job in self.store.pending_jobs():
                out.setdefault(job.pool, []).append(job)
            return {pool: [job_to_json(self.store, j,
                                       include_instances=False)
                           for j in sorted(
                               jobs, key=lambda j: (-j.priority,
                                                    j.submit_time_ms))[:200]]
                    for pool, jobs in out.items()}
        raise ApiError(503, "no scheduler attached")

    def running(self) -> List[Dict]:
        return [instance_to_json(inst)
                for _job, inst in self.store.running_instances()]

    def usage(self, params: Dict, auth_user: str = "") -> Dict:
        """GET /usage?user=&pool=&group_breakdown= (reference:
        rest/api.clj:2855-2968 UsageResponse + get-user-usage): running
        usage totals per pool, optionally broken down by job group
        (``grouped`` entries carry the group's uuid/name/running_jobs;
        ``ungrouped`` the rest).  Without ``user``, returns the
        cluster-wide per-user breakdown ``{"users": {user: usage}}``
        (admin-only here); ``pool`` restricts either form to one pool."""
        user = first(params.get("user"))
        pool_filter = first(params.get("pool")) or None  # "" = unfiltered
        if user is None:
            # admin check FIRST: no store scans for unauthorized callers
            self.require_admin(
                auth_user, "the all-users usage report is admin-only")
        # ONE usage scan per pool, shared by every user in the response
        # (the all-users form would otherwise rescan per user x pool)
        pool_usages = {p.name: self.store.user_usage(p.name)
                       for p in self.store.pools()
                       if pool_filter is None or p.name == pool_filter}
        breakdown = first(params.get("group_breakdown"), "false") == "true"
        if user is None:
            users: set = set()
            for usages in pool_usages.values():
                users.update(usages)
            running_by_user: Optional[Dict[str, List[Job]]] = None
            if breakdown:
                # ONE running-jobs scan bucketed by user (not one per user)
                running_by_user = {}
                for j in self.store.jobs_where(
                        lambda j: j.state is JobState.RUNNING
                        and (pool_filter is None or j.pool == pool_filter)):
                    running_by_user.setdefault(j.user, []).append(j)
            return {"users": {
                u: self._user_usage(
                    u, pool_filter, params, pool_usages,
                    running=(running_by_user.get(u, [])
                             if running_by_user is not None else None))
                for u in sorted(users)}}
        return self._user_usage(user, pool_filter, params, pool_usages)

    def _user_usage(self, user: str, pool_filter: Optional[str],
                    params: Dict, pool_usages: Dict[str, Dict],
                    running: Optional[List[Job]] = None) -> Dict:
        breakdown = first(params.get("group_breakdown"), "false") == "true"
        out: Dict[str, Any] = {
            "total_usage": {"cpus": 0.0, "mem": 0.0, "gpus": 0.0,
                            "jobs": 0}, "pools": {}}
        for pool_name, usages in pool_usages.items():
            usage = usages.get(user)
            if not usage:
                continue
            out["pools"][pool_name] = {
                "cpus": usage["cpus"], "mem": usage["mem"],
                "gpus": usage["gpus"], "jobs": int(usage["count"])}
            out["total_usage"]["cpus"] += usage["cpus"]
            out["total_usage"]["mem"] += usage["mem"]
            out["total_usage"]["gpus"] += usage["gpus"]
            out["total_usage"]["jobs"] += int(usage["count"])
        if breakdown:
            if running is None:
                running = self.store.jobs_where(
                    lambda j: j.user == user
                    and j.state is JobState.RUNNING
                    and (pool_filter is None or j.pool == pool_filter))

            def usage_of(jobs: List[Job]) -> Dict:
                return {"cpus": sum(j.resources.cpus for j in jobs),
                        "mem": sum(j.resources.mem for j in jobs),
                        "gpus": sum(j.resources.gpus for j in jobs),
                        "jobs": len(jobs)}

            by_group: Dict[Optional[str], List[Job]] = {}
            for j in running:
                by_group.setdefault(j.group, []).append(j)
            grouped = []
            for guuid, jobs in sorted(by_group.items(),
                                      key=lambda kv: kv[0] or ""):
                if guuid is None:
                    continue
                group = self.store.group(guuid)
                grouped.append({
                    "group": {"uuid": guuid,
                              "name": group.name if group else "",
                              "running_jobs": [j.uuid for j in jobs]},
                    "usage": usage_of(jobs)})
            loose = by_group.get(None, [])
            out["grouped"] = grouped
            out["ungrouped"] = {"running_jobs": [j.uuid for j in loose],
                                "usage": usage_of(loose)}
        return out

    def share_get(self, params: Dict) -> Dict:
        user = first(params.get("user"))
        if user is None:
            raise ApiError(400, "user parameter required")
        pools = [p.name for p in self.store.pools()] or ["default"]
        return {pool: _finite(self.store.get_share(user, pool))
                for pool in pools}

    def share_set(self, body: Dict, user: str) -> Dict:
        self.require_admin(user)
        target = body.get("user")
        if not target:
            raise ApiError(400, "user required")
        for pool, resources in body.get("pools", {}).items():
            self.store.set_share(target, pool, resources,
                                 reason=body.get("reason", ""))
        return {"user": target}

    def share_delete(self, params: Dict, user: str) -> Dict:
        self.require_admin(user)
        target = first(params.get("user"))
        for pool in [p.name for p in self.store.pools()] or ["default"]:
            self.store.retract_share(target, pool)
        return {"user": target}

    def quota_get(self, params: Dict) -> Dict:
        user = first(params.get("user"))
        if user is None:
            raise ApiError(400, "user parameter required")
        pools = [p.name for p in self.store.pools()] or ["default"]
        return {pool: _finite(self.store.get_quota(user, pool))
                for pool in pools}

    def quota_set(self, body: Dict, user: str) -> Dict:
        self.require_admin(user)
        target = body.get("user")
        if not target:
            raise ApiError(400, "user required")
        for pool, resources in body.get("pools", {}).items():
            resources = dict(resources)
            count = resources.pop("count", float("inf"))
            self.store.set_quota(target, pool, resources, count=count,
                                 reason=body.get("reason", ""))
        return {"user": target}

    def quota_delete(self, params: Dict, user: str) -> Dict:
        self.require_admin(user)
        target = first(params.get("user"))
        for pool in [p.name for p in self.store.pools()] or ["default"]:
            self.store.retract_quota(target, pool)
        return {"user": target}

    def pools(self) -> List[Dict]:
        return [{"name": p.name, "purpose": p.purpose, "state": p.state,
                 "dru-mode": p.dru_mode.value,
                 "scheduler": p.scheduler.value}
                for p in self.store.pools()]

    def unscheduled(self, params: Dict) -> List[Dict]:
        """GET /unscheduled_jobs?job=...&partial= (reference:
        UnscheduledJobParams rest/api.clj:3112-3117: ``partial`` allows a
        mix of valid and unknown uuids to return the valid subset)."""
        uuids = params.get("job", [])
        partial = first(params.get("partial"), "false") == "true"
        out = []
        for uuid in uuids:
            job = self.store.job(uuid)
            if job is None:
                if partial:
                    continue
                raise ApiError(404, f"no such job {uuid}")
            out.append({"uuid": uuid,
                        "reasons": job_reasons(self.store, job,
                                               scheduler=self.scheduler,
                                               queue_limits=self.queue_limits),
                        # decision HISTORY next to the live reasons: the
                        # newest audit events (utils/audit.py) — "what
                        # has the scheduler done with this job so far",
                        # not just "what blocks it right now"
                        "history": self.store.audit.timeline(uuid)[-20:]})
        if not out and uuids and partial:
            raise ApiError(404, "none of the requested jobs exist")
        return out

    def failure_reasons(self) -> List[Dict]:
        return [{"code": r.code, "name": r.name, "mea_culpa": r.mea_culpa,
                 "failure_limit": r.failure_limit}
                for r in Reasons.all()]

    def stats_instances(self, params: Dict, user: str) -> Dict:
        """GET /stats/instances?status=&start=&end=&name= — histogram
        statistics (percentiles + totals of run-time/cpu/mem-seconds)
        overall, by reason, by user-and-reason, plus per-user leaders,
        for instances started inside the window (reference:
        rest/api.clj:3185-3232 task-stats-handler + task_stats.clj).

        Without parameters, serves the legacy quick aggregate (instance
        counts by status and by reason) — a cook_tpu extension kept for
        dashboards; any parameter engages full reference validation."""
        if not params:
            from ..state.partition import substores
            by_status: Dict[str, int] = {}
            by_reason: Dict[str, int] = {}
            # one partition's lock at a time, never nested (the
            # store[pN] sibling rule, utils/locks.py)
            for shard in substores(self.store):
                with shard._lock:
                    for inst in shard._instances.values():
                        by_status[inst.status.value] = \
                            by_status.get(inst.status.value, 0) + 1
                        if inst.reason_code is not None:
                            name = Reasons.by_code(inst.reason_code).name
                            by_reason[name] = by_reason.get(name, 0) + 1
            return {"by_status": by_status, "by_reason": by_reason}
        self.require_admin(user)
        try:
            v = task_stats.validate_params(params)
        except task_stats.StatsParamError as e:
            raise ApiError(400, str(e))
        return task_stats.get_stats(
            self.store, v["status"], v["start_ms"], v["end_ms"],
            v["name_fn"], now_ms=self.store.clock())

    def progress(self, task_id: str, body: Dict) -> Dict:
        ok = self.store.update_instance_progress(
            task_id, int(body.get("progress_percent", 0)),
            message=body.get("progress_message", ""),
            sequence=int(body.get("progress_sequence", 0)))
        if not ok:
            raise ApiError(404, f"no such instance {task_id} "
                                "(or stale sequence)")
        if self.scheduler is not None:
            # progress frames double as liveness (heartbeat.clj:100-123)
            self.scheduler.heartbeat(task_id)
        return {"task_id": task_id}

    def info(self) -> Dict:
        from .. import __version__
        out = {"version": __version__,
               "leader": self.scheduler is not None,
               "authentication-scheme": "open",
               "start-up-time": 0}
        rs = getattr(self, "repl_server", None)
        if rs is not None:
            # socket-replication leader: operators (and failover tests)
            # need to see when a standby's mirror is actually synced —
            # the no-loss guarantee only covers commits made after that
            out["replication"] = {"port": rs.port,
                                  "followers": rs.follower_count,
                                  "synced_followers":
                                      rs.synced_follower_count}
        return out

    def swagger_docs(self) -> Dict:
        """Machine-readable API description (reference: the swagger-docs
        endpoint compojure-api generates from the route table,
        rest/api.clj:3640).  OpenAPI-3 shape, hand-maintained from the
        same dispatch table do_* routes serve."""
        from .. import __version__
        paths: Dict[str, Dict] = {}
        # declared query parameters for the read endpoints whose contracts
        # carry validation (the reference's compojure-api schemas)
        query_params = {
            # status/start/end are required TOGETHER for the windowed
            # report; omitting all of them serves the legacy quick
            # aggregate, so none is individually required:true
            ("GET", "/stats/instances"): [
                ("status", False, "unknown|running|success|failed "
                                  "(required for the windowed report)"),
                ("start", False, "epoch-ms or ISO-8601 "
                                 "(required for the windowed report)"),
                ("end", False, "epoch-ms or ISO-8601, window <= 31 days "
                               "(required for the windowed report)"),
                ("name", False, "job-name filter, * wildcard")],
            ("GET", "/list"): [
                ("user", True, ""), ("state", False, ""),
                ("start-ms", False, ""), ("end-ms", False, ""),
                ("limit", False, ""), ("name", False, "* wildcard"),
                ("pool", False, "")],
            ("GET", "/usage"): [
                ("user", False, "omit for the all-users report (admin)"),
                ("pool", False, ""),
                ("group_breakdown", False, "true|false")],
            ("GET", "/jobs"): [
                ("uuid", False, "repeatable; omit to query by user/state"),
                ("user", False, "with state: the listing form"),
                ("state", False, "waiting|running|completed (+-joined)"),
                ("partial", False, "true returns the found subset")],
            ("GET", "/unscheduled_jobs"): [
                ("job", True, "repeatable"),
                ("partial", False, "true returns the found subset")],
            ("GET", "/debug/cycles"): [
                ("limit", False, "newest-last record count, default 50")],
            ("GET", "/debug/trace"): [
                ("trace_id", False,
                 "trace_id of a span or CycleRecord; the response is "
                 "Chrome trace-event JSON (chrome://tracing, "
                 "ui.perfetto.dev)"),
                ("job", False,
                 "job uuid: stitch the job's audit track in; alone "
                 "(no trace_id) the export is the per-job stitched "
                 "view — launching cycle + submission request track")],
            ("GET", "/debug/requests"): [
                ("limit", False, "records per ring, default 50")],
        }
        for method, path, summary, leader_only in API_ROUTES:
            entry = paths.setdefault(path, {})
            op = {
                "summary": summary,
                "x-leader-only": leader_only,
                "responses": {"200": {"description": "success"}},
            }
            # declared path parameters, required by the OpenAPI spec for
            # every templated segment
            names = re.findall(r"{([^}]+)}", path)
            params = [{"name": n, "in": "path", "required": True,
                       "schema": {"type": "string"}} for n in names]
            for qname, required, desc in query_params.get((method, path),
                                                          []):
                q = {"name": qname, "in": "query", "required": required,
                     "schema": {"type": "string"}}
                if desc:
                    q["description"] = desc
                params.append(q)
            if params:
                op["parameters"] = params
            entry[method.lower()] = op
        return {
            "openapi": "3.0.0",
            "info": {"title": "cook_tpu scheduler API",
                     "version": __version__,
                     "description": "TPU-native fair-share batch scheduler "
                                    "(Cook-compatible REST surface)"},
            "paths": paths,
        }

    def swagger_ui(self) -> str:
        """Minimal self-contained HTML view of the API (no external
        assets; the image is zero-egress)."""
        rows = "".join(
            f"<tr><td><code>{m}</code></td><td><code>{p}</code></td>"
            f"<td>{s}</td><td>{'leader' if lo else ''}</td></tr>"
            for m, p, s, lo in API_ROUTES)
        return ("<!doctype html><html><head><title>cook_tpu API</title>"
                "<style>body{font-family:sans-serif;margin:2em}"
                "table{border-collapse:collapse}td,th{border:1px solid #ccc;"
                "padding:4px 8px;text-align:left}</style></head><body>"
                "<h1>cook_tpu scheduler API</h1>"
                "<p>Machine-readable spec at <a href='/swagger-docs'>"
                "/swagger-docs</a>.</p><table><tr><th>Method</th>"
                f"<th>Path</th><th>Summary</th><th></th></tr>{rows}"
                "</table></body></html>")

    def debug(self) -> Dict:
        from ..utils.flight import recorder
        from ..utils.tracing import tracer
        return {"healthy": True,
                "pools": [p.name for p in self.store.pools()],
                "clusters": (list(self.scheduler.clusters)
                             if self.scheduler else []),
                "recent-spans": tracer.recent(limit=50),
                "recent-cycles": recorder.recent(limit=10)}

    def debug_cycles(self, params: Dict) -> Dict:
        """GET /debug/cycles?limit= — the flight recorder's newest-last
        CycleRecords (docs/OBSERVABILITY.md documents every field).
        When sharded cycles are in the ring (ISSUE 19: records carry a
        ``shard`` id) the response adds the per-shard summary roll-up
        (cycle count + p50/p99 per shard) under ``by_shard``."""
        from ..utils.flight import recorder
        try:
            limit = int(params.get("limit", ["50"])[0])
        except ValueError:
            raise ApiError(400, "limit must be an integer")
        out: Dict = {"cycles": recorder.recent(limit=limit)}
        by_shard = recorder.summary().get("by_shard")
        if by_shard:
            out["by_shard"] = by_shard
        return out

    def debug_trace(self, params: Dict) -> Dict:
        """GET /debug/trace?trace_id=&job= — spans as Chrome trace-event
        JSON (load in chrome://tracing / ui.perfetto.dev).  CycleRecords
        carry their trace_id, so /debug/cycles -> /debug/trace is the
        slow-cycle drill-down.

        With ``job`` alone (no trace_id), the export is the STITCHED
        per-job view (docs/OBSERVABILITY.md "tracing one request"): the
        cycle that launched the job (resolved from the ``launched``
        audit event's recorded cycle trace) as the base flamegraph, the
        submission request's span tree (http.request -> journal append
        -> replication ack wait) as its own named track, and the job's
        audit timeline as an instant-event lane — one Perfetto timeline
        from client submit to launch RPC."""
        from ..utils.tracing import job_track_events, tracer, track_meta
        trace_id = params.get("trace_id", [None])[0]
        job = params.get("job", [None])[0]
        req_trace = cycle_trace = None
        timeline: List[Dict[str, Any]] = []
        if job:
            timeline = self.store.audit.timeline(job)
            jb = self.store.job(job)
            if jb is not None:
                req_trace = jb.trace_id
            for ev in timeline:
                data = ev.get("data") or {}
                if req_trace is None and ev["kind"] == "submitted":
                    req_trace = data.get("trace")
                if ev["kind"] == "launched" and data.get("cycle_trace"):
                    cycle_trace = data["cycle_trace"]
        if not trace_id:
            # job-only form: base the export on the launching cycle when
            # one is known, else on the request trace alone
            trace_id = cycle_trace or req_trace
            if not trace_id:
                if job:
                    raise ApiError(
                        404, f"no trace recorded for job {job}")
                raise ApiError(400, "trace_id or job query parameter "
                                    "is required")
        if self.fleet is not None:
            # fleet-wide stitch (sched/fleet.py): fan out to every
            # known member's span ring and export per-PROCESS tracks —
            # leader txn, partition fsync, agent exec, barrier release
            # on one timeline (docs/OBSERVABILITY.md "Debugging the
            # fleet")
            return self._debug_trace_fleet(trace_id, req_trace, job,
                                           timeline)
        trace = tracer.export_chrome_trace(trace_id)
        if not trace["traceEvents"] and not (job and timeline):
            raise ApiError(404, f"no spans recorded for trace {trace_id}")
        if job:
            # stitch the submission request's span tree as a named track
            # next to the cycle flamegraph (skipped when it IS the base)
            if req_trace and req_trace != trace_id:
                req_events = tracer.trace_events(req_trace, tid=3)
                if req_events:
                    trace["traceEvents"].append(
                        track_meta(f"request {job[:13]}", 3))
                    trace["traceEvents"].extend(req_events)
            # the job's audit events as a per-job instant-event track
            # (utils/audit.py; docs/OBSERVABILITY.md "debugging one
            # job"): decision history and flamegraph on one timeline
            trace["traceEvents"].extend(job_track_events(job, timeline))
        return trace

    def _debug_trace_fleet(self, trace_id: str,
                           req_trace: Optional[str], job: Optional[str],
                           timeline: List[Dict[str, Any]]) -> Dict:
        """The stitched form of /debug/trace: local ring + per-member
        fan-out, merged and deduped, exported with per-process tracks;
        a distinct submission-request trace merges onto the same member
        tracks (the spans carry which process recorded them).  Fan-out
        provenance lands in ``otherData.members`` so a partial stitch
        (unreachable member) is visible, not silent."""
        from ..utils.tracing import export_fleet_trace, job_track_events
        spans, provenance = self.fleet.collect_trace(trace_id)
        if req_trace and req_trace != trace_id:
            req_spans, req_prov = self.fleet.collect_trace(req_trace)
            seen = {(d.get("proc"), d.get("span_id")) for d in spans}
            spans += [d for d in req_spans
                      if (d.get("proc"), d.get("span_id")) not in seen]
            provenance += [{**p, "trace": req_trace} for p in req_prov]
        if not spans and not (job and timeline):
            raise ApiError(404, f"no spans recorded for trace {trace_id}")
        trace = export_fleet_trace(spans, trace_id, members=provenance)
        if job and timeline:
            # the audit lane keeps its classic pid-1 home; name the
            # process so the fleet view labels the timeline track
            trace["traceEvents"].append(
                {"name": "process_name", "ph": "M", "pid": 1, "tid": 0,
                 "args": {"name": f"job {job[:13]} timeline"}})
            trace["traceEvents"].extend(job_track_events(job, timeline))
        return trace

    def debug_trace_spans(self, params: Dict) -> Dict:
        """GET /debug/trace/spans?trace_id= — THIS process's raw span
        docs for one trace, straight off the bounded local ring
        (utils/tracing.py): the per-member stitch source the fleet
        trace collector merges and dedupes.  Served locally on every
        role — a follower or agent-side process answers for its own
        ring, it never redirects (the whole point is that each member
        holds spans nobody else has)."""
        from ..utils import tracing as _tracing
        trace_id = params.get("trace_id", [None])[0]
        if not trace_id:
            raise ApiError(400, "trace_id query parameter is required")
        return {"trace_id": trace_id,
                "proc": self.instance or _tracing.process_identity(),
                "spans": _tracing.tracer.traces(trace_id)}

    def _role(self) -> str:
        """This process's fleet role as surfaced on /debug/health and
        /debug/fleet: ``leader`` (scheduler attached), ``follower`` (a
        live read view or replication mirror), else ``standby``."""
        if self.scheduler is not None:
            return "leader"
        if self.read_view is not None or self.repl_follower is not None:
            return "follower"
        return "standby"

    def debug_fleet(self) -> Dict:
        """GET /debug/fleet — the federated fleet panel (`cs debug
        fleet` renders it): per-member health, staleness, SLO burn,
        saturation hot-spots, and last-scrape age off the FleetScraper,
        plus this process's LIVE saturation block (recomputed now, not
        the last sweep's).  Without a scraper attached (follower,
        api-only node, federation disabled) the local block still
        serves — a probe of any member always answers."""
        from ..sched.fleet import compute_saturation
        sat = compute_saturation(self.config, store=self.store,
                                 read_view=self.read_view,
                                 rate_limits=self.rate_limits)
        red = self.config.fleet.saturation_red_line
        local = {"instance": self.instance, "role": self._role(),
                 "saturation": sat,
                 "hot": sorted(r for r, v in sat.items() if v >= red)}
        if self.fleet is None:
            return {"enabled": False, "members": [], "local": local,
                    "saturation_red_line": red}
        self.fleet.maybe_scrape()
        doc = self.fleet.fleet_doc()
        doc["local"] = local
        return doc

    def debug_federation_summary(self) -> Dict:
        """GET /debug/federation/summary — what this cell contributes
        to a federation front door (federation/summary.py): the SAME
        bounded per-user table partitions exchange intra-cell
        (state/store.py user_summary: a few floats per distinct user,
        never job state), a freshness age, and a bounded host inventory
        for goodput-mode cross-cell placement scoring.  Cheap enough to
        poll every summary sweep."""
        store = self.store if self.store is not None else (
            self.read_view.store if self.read_view is not None else None)
        users = store.user_summary() if store is not None else {}
        hosts: List[Dict[str, Any]] = []
        if self.scheduler is not None:
            seen = set()
            pools = [p.name for p in (store.pools() if store else [])] \
                or ["default"]
            for cluster in self.scheduler.clusters.values():
                for pool in pools:
                    try:
                        offers = cluster.hosts(pool)
                    except Exception:
                        continue
                    for o in offers:
                        if o.hostname in seen:
                            continue
                        seen.add(o.hostname)
                        hosts.append({
                            "hostname": o.hostname,
                            "cpus": o.capacity.cpus,
                            "mem": o.capacity.mem,
                            "gpus": o.capacity.gpus,
                            "pool": o.pool,
                            "attributes": dict(o.attributes),
                            "gpu_model": o.gpu_model})
                        if len(hosts) >= 256:
                            break
                    if len(hosts) >= 256:
                        break
                if len(hosts) >= 256:
                    break
        return {"users": users, "age_s": 0.0, "hosts": hosts}

    def metrics_fleet(self) -> str:
        """GET /metrics/fleet — the merged fleet exposition: every
        member's /metrics re-labeled with {instance, role}
        (sched/fleet.py).  A pull nudges the self-gated scraper, so a
        fresh leader serves real data without waiting a monitor sweep;
        without a scraper the local exposition serves (the scrape
        target never 404s during failover)."""
        if self.fleet is None:
            return self.metrics()
        self.fleet.maybe_scrape()
        merged = self.fleet.merged_exposition()
        return merged if merged else self.metrics()

    def debug_requests(self, params: Dict) -> Dict:
        """GET /debug/requests?limit= — the serving plane's bounded
        request-capture rings (rest/instrument.py): newest recent
        requests, the slow ring with per-phase breakdowns, and rolling
        phase-share totals.  Params are redacted; join records to traces
        via ``trace_id`` and to user reports via ``request_id``."""
        try:
            limit = int(params.get("limit", ["50"])[0])
        except ValueError:
            raise ApiError(400, "limit must be an integer")
        return self.request_obs.snapshot(limit=limit)

    def debug_health(self) -> Dict:
        """GET /debug/health — the one-shot operator roll-up `cs debug
        health` renders: every "is this cell healthy" signal that
        otherwise takes five /debug/* fetches (docs/OBSERVABILITY.md)."""
        from ..utils.locks import monitor as lock_monitor
        from ..utils.metrics import registry
        from ..utils.retry import breakers

        def series(name: str) -> List[Dict[str, Any]]:
            return [{**labels, "value": value}
                    for labels, value in registry.series(name)]

        from ..sched.fleet import compute_saturation
        repl = self.debug_replication()
        saturation = compute_saturation(self.config, store=self.store,
                                        read_view=self.read_view,
                                        rate_limits=self.rate_limits)
        red_line = self.config.fleet.saturation_red_line
        health: Dict[str, Any] = {
            "healthy": True,
            "leader": self.scheduler is not None,
            # fleet role marker: a follower probed directly must SAY so
            # (and carry its read-view block below) instead of looking
            # like a healthy leader-shaped process
            "role": self._role(),
            # normalized 0-1 saturation signals (sched/fleet.py
            # formulas; docs/OBSERVABILITY.md) — the adaptive-admission
            # input contract, recomputed live for this probe
            "saturation": saturation,
            "saturation_red_line": red_line,
            "saturation_hot": sorted(r for r, v in saturation.items()
                                     if v >= red_line),
            "slo_burn_rates": series("cook_slo_burn_rate"),
            # overload ladder state (sched/admission.py): the adaptive
            # admission level, the brownout stage + recent flips on a
            # leader; followers report the journaled stage they act on
            "admission": self.admission_state(),
            "breakers": breakers.states(),
            "replication": {
                k: repl.get(k)
                for k in ("role", "epoch", "fenced", "synced_followers",
                          "follower_count", "min_acked", "journal_bytes",
                          "mirror", "serving", "group_commit",
                          "partitions", "summary_exchange")
                if repl.get(k) is not None},
            "pipeline_depth": next(
                (v for _lbl, v in registry.series("cook_pipeline_depth")),
                None),
            "resident_repacks": series("cook_resident_repack"),
            "audit": {k: v for k, v in self.store.audit.stats().items()
                      if k in ("jobs", "pending_durable",
                               "shed_advisory", "shed_count")},
            "http": self.request_obs.snapshot(limit=0)["totals"],
            # lock-order sanitizer (utils/locks.py, docs/ANALYSIS.md):
            # the observed acquisition-graph edge set + violation counts
            "locks": lock_monitor.snapshot(),
        }
        # static-vs-observed lock-coverage diff (docs/ANALYSIS.md): the
        # static edge set is computed ONCE per process off a background
        # thread (a ~1 s source scan must never stall a health probe);
        # until it lands, the block reports "computing".  unexercised =
        # statically possible orderings tier-1 never drove; observed-
        # only = a resolution gap in the static analysis (report it).
        lk = health["locks"]
        try:
            from ..analysis.summaries import (static_edge_error,
                                              static_edge_families)
            static = static_edge_families(wait=False)
            err = static_edge_error()
        except Exception:  # analysis package stripped from this deploy
            lk["static_edges"] = "unavailable"
        else:
            if static is not None:
                observed = set(lk.get("observed_edges", []))
                lk["static_edges"] = static
                lk["unexercised_edges"] = sorted(set(static) - observed)
                lk["observed_only_edges"] = sorted(observed - set(static))
            elif err is not None:
                lk["static_edges"] = f"failed: {err}"
            else:
                lk["static_edges"] = "computing"
        followers = repl.get("followers") or []
        if followers:
            health["replication"]["max_lag_bytes"] = max(
                int(f.get("lag_bytes", 0)) for f in followers)
        rv = self.read_view
        if rv is not None:
            # the read-view apply-loop block /debug/replication always
            # had but this roll-up omitted: a follower probed directly
            # looked healthier than it was — no staleness age, no
            # applied offset, no reads-served count
            health["read_view"] = {**rv.stats(),
                                   "reads_served": self.follower_reads}
        # persistence-integrity roll-up (full detail: /debug/storage) —
        # a poisoned journal or a corrupt mirror is NOT healthy even
        # while the process keeps serving its verified prefix
        storage = self.debug_storage()
        health["storage"] = {
            k: storage.get(k)
            for k in ("poisoned", "corruptions", "repairs",
                      "enospc_aborts", "mirror_corrupt")
            if storage.get(k) is not None}
        # burning past budget, a fenced store, or a potential-deadlock
        # lock graph is not healthy
        if any(s["value"] > 1.0 for s in health["slo_burn_rates"]) \
                or repl.get("fenced") \
                or health["locks"]["violations"] \
                or health["locks"]["blocking_events"]:
            health["healthy"] = False
        if storage.get("poisoned") or storage.get("mirror_corrupt"):
            health["healthy"] = False
        if rv is not None and saturation["follower_staleness"] >= 1.0:
            # a follower serving reads staler than the red line
            # (fleet.staleness_red_line_seconds) is NOT healthy — the
            # exact "looks healthier than it is" gap this block closes
            health["healthy"] = False
        return health

    def debug_storage(self) -> Dict:
        """GET /debug/storage — the persistence-integrity panel `cs
        debug storage` renders: per-partition scrub progress (last
        verified offset vs journal size), corruption/repair counters,
        checkpoint manifest status, ENOSPC aborts, boot hygiene, and —
        on a follower — the read view's poison state
        (docs/DEPLOY.md corrupted-journal runbook)."""
        from ..state.partition import substores
        shards: List[Dict[str, Any]] = []
        for shard in substores(self.store):
            try:
                shards.append(shard.storage_stats())
            except Exception as e:  # pragma: no cover — defensive
                shards.append({"error": str(e)})
        doc: Dict[str, Any] = {
            "shards": shards,
            "poisoned": any(s.get("journal_poisoned") for s in shards),
            "corruptions": sum(int(s.get("scrub_corruptions", 0) or 0)
                               for s in shards),
            "repairs": sum(int(s.get("scrub_repairs", 0) or 0)
                           for s in shards),
            "enospc_aborts": sum(int(s.get("enospc_aborts", 0) or 0)
                                 for s in shards),
            "hygiene_removed": sum(int(s.get("hygiene_removed", 0) or 0)
                                   for s in shards),
        }
        sc = getattr(self.config, "storage", None)
        if sc is not None:
            doc["scrub"] = {
                "enabled": bool(sc.scrub_enabled),
                "interval_seconds": sc.scrub_interval_seconds,
                "chunk_bytes": sc.scrub_chunk_bytes,
                "checkpoint_on_corruption":
                    bool(sc.checkpoint_on_corruption),
            }
        rv = self.read_view
        if rv is not None:
            st = rv.stats()
            doc["read_view"] = {
                k: st.get(k)
                for k in ("offset", "epoch", "jobs", "corrupt")
                if st.get(k) is not None}
            doc["mirror_corrupt"] = \
                getattr(rv, "corrupt", None) is not None
        return doc

    def debug_job_timeline(self, uuid: str) -> Dict:
        """GET /debug/job/<uuid>/timeline — the job's full decision
        audit trail (utils/audit.py): submit -> ranked -> skips/deferrals
        with reasons -> launch intent/ack -> instance transitions ->
        preemption (with the DRU delta) -> terminal, surviving leader
        failover via the journal-backed lane.  Answers live next to the
        history: a still-waiting job also gets the unscheduled
        explainer's current reasons and the user's fairness position."""
        job = self.store.job(uuid)
        timeline = self.store.audit.timeline(uuid)
        if job is None and not timeline:
            raise ApiError(404, f"no such job {uuid}")
        out: Dict[str, Any] = {"uuid": uuid, "timeline": timeline}
        if job is not None:
            out["state"] = job_state_string(self.store, job)
            out["user"] = job.user
            out["pool"] = job.pool
            dru = self.store.audit.user_dru(job.pool, job.user)
            if dru is not None:
                out["user_dru"] = dru
            if job.state is JobState.WAITING:
                out["reasons"] = job_reasons(
                    self.store, job, scheduler=self.scheduler,
                    queue_limits=self.queue_limits)
        return out

    def debug_optimizer(self) -> Dict:
        """GET /debug/optimizer — the goodput loop's decision panel
        (`cs debug optimizer` renders it; docs/GANG.md elasticity):
        cycle counts + last error, the last per-pool decisions (grow
        budget, shrink pressure, preemption budget, autoscale target,
        candidate scores), the legacy observational schedule, and the
        elastic resize plane's live state (pending grace shrinks,
        standing budgets, grow/shrink totals)."""
        sched = self.scheduler
        if sched is None:
            raise ApiError(503, "no scheduler attached (not the leader)")
        out: Dict[str, Any] = {
            "enabled": sched.config.optimizer is not None,
            "elastic": sched.elastic.debug(),
        }
        cyc = sched.optimizer_cycler
        if cyc is None:
            return out
        decisions = getattr(cyc.optimizer, "last_decisions", {})
        schedule = None
        if cyc.last_schedule is not None:
            # HostInfo keys are not JSON; render them
            schedule = {
                str(period): {
                    "suggested-matches": [
                        {"host": vars(hi), "jobs": list(uuids)}
                        for hi, uuids in step["suggested-matches"].items()]}
                for period, step in cyc.last_schedule.items()}
        out.update({
            "cycles": cyc.cycles,
            "interval_seconds": cyc.interval_seconds,
            "last_error": (repr(cyc.last_error)
                           if cyc.last_error is not None else None),
            "decisions": {p: d.to_dict() for p, d in decisions.items()},
            "last_schedule": schedule,
        })
        return out

    def debug_faults(self) -> Dict:
        """GET /debug/faults — degradation panel: armed fault points and
        their trigger counts, per-cluster circuit-breaker states, and open
        launch intents (docs/ROBUSTNESS.md).  Served locally on every
        node like the other debug surfaces."""
        from ..utils.faults import injector
        from ..utils.retry import breakers
        return {"fault_points": injector.active(),
                "seed": injector.seed,
                "breakers": breakers.states(),
                "launch_intents": self.store.launch_intents()}

    def debug_replication(self) -> Dict:
        """GET /debug/replication — the failover-protocol panel
        (docs/OBSERVABILITY.md): per-follower acked offsets and synced
        flags, min_acked, journal head and lag on the leader; the
        mirror's offset/synced state on a standby; plus every candidate
        position currently published into the election medium.  Served
        locally on every node (each node's view IS the datum)."""
        out: Dict[str, Any] = {"role": "none"}
        rs = self.repl_server
        if rs is not None:
            followers = rs.status()
            head = 0
            if getattr(rs, "directory", None):
                try:
                    import os as _os
                    head = _os.path.getsize(
                        _os.path.join(rs.directory, "journal.jsonl"))
                except OSError:
                    head = 0
            for f in followers:
                f["lag_bytes"] = max(0, head - int(f.get("acked", 0)))
            out.update(
                role="leader", epoch=getattr(rs, "epoch", None),
                fenced=bool(getattr(rs, "fenced", False)),
                port=rs.port, journal_bytes=head,
                min_acked=rs.min_acked(),
                follower_count=rs.follower_count,
                synced_followers=rs.synced_follower_count,
                followers=followers)
            gc = self.store.group_commit_stats() \
                if hasattr(self.store, "group_commit_stats") else None
            if gc is not None:
                # write-path admission batching: batches, demuxed
                # outcomes, and the largest batch amortized so far
                out["group_commit"] = gc
        rf = self.repl_follower
        if rf is not None:
            out["role"] = "standby"
            out["mirror"] = {"offset": rf.offset,
                             "connected": rf.connected}
        rv = self.read_view
        if rv is not None:
            # the SERVING role of this standby: local apply position vs
            # the mirrored head (staleness in bytes + age) and how many
            # GETs this node has answered from its live store
            out["serving"] = {**rv.stats(),
                              "reads_served": self.follower_reads}
        pstats = getattr(self.store, "partition_stats", None)
        if pstats is not None:
            # partitioned write plane (state/partition.py): one block
            # per partition — journal head, lease epoch, group-commit
            # stage, declared pool groups — plus the summary-exchange
            # state cross-partition invariants read through
            out["partitions"] = pstats()
            summaries = getattr(self.store, "summaries", None)
            if summaries is not None:
                out["summary_exchange"] = summaries.stats()
        for srv in getattr(self, "partition_repl_servers", None) or []:
            # per-partition replication topologies (each partition owns
            # its own server + synced-standby set)
            out.setdefault("partition_replication", []).append({
                "partition": f"p{srv.partition}"
                if getattr(srv, "partition", None) is not None else None,
                "port": srv.port,
                "synced_followers": srv.synced_follower_count,
                "min_acked": srv.min_acked(),
            })
        if self.repl_dir:
            from ..state.replication import candidate_position
            out["position"] = candidate_position(self.repl_dir)
        if self.elector is not None:
            try:
                out["candidates"] = self.elector.read_candidates()
            except Exception:
                out["candidates"] = {}
        return out

    def settings(self) -> Dict:
        from ..sched.rebalancer import effective_rebalancer_params
        cfg = self.config
        # resolved against the store's dynamic document so api-only nodes
        # (no scheduler attached) report the same truth they accept
        # updates against
        reb = effective_rebalancer_params(cfg, self.store)
        return {
            "rank-interval-seconds": cfg.rank_interval_seconds,
            "match-interval-seconds": cfg.match_interval_seconds,
            "max-over-quota-jobs": cfg.max_over_quota_jobs,
            "default-pool": cfg.default_pool,
            "rebalancer": {
                "enabled": reb.enabled,
                "safe-dru-threshold": reb.safe_dru_threshold,
                "min-dru-diff": reb.min_dru_diff,
                "max-preemption": reb.max_preemption,
                "interval-seconds": reb.interval_seconds,
            },
            # clients derive their submission expectations from this block
            # (reference: settings -> :task-constraints, read by the
            # integration tier's limit probes)
            "task-constraints": {
                "cpus": cfg.task_constraints.cpus,
                "memory-gb": cfg.task_constraints.memory_gb,
                "max-ports": cfg.task_constraints.max_ports,
                "retry-limit": cfg.task_constraints.retry_limit,
                "command-length-limit":
                    cfg.task_constraints.command_length_limit,
                "docker-parameters-allowed": (
                    cfg.task_constraints.docker_parameters_allowed
                    if cfg.task_constraints.docker_parameters_allowed
                    is not None
                    else sorted(DEFAULT_DOCKER_PARAMETERS_ALLOWED)),
            },
            "pools": {
                "default-containers": [
                    {"pool-regex": rx, "container": c}
                    for rx, c in cfg.default_containers],
                "default-envs": [{"pool-regex": rx, "env": e}
                                 for rx, e in cfg.default_envs],
                "valid-gpu-models": [{"pool-regex": rx, "valid-models": m}
                                     for rx, m in cfg.valid_gpu_models],
            },
            **self._k8s_settings(),
        }

    def _k8s_settings(self) -> Dict:
        """The kubernetes config block (reference: settings ->
        :kubernetes, read by the integration tier's disallowed-volume/
        var probes).  Config is the cross-node source of truth; any live
        backend's values are unioned in, so leaders and api-only
        followers serve one consistent settings document."""
        paths = set(self.config.kubernetes_disallowed_container_paths)
        names = set(self.config.kubernetes_disallowed_var_names)
        for cluster in (self.scheduler.clusters.values()
                        if self.scheduler else []):
            if hasattr(cluster, "disallowed_container_paths"):
                paths |= cluster.disallowed_container_paths
                names |= cluster.disallowed_var_names
        return {"kubernetes": {
            "disallowed-container-paths": sorted(paths),
            "disallowed-var-names": sorted(names)}}

    # wire-name -> (field, coercion): values are validated/coerced so a
    # mistyped document can never poison every later rebalance cycle
    _REBALANCER_PARAMS = {
        "enabled": ("enabled", bool),
        "safe-dru-threshold": ("safe_dru_threshold", float),
        "min-dru-diff": ("min_dru_diff", float),
        "max-preemption": ("max_preemption", int),
        "interval-seconds": ("interval_seconds", float),
    }

    def rebalancer_set(self, body: Dict, user: str) -> Dict:
        """POST /settings/rebalancer — durable no-restart parameter update
        (reference: the rebalancer's Datomic params, rebalancer.clj:535-557,
        re-read every cycle; interval changes take effect on the next
        tick)."""
        self.require_admin(user)
        unknown = set(body) - set(self._REBALANCER_PARAMS)
        if unknown:
            raise ApiError(400, f"unknown rebalancer params: {sorted(unknown)}")
        updates = {}
        for wire, value in body.items():
            field_name, coerce = self._REBALANCER_PARAMS[wire]
            try:
                if coerce is bool and not isinstance(value, bool):
                    raise ValueError("expected a boolean")
                if coerce is int and float(value) != int(value):
                    raise ValueError("expected an integer")
                updates[field_name] = coerce(value)
            except (TypeError, ValueError) as e:
                raise ApiError(400, f"bad value for {wire}: {e}")
        merged = self.store.update_dynamic_config("rebalancer", updates)
        return {"rebalancer": merged}

    # --------------------------------------------- dynamic compute clusters
    def compute_clusters(self) -> List[Dict]:
        if self.scheduler is None:
            raise ApiError(503, "no scheduler attached")
        return [{"name": c.name, "state": c.state,
                 "type": type(c).__name__}
                for c in self.scheduler.clusters.values()]

    def compute_cluster_update(self, name: str, body: Dict,
                               user: str) -> Dict:
        """Dynamic cluster CRUD (reference: compute_cluster.clj:450-594):
        CREATE a new backend from a factory spec, or drive the state
        machine running -> draining -> deleted.  Deletion is refused while
        the cluster still runs tasks (the reference's integration flow
        polls deleted until the drain empties the cluster,
        integration/tests/cook/test_dynamic_clusters.py)."""
        self.require_admin(user)
        if self.scheduler is None:
            raise ApiError(503, "no scheduler attached")
        cluster = self.scheduler.clusters.get(name)
        if cluster is None:
            factory = body.get("factory")
            if not factory:
                raise ApiError(404, f"no such cluster {name} "
                                    "(create needs a 'factory' spec)")
            # an HTTP body must not become a code-loading surface: only
            # factories the operator pre-declared (static cluster specs /
            # explicit allowlist, the reference's factory-fn templates)
            # may be instantiated dynamically
            allowed = getattr(self.config, "cluster_factory_allowlist",
                              None) or []
            if factory not in allowed:
                raise ApiError(
                    403, f"factory {factory!r} not in the configured "
                         "cluster_factory_allowlist")
            from ..daemon import build_clusters
            try:
                [fresh] = build_clusters(
                    [{"factory": factory,
                      "kwargs": dict(body.get("kwargs") or {},
                                     name=name)}], self.store,
                    config=self.config)
            except Exception as e:
                raise ApiError(422, f"cluster factory failed: {e}")
            self.scheduler.add_cluster(fresh)
            return {"name": name, "state": fresh.state, "created": True}
        new_state = body.get("state")
        legal = {"running": {"draining"}, "draining": {"running", "deleted"}}
        if new_state not in legal.get(cluster.state, set()):
            raise ApiError(422, f"illegal transition {cluster.state} "
                                f"-> {new_state}")
        if new_state == "deleted":
            # backend-agnostic liveness: the store is the source of truth
            # (a backend-specific probe would silently no-op for adapters
            # that don't expose one)
            live = sum(1 for _j, inst in self.store.running_instances()
                       if inst.compute_cluster == name)
            if live:
                raise ApiError(422, f"cluster {name} still runs "
                                    f"{live} tasks; drain first")
            gone = self.scheduler.clusters.pop(name)
            shutdown = getattr(gone, "shutdown", None)
            if shutdown:
                try:
                    shutdown()  # unhook watches/threads (daemon contract)
                except Exception:
                    pass
        else:
            cluster.state = new_state
        return {"name": name, "state": new_state}

    # -------------------------------------------------- incremental config
    def incremental_get(self) -> Dict:
        return self.incremental.all()

    def incremental_set(self, body: Dict, user: str) -> Dict:
        self.require_admin(user)
        try:
            self.incremental.set_many(body)  # all-or-nothing
        except (ValueError, KeyError, TypeError) as e:
            raise ApiError(400, f"bad incremental config: {e}")
        return self.incremental.all()

    def metrics(self) -> str:
        """Prometheus text exposition (reference: prometheus_metrics.clj +
        /metrics handler rest/api.clj:3981)."""
        from ..utils.metrics import registry
        repl_servers = [s for s in ([self.repl_server]
                                    + list(self.partition_repl_servers))
                        if s is not None and not getattr(s, "fenced",
                                                         False)]
        if repl_servers:
            # per-follower mirror lag, refreshed at scrape time (the
            # replication-health signal operators alert on:
            # docs/OBSERVABILITY.md cook_replication_lag_bytes).  The
            # follower label is a per-CONNECTION id, so stale series are
            # dropped first — reconnect churn must not accumulate frozen
            # dead-follower series forever.  On a partitioned leader
            # every partition's server exports its own partition-labeled
            # series (each partition is its own replication topology).
            registry.gauge_clear("cook_replication_lag_bytes")
            for rs in repl_servers:
                try:
                    import os as _os
                    head = _os.path.getsize(
                        _os.path.join(rs.directory, "journal.jsonl"))
                except OSError:
                    head = 0
                part = getattr(rs, "partition", None)
                for f in rs.status():
                    registry.gauge_set(
                        "cook_replication_lag_bytes",
                        max(0, head - int(f.get("acked", 0))),
                        labels={"follower": str(f.get("id")),
                                "synced":
                                    str(bool(f.get("synced"))).lower(),
                                **({"partition": f"p{part}"}
                                   if part is not None else {})})
        rv = self.read_view
        if rv is not None:
            # follower serving-plane staleness, refreshed at scrape time
            # like the leader's per-follower lag above
            registry.gauge_set("cook_follower_apply_lag_bytes",
                               float(rv.lag_bytes()))
            registry.gauge_set("cook_follower_staleness_seconds",
                               round(rv.age_ms() / 1000.0, 6))
        # saturation gauges refresh at scrape time on EVERY role: the
        # leader's monitor sweep also publishes them, but followers and
        # api-only nodes run no monitor — without this their federated
        # series would read a boot-time zero forever (sched/fleet.py)
        from ..sched.fleet import compute_saturation, publish_saturation
        publish_saturation(
            compute_saturation(self.config, store=self.store,
                               read_view=rv,
                               rate_limits=self.rate_limits),
            registry)
        lines = registry.expose()
        # always include live gauges derivable from state (per-shard
        # locks taken in turn, never nested — utils/locks.py)
        from ..state.partition import substores
        waiting = running = 0
        for shard in substores(self.store):
            with shard._lock:
                waiting += sum(1 for j in shard._jobs.values()
                               if j.state is JobState.WAITING
                               and j.committed)
                running += sum(1 for j in shard._jobs.values()
                               if j.state is JobState.RUNNING)
        lines += (f"\ncook_jobs_waiting {waiting}"
                  f"\ncook_jobs_running {running}\n")
        return lines


ALLOWED_LIST_STATES = frozenset(
    {"waiting", "running", "completed", "success", "failed"})


def parse_states(params: Dict) -> set:
    """State filter from query params. '+' is the documented separator, but
    standard URL decoding turns a literal '+' into a space, so accept
    space/comma too, and repeated state params."""
    states = set()
    for value in params.get("state", []):
        states.update(s for s in re.split(r"[+,\s]+", value) if s)
    if states and not states <= ALLOWED_LIST_STATES:
        raise ApiError(400, f"unsupported state in {sorted(states)}, must "
                            f"be one of: {sorted(ALLOWED_LIST_STATES)}")
    return states


def job_matches_states(store: Store, job: Job, states: set) -> bool:
    """'completed' means both success and failed (reference:
    rest/api.clj:1659-1668 normalize-list-states)."""
    if not states:
        return True
    if job.state.value in states:
        return True
    # resolving success/failed reads the job's instances — skip it unless
    # the filter can actually match a resolved state
    if job.state is not JobState.COMPLETED \
            or not states & {"success", "failed"}:
        return False
    return job_state_string(store, job) in states


def first(values, default=None):
    if not values:
        return default
    return values[0]


def _finite(d: Dict[str, float]) -> Dict[str, Any]:
    return {k: (v if v != float("inf") else None) for k, v in d.items()}


class _Handler(BaseHTTPRequestHandler):
    api: CookApi = None  # set by server factory
    protocol_version = "HTTP/1.1"
    # keep-alive is the serving plane's thread model: ThreadingHTTPServer
    # runs one thread per CONNECTION, so connection reuse (JobClient's
    # pooled http.client sockets) turns per-request thread churn into one
    # long-lived thread per client.  Nagle off: small JSON responses must
    # not wait out delayed-ACK interactions on localhost benches.
    disable_nagle_algorithm = True
    # an idle keep-alive connection releases its thread eventually
    # instead of holding it for the client process lifetime
    timeout = 120

    # ------------------------------------------------------------- plumbing
    def log_message(self, fmt, *args):  # pragma: no cover - silence
        pass

    def _authenticate(self) -> str:
        """Resolve (and in verified mode, check) the caller identity; runs
        for EVERY request before dispatch (reference: the auth middleware
        wraps the whole handler stack, components.clj:266-284)."""
        if self.api.auth_chain is not None:
            from .auth import AuthError
            try:
                # schemes may fill response headers (e.g. the GSSAPI
                # acceptor's mutual-auth token), sent with the 200
                self._auth_respond_headers = {}
                return self.api.auth_chain.authenticate(
                    self.headers, self._auth_respond_headers)
            except AuthError as e:
                headers = ({"WWW-Authenticate": e.challenge}
                           if e.challenge else None)
                raise ApiError(401, e.message, headers=headers)
        # open mode: identity from unverified Basic or the trusted header
        auth = self.headers.get("Authorization", "")
        user = self.headers.get("X-Cook-User", "")
        if auth.startswith("Basic "):
            try:
                user = base64.b64decode(auth[6:]).decode().partition(":")[0]
            except Exception:
                raise ApiError(401, "malformed basic auth")
        return user or "anonymous"

    def _user(self) -> str:
        return self.api.resolve_user(
            self._auth_user, self.headers.get("X-Cook-Impersonate"))

    def _body(self) -> Dict:
        length = int(self.headers.get("Content-Length", 0))
        if not length:
            return {}
        try:
            return json.loads(self.rfile.read(length))
        except json.JSONDecodeError:
            raise ApiError(400, "malformed JSON body")

    def _cors_headers(self) -> None:
        origin = self.headers.get("Origin")
        if origin and self.api.origin_allowed(origin):
            self.send_header("Access-Control-Allow-Origin", origin)
            self.send_header("Access-Control-Allow-Credentials", "true")
            self.send_header("Vary", "Origin")

    def _respond(self, status: int, payload,
                 extra_headers: Optional[Dict[str, str]] = None) -> None:
        # {"_raw"}/{"_html"} payloads are plain-text surfaces (/metrics,
        # /swagger-ui); everything else is the JSON plane
        html = isinstance(payload, dict) and "_html" in payload
        raw = isinstance(payload, dict) and "_raw" in payload
        if raw or html:
            data = payload.get("_raw", payload.get("_html")).encode()
            ctype = "text/html" if html else "text/plain"
        else:
            data = json.dumps(to_json(payload)).encode()
            ctype = "application/json"
        self._status = status
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        # gzip the observability surfaces (Prometheus scrapes, Perfetto
        # trace exports run to MBs) when the client opts in; tiny bodies
        # skip the compressor (the header bytes would outweigh the win)
        path = self.path.split("?", 1)[0]
        if len(data) > 512 \
                and (path in ("/metrics", "/metrics/fleet")
                     or path.startswith("/debug")) \
                and instrument.wants_gzip(
                    self.headers.get("Accept-Encoding")):
            data = instrument.gzip_body(data)
            self.send_header("Content-Encoding", "gzip")
            self.send_header("Vary", "Accept-Encoding")
        self.send_header("Content-Length", str(len(data)))
        # every response (success AND error) echoes the request id so a
        # user report joins to the slow-request ring and the trace
        rid = getattr(self, "_request_id", None)
        if rid:
            self.send_header("X-Cook-Request-Id", rid)
        for k, v in (extra_headers or {}).items():
            self.send_header(k, v)
        if not (raw or html):
            self._cors_headers()
        self.end_headers()
        self.wfile.write(data)
        self._bytes_out = len(data)

    # paths the front door NEVER rate-limits (ISSUE 17 / docs/DEPLOY.md
    # overload runbook): the observability and health surfaces must
    # survive the very incident that trips the limiter — an operator
    # locked out of /metrics and /debug/* mid-overload is flying blind
    @staticmethod
    def _admission_exempt(path: str) -> bool:
        # NOT /info: it has been IP-throttled since the limiter shipped
        # and is cheap to re-probe; the exemption exists for the surfaces
        # an operator needs DURING the stampede (/debug/health et al.)
        return (path in ("/metrics", "/metrics/fleet",
                         "/failure_reasons", "/settings")
                or path.startswith("/debug"))

    def _check_ip_limit(self) -> bool:
        """Admit or 429 this request per the client-IP bucket (covers
        every verb incl. OPTIONS — the reference's middleware wraps the
        whole handler).  try_spend is atomic: a full token per request,
        so the fractional refill trickle never admits a burst.
        Observability/health paths are exempt (_admission_exempt)."""
        limiter = self.api.ip_limiter
        if limiter is None:
            return True
        path = urllib.parse.urlparse(self.path).path
        if self._admission_exempt(path):
            return True
        ip = self.client_address[0]
        if limiter.try_spend(ip):
            return True
        from ..utils.metrics import registry
        registry.counter_inc("cook_admission_rejections", 1.0,
                             {"scope": "ip", "reason": "rate-limited"})
        # one token's worth of refill is when the next request can pass
        rate = limiter.tokens_per_minute * getattr(limiter, "refill_scale",
                                                   1.0)
        retry_s = max(1, int(60.0 / max(rate, 1e-9))
                      + int(min(limiter.time_until_out_of_debt_s(ip),
                                3600.0)))
        # minted lazily: verbs that gate on the IP bucket before _route
        # (OPTIONS) reject before the request id would normally be set
        rid = getattr(self, "_request_id", None) \
            or self.headers.get("X-Cook-Request-Id") \
            or uuidlib.uuid4().hex[:16]
        self._request_id = rid
        self._respond(429, {"error": "too many requests from this "
                                     "address",
                            "reason": "rate-limited",
                            "scope": "ip",
                            "request_id": rid},
                      extra_headers={"Retry-After": str(retry_s)})
        return False

    def _route(self, method: str) -> None:
        """Instrumented ingress (docs/OBSERVABILITY.md serving plane):
        every request gets an id (client's X-Cook-Request-Id or minted
        here), and — unless the operator disabled the http observe knob —
        an ``http.request`` root span under any client-sent traceparent,
        RED metrics on the templated endpoint, and a capture-ring record
        carrying the per-phase breakdown the span tree accumulated
        (journal append, replication ack wait, ...).

        Spans record under this node's fleet identity (CookApi.instance)
        for the request's duration: an in-process multi-server topology
        (tests, the simulator) shares one span ring, and the per-process
        tracks of the stitched fleet export are grouped by which MEMBER
        served the request, not which OS process ran it."""
        with tracing.scoped_identity(getattr(self.api, "instance", None)):
            self._route_identified(method)

    def _route_identified(self, method: str) -> None:
        parsed = urllib.parse.urlparse(self.path)
        self._request_id = (self.headers.get("X-Cook-Request-Id")
                            or uuidlib.uuid4().hex[:16])
        self._status = 500
        self._bytes_out = 0
        # per-request response headers the dispatch layer fills (the
        # serving-plane contract: X-Cook-Replication-Offset/-Age-Ms on
        # follower-served reads, X-Cook-Commit-Offset on leader writes)
        self._resp_headers: Dict[str, str] = {}
        # keep-alive connections reuse this handler instance: a stale
        # identity from the previous request must not be attributed to
        # one that fails authentication
        self._auth_user = ""
        obs = self.api.request_obs
        if not (obs.enabled and tracing.tracer.enabled):
            self._handle(method, parsed)
            return
        endpoint = instrument.endpoint_template(method, parsed.path)
        remote = tracing.parse_traceparent(self.headers.get("traceparent"))
        try:
            bytes_in = int(self.headers.get("Content-Length", 0) or 0)
        except ValueError:
            # a garbage Content-Length must not kill the connection
            # before _handle can answer it with a proper error
            bytes_in = 0
        obs.begin()
        t0 = time.perf_counter()
        trace_id = None
        phases: Dict[str, float] = {}
        try:
            with tracing.collect_phases() as phases, \
                    tracing.span("http.request", remote_parent=remote,
                                 endpoint=endpoint, method=method,
                                 request_id=self._request_id) as sp:
                trace_id = getattr(sp, "trace_id", None)
                self._handle(method, parsed)
                sp.set_tag("status", self._status)
                user = str(getattr(self, "_auth_user", "") or "")
                if user:
                    sp.set_tag("user", user)
        finally:
            obs.end(
                method=method, endpoint=endpoint, status=self._status,
                duration_s=time.perf_counter() - t0, phases=phases,
                params=(urllib.parse.parse_qs(parsed.query)
                        if parsed.query else {}),
                request_id=self._request_id, trace_id=trace_id,
                user=str(getattr(self, "_auth_user", "") or ""),
                bytes_in=bytes_in, bytes_out=self._bytes_out,
                objective_s=self.api.config.slo
                .endpoint_latency_objective_s)

    def _drained_bucket_reject(self) -> bool:
        """Ingress fast path for the stampede case (DAGOR: reject at
        the cheapest possible layer): a user whose submission bucket is
        fully drained cannot admit ANY batch — every batch needs at
        least one token — so answer the 429 before the body is parsed.
        A stampeding client then costs the server one header parse and
        a raw body drain, not a JSON decode + validation pass; the
        saved CPU is exactly the goodput retained under overload
        (bench.py ``overload`` leg).  Behavior-equivalent to the
        ``_admit_submission`` bucket check, just earlier and cheaper:
        a non-empty bucket falls through to the full front door."""
        rl = self.api.rate_limits.job_submission
        if not getattr(rl, "enforce", False):
            return False
        user = str(self._auth_user or "")
        if rl.get_token_count(user) > 0:
            return False
        from ..utils.metrics import registry
        registry.counter_inc("cook_admission_rejections", 1.0,
                             {"scope": "user", "reason": "rate-limited"})
        try:
            leftover = int(self.headers.get("Content-Length", 0) or 0)
        except ValueError:
            leftover = 0
        if leftover:
            self.rfile.read(leftover)  # keep the keep-alive conn sound
        retry = max(1, min(int(rl.retry_after_s(user, 1)) + 1, 3600))
        self._respond(429, {"error": "job submission rate limit "
                                     "exceeded",
                            "reason": "rate-limited", "scope": "user",
                            "request_id": self._request_id},
                      extra_headers={"Retry-After": str(retry)})
        return True

    def _handle(self, method: str, parsed) -> None:
        try:
            if not self._check_ip_limit():
                return
            self._auth_user = self._authenticate()
            if method == "POST" and parsed.path == "/jobs" \
                    and self._drained_bucket_reject():
                return
            params = urllib.parse.parse_qs(parsed.query)
            payload = self._dispatch(method, parsed.path, params)
            if method in ("POST", "PUT", "DELETE") \
                    and self.api.read_view is None:
                # leader/standalone write: return the commit position
                # ("<epoch>:<offset>", offset-space-qualified) so the
                # client can demand read-your-writes from followers
                if self.api.store.commit_offset():
                    self._resp_headers.setdefault(
                        "X-Cook-Commit-Offset",
                        self.api.store.commit_token())
            self._respond(200, payload,
                          extra_headers={
                              **self._resp_headers,
                              **(getattr(self, "_auth_respond_headers",
                                         None) or {})})
        except _Redirect as r:
            # 307 preserves the method+body, as the reference's
            # leader-redirect does. Drain any unread body first: leaving it
            # on the socket corrupts the next keep-alive request.
            leftover = int(self.headers.get("Content-Length", 0))
            if leftover:
                self.rfile.read(leftover)
            self._status = 307
            self.send_response(307)
            self.send_header("Location", r.location)
            self.send_header("X-Cook-Request-Id", self._request_id)
            self.send_header("Content-Length", "0")
            self.end_headers()
        except ApiError as e:
            # the request id rides the error BODY too: a pasted error
            # report alone is joinable to /debug/requests and the trace
            self._respond(e.status,
                          {"error": e.message,
                           "request_id": self._request_id, **e.extra},
                          extra_headers={
                              **getattr(self, "_resp_headers", {}),
                              **(e.headers or {})})
        except ReplicationIndeterminate as e:
            # write paths that don't build their own ambiguous-outcome
            # body (kill/retry/status — all idempotent): the transaction
            # is applied locally but unconfirmed on the mirror
            self._respond(504, {"error": str(e), "indeterminate": True,
                                "request_id": self._request_id})
        except StorageFullError as e:
            # ENOSPC clean abort (state/store.py): the journal excised
            # the torn append, in-memory state matches disk, nothing was
            # committed.  Escalation happens HERE rather than inside the
            # store because force_shed_writes journals its stage flip —
            # doing that under the store lock on a full disk would
            # recurse into the same failing append.
            try:
                ctrl = self.api.admission_controller()
                if ctrl is not None:
                    ctrl.force_shed_writes("storage:enospc")
            except Exception:
                pass
            self._respond(503, {"error": str(e), "storage_full": True,
                                "request_id": self._request_id},
                          extra_headers={"Retry-After": "30"})
        except Exception as e:  # pragma: no cover
            self._respond(500, {"error": f"internal error: {e}",
                                "request_id": self._request_id})

    # ------------------------------------------------------------- dispatch
    _LOCAL_PATHS = {"/info", "/debug", "/debug/cycles", "/debug/trace",
                    "/debug/trace/spans", "/debug/fleet",
                    "/debug/federation/summary",
                    "/debug/faults", "/debug/replication",
                    "/debug/requests", "/debug/health", "/debug/storage",
                    "/metrics",
                    "/metrics/fleet",
                    "/failure_reasons", "/settings", "/swagger-docs",
                    "/swagger-ui"}

    #: GET paths a replication standby with a live read view serves
    #: LOCALLY (bounded staleness, labeled by the replication headers)
    #: instead of 307-redirecting — ROADMAP item 1's read fleet
    _FOLLOWER_READ_PATHS = {
        "/jobs", "/rawscheduler", "/group", "/list", "/running",
        "/usage", "/share", "/quota", "/pools", "/queue",
        "/unscheduled_jobs", "/stats/instances"}

    @classmethod
    def _follower_readable(cls, path: str, parts: List[str]) -> bool:
        if path in cls._FOLLOWER_READ_PATHS:
            return True
        if len(parts) == 2 and parts[0] in ("jobs", "instances"):
            return True
        return (len(parts) == 4 and parts[0] == "debug"
                and parts[1] == "job" and parts[3] == "timeline")

    @staticmethod
    def _parse_min_offset(token: str):
        """An X-Cook-Min-Offset token: ``<epoch>:<offset>`` (the epoch
        qualifies the journal offset SPACE) or bare ``<offset>``.
        Returns (epoch or None, offset); raises 400 on garbage."""
        try:
            if ":" in token:
                ep, _, off = token.partition(":")
                return int(ep), int(off)
            return None, int(token)
        except ValueError:
            raise ApiError(400, "malformed X-Cook-Min-Offset")

    def _redirect(self, base: str, path: str) -> None:
        """Raise the 307 to ``base``, preserving this request's query."""
        query = urllib.parse.urlparse(self.path).query
        raise _Redirect(base + path + ("?" + query if query else ""))

    def _serve_from_follower(self, target: str, path: str) -> None:
        """Admit this GET to the local read view: honor the client's
        read-your-writes token (wait briefly, else redirect to the
        leader) and attach the staleness contract headers."""
        api = self.api
        rv = api.read_view
        # brownout stage >= 2 (sched/admission.py, journaled by the
        # leader and replicated into this mirror): the min-offset wait
        # gate RELAXES — reads stop queueing behind replication under
        # overload and serve bounded-stale instead.  The staleness
        # contract stays honest: the real age rides the response
        # headers, an unsatisfiable token still redirects (read-your-
        # writes is never faked), and the degrade is visible via
        # X-Cook-Brownout.
        brownout = api.brownout_stage() >= 2
        wait_s = api.config.serving.min_offset_wait_seconds
        if brownout:
            wait_s *= api.config.admission.relaxed_offset_wait_factor
        want = self.headers.get("X-Cook-Min-Offset")
        if want is not None:
            # vector-aware gate (the partitioned plane's token form —
            # entries satisfied against the mirror of THEIR partition);
            # legacy single tokens go through the same method
            gate = getattr(rv, "wait_commit_token", None)
            try:
                if gate is not None:
                    ok = gate(want, wait_s)
                else:
                    ep, off = self._parse_min_offset(want)
                    ok = rv.wait_token(ep, off, wait_s)
            except ValueError:
                raise ApiError(400, "malformed X-Cook-Min-Offset")
            if not ok:
                # still behind the client's own write (or mirroring an
                # EARLIER leadership's / a SIBLING partition's offset
                # space): the leader is the only node that can
                # guarantee read-your-writes
                self._redirect(target, path)
        api.follower_reads += 1
        from ..utils.metrics import registry
        registry.counter_inc("cook_follower_reads")
        if brownout:
            self._resp_headers["X-Cook-Brownout"] = "stale-reads"
        self._resp_headers["X-Cook-Replication-Offset"] = str(rv.offset)
        self._resp_headers["X-Cook-Replication-Age-Ms"] = \
            str(round(rv.age_ms(), 1))

    def _dispatch(self, method: str, path: str, params: Dict):
        api = self.api
        parts = [p for p in path.split("/") if p]
        if path not in self._LOCAL_PATHS:
            target = api.leader_redirect_target()
            if target is not None:
                if method == "GET" and api.read_view is not None \
                        and self._follower_readable(path, parts):
                    # serve from the live mirror instead of redirecting
                    # (may itself redirect when a read-your-writes token
                    # cannot be satisfied in time)
                    self._serve_from_follower(target, path)
                else:
                    self._redirect(target, path)
            elif method == "GET" \
                    and self.headers.get("X-Cook-Min-Offset") \
                    and api.fence_guard is not None and api.fence_guard():
                # a DEPOSED leader cannot honor a read-your-writes token:
                # the successor holds commits beyond this journal's fence
                # epoch, so offsets here no longer bound staleness.
                # Plain reads stay served (honest best-effort, clients
                # re-resolve the leader); token-bearing reads refuse.
                successor = api.elector.leader_url() if api.elector \
                    else None
                if successor and successor != api.node_url:
                    self._redirect(successor, path)
                raise ApiError(
                    503, "this leader has been superseded (stale "
                         "election epoch); its offsets cannot satisfy "
                         "read-your-writes — retry against the new "
                         "leader", headers={"Retry-After": "1"})
            if method in ("POST", "PUT", "DELETE") \
                    and api.fence_guard is not None and api.fence_guard():
                # deposed replication leader: a successor minted a higher
                # election epoch.  Journal fencing already rejects the
                # next append, but accepting the request at all risks a
                # split-brain write observed by clients — flip the write
                # path immediately (redirect when the successor is
                # already published, 503 otherwise).
                successor = api.elector.leader_url() if api.elector \
                    else None
                if successor and successor != api.node_url:
                    self._redirect(successor, path)
                raise ApiError(
                    503, "this leader has been superseded (stale "
                         "election epoch); retry against the new leader",
                    headers={"Retry-After": "1"})
        if method == "GET":
            if path == "/jobs" or path == "/rawscheduler":
                return api.get_jobs(params)
            if len(parts) == 2 and parts[0] == "jobs":
                return api.get_jobs({"uuid": [parts[1]]})[0]
            if len(parts) == 2 and parts[0] == "instances":
                inst = api.store.instance(parts[1])
                if inst is None:
                    raise ApiError(404, f"no such instance {parts[1]}")
                return instance_to_json(inst)
            if path == "/queue":
                return api.queue(self._user())
            if path == "/group":
                return api.group_get(params)
            if path == "/list":
                return api.list_jobs(params)
            if path == "/running":
                return api.running()
            if path == "/usage":
                return api.usage(params, self._user())
            if path == "/share":
                return api.share_get(params)
            if path == "/quota":
                return api.quota_get(params)
            if path == "/pools":
                return api.pools()
            if path == "/unscheduled_jobs":
                return api.unscheduled(params)
            if path == "/failure_reasons":
                return api.failure_reasons()
            if path == "/stats/instances":
                return api.stats_instances(params, self._user())
            if path == "/settings":
                return api.settings()
            if path == "/info":
                return api.info()
            if path == "/debug":
                return api.debug()
            if path == "/debug/cycles":
                return api.debug_cycles(params)
            if path == "/debug/trace":
                return api.debug_trace(params)
            if path == "/debug/faults":
                return api.debug_faults()
            if path == "/debug/replication":
                return api.debug_replication()
            if path == "/debug/requests":
                return api.debug_requests(params)
            if path == "/debug/health":
                return api.debug_health()
            if path == "/debug/storage":
                return api.debug_storage()
            if path == "/debug/optimizer":
                return api.debug_optimizer()
            if path == "/debug/trace/spans":
                return api.debug_trace_spans(params)
            if path == "/debug/fleet":
                return api.debug_fleet()
            if path == "/debug/federation/summary":
                return api.debug_federation_summary()
            if len(parts) == 4 and parts[0] == "debug" \
                    and parts[1] == "job" and parts[3] == "timeline":
                return api.debug_job_timeline(parts[2])
            if path == "/swagger-docs":
                return api.swagger_docs()
            if path == "/swagger-ui":
                return {"_html": api.swagger_ui()}
            if path == "/metrics":
                return {"_raw": api.metrics()}
            if path == "/metrics/fleet":
                return {"_raw": api.metrics_fleet()}
            if path == "/compute-clusters":
                return api.compute_clusters()
            if path == "/incremental-config":
                return api.incremental_get()
        elif method == "POST":
            if len(parts) == 2 and parts[0] == "compute-clusters":
                return api.compute_cluster_update(parts[1], self._body(),
                                                  self._user())
            if path == "/incremental-config":
                return api.incremental_set(self._body(), self._user())
            if path == "/jobs" or path == "/rawscheduler":
                return api.submit_jobs(self._body(), self._user())
            if path == "/retry":
                return api.retry(self._body(), self._user())
            if path == "/share":
                return api.share_set(self._body(), self._user())
            if path == "/quota":
                return api.quota_set(self._body(), self._user())
            if path == "/settings/rebalancer":
                return api.rebalancer_set(self._body(), self._user())
            if len(parts) == 2 and parts[0] == "progress":
                return api.progress(parts[1], self._body())
            if path == "/shutdown-leader":
                return api.shutdown_leader(self._user())
        elif method == "PUT":
            if path == "/retry":
                return api.retry(self._body(), self._user(),
                                 deprecated=False)
        elif method == "DELETE":
            if path == "/jobs" or path == "/rawscheduler":
                return api.kill_jobs(params, self._user())
            if path == "/instances":
                return api.kill_instances(params, self._user())
            if path == "/group":
                return api.group_kill(params, self._user())
            if path == "/share":
                return api.share_delete(params, self._user())
            if path == "/quota":
                return api.quota_delete(params, self._user())
        raise ApiError(404, f"no such endpoint {method} {path}")

    def do_OPTIONS(self):
        """CORS preflight (reference: cors.clj preflight handling): 200 with
        allow headers for an allowed origin, 403 otherwise."""
        if not self._check_ip_limit():
            return
        origin = self.headers.get("Origin", "")
        if not self.api.origin_allowed(origin):
            self._respond(403, {"error": f"Origin {origin} not allowed"})
            return
        self.send_response(200)
        self.send_header("Access-Control-Allow-Origin", origin)
        self.send_header("Access-Control-Allow-Credentials", "true")
        self.send_header("Access-Control-Allow-Methods",
                         "GET, POST, PUT, DELETE, OPTIONS")
        self.send_header(
            "Access-Control-Allow-Headers",
            self.headers.get("Access-Control-Request-Headers", "*"))
        self.send_header("Access-Control-Max-Age", "86400")
        self.send_header("Content-Length", "0")
        self.end_headers()

    def do_GET(self):
        self._route("GET")

    def do_POST(self):
        self._route("POST")

    def do_DELETE(self):
        self._route("DELETE")

    def do_PUT(self):
        self._route("PUT")


class _CookHTTPServer(ThreadingHTTPServer):
    # a deep accept backlog: reader fleets open their keep-alive
    # connections in a burst at client start; the default backlog (5)
    # made that burst retry its SYNs — part of the 4->8 reader QPS
    # regression in the r8 rest_plane baseline
    request_queue_size = 128
    daemon_threads = True

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        # live client sockets, so kill() can sever established
        # keep-alive connections the way a process death would —
        # shutdown() alone only stops the LISTENER, leaving pooled
        # connections served by their handler threads indefinitely
        self._live: set = set()
        self._live_mu = threading.Lock()

    def process_request(self, request, client_address):
        with self._live_mu:
            self._live.add(request)
        super().process_request(request, client_address)

    def shutdown_request(self, request):
        with self._live_mu:
            self._live.discard(request)
        super().shutdown_request(request)

    def close_all_connections(self) -> None:
        with self._live_mu:
            live = list(self._live)
            self._live.clear()
        for sock in live:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass


class ApiServer:
    """Threaded HTTP server wrapper."""

    def __init__(self, api: CookApi, host: str = "127.0.0.1", port: int = 0):
        # _Handler._respond serves the {"_raw"}/{"_html"} text surfaces
        # (/metrics, /swagger-ui) itself — no wrapper needed
        handler = type("BoundHandler", (_Handler,), {"api": api})
        self.server = _CookHTTPServer((host, port), handler)
        self.host, self.port = self.server.server_address
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self.server.serve_forever,
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self.server.shutdown()
        self.server.server_close()
        if self._thread:
            self._thread.join(timeout=5)

    def kill(self) -> None:
        """Hard-stop: close the listener AND sever every established
        client connection, like a process death would.  The graceful
        stop() leaves keep-alive connections draining — correct for
        shutdown, wrong for an outage drill (sim/federation.py's
        full-cell kill needs remote sockets to actually die)."""
        self.server.shutdown()
        self.server.server_close()
        self.server.close_all_connections()
        if self._thread:
            self._thread.join(timeout=5)

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"
