"""Pluggable request authentication schemes.

The reference composes SPNEGO/Kerberos, HTTP basic, and open (trusted
header) authentication in its middleware stack (reference:
rest/spnego.clj, rest/basic_auth.clj, composable is-authorized-fn
rest/authorization.clj, wired at components.clj:266-284). This module is
that seam: an ordered chain of Authenticators; the first one that resolves
an identity wins, and configuring a chain makes authentication mandatory.

:class:`GssapiAuthenticator` fills the SPNEGO slot with real GSSAPI
accept-context validation (needs the gssapi package + a keytab at
runtime); :class:`HmacTokenAuthenticator` is the KDC-free alternative —
self-contained signed tickets (user, expiry, HMAC) presented as
``Authorization: Bearer`` or ``Negotiate``, the moral shape of a kerberos
service ticket: issued out of band, verified statelessly, time-bounded.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import time
from typing import Callable, Dict, Optional, Union


class AuthError(Exception):
    """Malformed or rejected credentials (maps to HTTP 401)."""

    def __init__(self, message: str, challenge: Optional[str] = None):
        super().__init__(message)
        self.message = message
        self.challenge = challenge


class Authenticator:
    """One authentication scheme. Returns the identity, or None when the
    request carries no credentials for this scheme (the chain moves on);
    raises AuthError when credentials are present but invalid.

    ``respond``, when provided, is a dict the scheme may fill with
    response headers to send on success (e.g. the GSSAPI acceptor's
    mutual-authentication token in ``WWW-Authenticate``)."""

    challenge: Optional[str] = None

    def authenticate(self, headers,
                     respond: Optional[Dict[str, str]] = None
                     ) -> Optional[str]:  # pragma: no cover
        raise NotImplementedError


class HeaderTrustAuthenticator(Authenticator):
    """Open mode: trust a proxy-provided identity header (the reference's
    one-user-per-request open auth)."""

    def __init__(self, header: str = "X-Cook-User"):
        self.header = header

    def authenticate(self, headers, respond=None) -> Optional[str]:
        return headers.get(self.header) or None


class BasicAuthenticator(Authenticator):
    """HTTP basic with a user->password table or a check callable."""

    challenge = 'Basic realm="cook"'

    def __init__(self, users: Union[Dict[str, str],
                                    Callable[[str, str], bool]]):
        if callable(users):
            self._check = users
        else:
            # constant-time compare: don't leak password prefixes via timing
            self._check = lambda u, p: hmac.compare_digest(
                users.get(u, ""), p)

    def authenticate(self, headers, respond=None) -> Optional[str]:
        auth = headers.get("Authorization", "")
        if not auth.startswith("Basic "):
            return None
        try:
            user, _, password = \
                base64.b64decode(auth[6:]).decode().partition(":")
        except Exception:
            raise AuthError("malformed basic auth", self.challenge)
        if not user or not self._check(user, password):
            raise AuthError("bad credentials", self.challenge)
        return user


class HmacTokenAuthenticator(Authenticator):
    """Signed ticket auth: ``base64(user:expiry_epoch_s:hexmac)``.

    mint() issues tickets (the KDC stand-in); authenticate() verifies them
    statelessly. Accepted under ``Authorization: Bearer <t>`` or
    ``Negotiate <t>`` (the header SPNEGO uses)."""

    challenge = "Negotiate"

    def __init__(self, secret: Union[str, bytes],
                 default_ttl_s: float = 8 * 3600.0):
        self.secret = secret.encode() if isinstance(secret, str) else secret
        self.default_ttl_s = default_ttl_s

    def _mac(self, user: str, expiry_s: int) -> str:
        msg = f"{user}:{expiry_s}".encode()
        return hmac.new(self.secret, msg, hashlib.sha256).hexdigest()

    def mint(self, user: str, ttl_s: Optional[float] = None) -> str:
        expiry = int(time.time() + (ttl_s if ttl_s is not None
                                    else self.default_ttl_s))
        raw = f"{user}:{expiry}:{self._mac(user, expiry)}"
        return base64.b64encode(raw.encode()).decode()

    def authenticate(self, headers, respond=None) -> Optional[str]:
        auth = headers.get("Authorization", "")
        scheme, _, token = auth.partition(" ")
        if scheme not in ("Bearer", "Negotiate") or not token:
            return None
        try:
            user, expiry_str, mac = \
                base64.b64decode(token).decode().rsplit(":", 2)
            expiry = int(expiry_str)
        except Exception:
            raise AuthError("malformed token", self.challenge)
        if not hmac.compare_digest(mac, self._mac(user, expiry)):
            raise AuthError("bad token signature", self.challenge)
        if time.time() > expiry:
            raise AuthError("token expired", self.challenge)
        return user


class AuthChain:
    """Ordered schemes; first resolved identity wins. A configured chain
    makes authentication mandatory (no anonymous fallthrough)."""

    def __init__(self, authenticators):
        self.authenticators = list(authenticators)

    def authenticate(self, headers,
                     respond: Optional[Dict[str, str]] = None) -> str:
        for a in self.authenticators:
            user = a.authenticate(headers, respond)
            if user:
                return user
        challenges = [a.challenge for a in self.authenticators if a.challenge]
        raise AuthError("authentication required",
                        challenges[0] if challenges else None)


class GssapiAuthenticator(Authenticator):
    """Real SPNEGO/Kerberos validation through GSSAPI (reference:
    rest/spnego.clj gss-context-from-token / authorization-fn).

    Accepts ``Authorization: Negotiate <base64 token>``, runs the token
    through the server's accept security context, and maps the initiator
    principal (``user@REALM``) to its bare user name — exactly the
    reference's ``principal->username``.  Needs the ``gssapi`` package and
    a keytab/KDC at runtime; construction takes the module as a dependency
    (injectable for tests, resolved from the environment by default) so
    the seam is exercised even where no KDC exists.
    """

    challenge = "Negotiate"

    def __init__(self, service: str = "HTTP", gssapi_module=None):
        if gssapi_module is None:
            try:
                import gssapi as gssapi_module  # type: ignore
            except ImportError as e:
                raise RuntimeError(
                    "GssapiAuthenticator needs the 'gssapi' package (and a "
                    "keytab); use HmacTokenAuthenticator where no KDC "
                    "exists") from e
        self.gssapi = gssapi_module
        self.service = service
        # acceptor credentials once, at construction: a missing/unreadable
        # keytab fails the daemon at boot (fail-fast), not per request,
        # and the hot auth path skips the per-request keytab resolution
        self._creds = None
        if service:
            # constrain acceptance to the configured service principal
            # (HTTP/<host>), matching the reference's keytab identity
            spn = self.gssapi.Name(
                service, name_type=self.gssapi.NameType.hostbased_service)
            self._creds = self.gssapi.Credentials(name=spn, usage="accept")

    def authenticate(self, headers, respond=None) -> Optional[str]:
        auth = headers.get("Authorization", "")
        scheme, _, token_b64 = auth.partition(" ")
        if scheme != "Negotiate" or not token_b64:
            return None
        try:
            token = base64.b64decode(token_b64)
        except Exception:
            raise AuthError("malformed negotiate token", self.challenge)
        # GSS-API initial context tokens are ASN.1 framed ([APPLICATION 0],
        # first byte 0x60).  Anything else under the Negotiate header is not
        # ours — pass it through so an HmacTokenAuthenticator later in the
        # chain (the KDC-free stand-in on the same header) can handle it,
        # while real-but-forged GSS tokens still fail fast below.
        if not token or token[0] != 0x60:
            return None
        try:
            ctx = self.gssapi.SecurityContext(creds=self._creds,
                                              usage="accept")
            out_token = ctx.step(token)
            principal = str(ctx.initiator_name)
        except Exception as e:  # gssapi raises its own hierarchy
            # GSS status strings can reveal principal/keytab/clock-skew
            # detail: log them, return a generic 401 to the caller
            import logging
            logging.getLogger(__name__).info(
                "GSSAPI rejected a negotiate token: %s", e)
            raise AuthError("GSSAPI rejected token", self.challenge)
        if not ctx.complete:
            # multi-round-trip negotiation is not supported over this
            # stateless seam (the reference also completes in one step
            # for standard krb5 service tickets)
            raise AuthError("GSSAPI negotiation incomplete", self.challenge)
        if out_token and respond is not None:
            # the acceptor's final token: clients requiring MUTUAL
            # authentication verify the server with it
            respond["WWW-Authenticate"] = \
                "Negotiate " + base64.b64encode(out_token).decode()
        return principal.partition("@")[0] or None
