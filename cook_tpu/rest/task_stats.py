"""Instance statistics for ``GET /stats/instances``.

Re-implements the reference's task-stats subsystem (reference:
scheduler/src/cook/task_stats.clj:22-122 and the endpoint validation in
rest/api.clj:3185-3232): tasks whose instance started inside a required
[start, end) window and carry a required status are aggregated into

  overall              count + {cpu,mem,run-time}-seconds histograms
  by-reason            the same, grouped by failure-reason name
  by-user-and-reason   the same, grouped by user then reason
  leaders              top-10 users by total cpu-seconds / mem-seconds

Histograms use the reference's Nearest Rank percentile method at
50/75/95/99/100 plus the group total.  Aggregation is vectorized with
numpy: one pass builds parallel value arrays, then group-bys are argsort
partitions rather than per-task dict updates.

Endpoint validation mirrors rest/api.clj:3194-3221: status must be one of
unknown/running/success/failed, the name filter admits only
``[A-Za-z0-9.-_*]`` (``*`` is a wildcard), end must be after start, and
the window may not exceed 31 days.  Times parse as epoch milliseconds or
ISO-8601 (util/parse-time accepts both).
"""

from __future__ import annotations

import datetime as _dt
import fnmatch
import re
from typing import Callable, Dict, List, Optional

import numpy as np

from ..state.schema import InstanceStatus, Reasons

ALLOWED_STATUSES = ("unknown", "running", "success", "failed")
MAX_WINDOW_DAYS = 31
_PERCENTILES = (50, 75, 95, 99, 100)
_NAME_FILTER_RE = re.compile(r"^[A-Za-z0-9.\-_*]*$")


class StatsParamError(ValueError):
    """Raised for a malformed parameter; the REST layer maps it to 400."""


def parse_time_ms(value: str, param: str) -> int:
    """Epoch milliseconds or ISO-8601 (reference: util/parse-time)."""
    value = (value or "").strip()
    if re.fullmatch(r"\d{12,}", value):
        return int(value)
    try:
        dt = _dt.datetime.fromisoformat(value.replace("Z", "+00:00"))
    except ValueError:
        raise StatsParamError(f"unsupported {param} time {value!r}, must be "
                              "epoch milliseconds or ISO-8601")
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=_dt.timezone.utc)
    return int(dt.timestamp() * 1000)


def validate_params(params: Dict) -> Dict:
    """Validates raw query params into {status, start_ms, end_ms, name_fn}.

    Mirrors the malformed? checks of rest/api.clj:3194-3221; raises
    StatsParamError with a reference-shaped message on the first failure.
    """
    def first(key: str) -> Optional[str]:
        v = params.get(key)
        return v[0] if isinstance(v, list) else v

    status = first("status")
    if status not in ALLOWED_STATUSES:
        raise StatsParamError(
            f"unsupported status {status}, must be one of: "
            + ", ".join(ALLOWED_STATUSES))
    name = first("name")
    if name is not None and not _NAME_FILTER_RE.fullmatch(name):
        raise StatsParamError(
            f"unsupported name filter {name}, can only contain alphanumeric "
            "characters, '.', '-', '_', and '*' as a wildcard")
    start_raw, end_raw = first("start"), first("end")
    if not start_raw or not end_raw:
        raise StatsParamError("start and end parameters are required")
    start_ms = parse_time_ms(start_raw, "start")
    end_ms = parse_time_ms(end_raw, "end")
    if end_ms <= start_ms:
        raise StatsParamError("end time must be after start time")
    if end_ms - start_ms > MAX_WINDOW_DAYS * 86_400_000:
        raise StatsParamError(
            "time interval must be less than or equal to 31 days")
    name_fn: Optional[Callable[[str], bool]] = None
    if name is not None:
        pattern = name
        name_fn = lambda n: fnmatch.fnmatchcase(n or "", pattern)  # noqa: E731
    return {"status": status, "start_ms": start_ms, "end_ms": end_ms,
            "name_fn": name_fn}


def _histogram(values: np.ndarray) -> Dict:
    """Nearest-Rank percentiles + total (task_stats.clj:59-91)."""
    order = np.sort(values)
    n = len(order)
    ranks = [min(n - 1, max(0, int(np.ceil(p / 100.0 * n)) - 1))
             for p in _PERCENTILES]
    return {"percentiles": {p: float(order[r])
                            for p, r in zip(_PERCENTILES, ranks)},
            "total": float(values.sum())}


def _group_stats(cpu_s: np.ndarray, mem_s: np.ndarray,
                 run_s: np.ndarray) -> Dict:
    if len(run_s) == 0:
        return {}
    return {"count": int(len(run_s)),
            "cpu-seconds": _histogram(cpu_s),
            "mem-seconds": _histogram(mem_s),
            "run-time-seconds": _histogram(run_s)}


def _stats_by(keys: List[str], cpu_s, mem_s, run_s) -> Dict[str, Dict]:
    out: Dict[str, Dict] = {}
    arr = np.asarray(keys, dtype=object)
    for k in sorted(set(keys)):
        sel = arr == k
        out[k] = _group_stats(cpu_s[sel], mem_s[sel], run_s[sel])
    return out


def get_stats(store, status: str, start_ms: int, end_ms: int,
              name_fn: Optional[Callable[[str], bool]],
              now_ms: int) -> Dict:
    """The TaskStatsResponse body (task_stats.clj:94-122)."""
    from ..state.partition import substores
    want = InstanceStatus(status)
    users: List[str] = []
    reasons: List[str] = []
    cpu, mem, run = [], [], []
    matched = []
    # per-shard locks in turn, never nested (utils/locks.py sibling rule)
    for shard in substores(store):
        with shard._lock:
            matched.extend(
                inst for inst in shard._instances.values()
                if inst.status is want and inst.start_time_ms
                and start_ms <= inst.start_time_ms < end_ms)
    # one batched read, one clone per JOB (not per attempt) — per-call
    # store.job() would re-lock and re-clone for every instance
    uuids = list({inst.job_uuid for inst in matched})
    jobs = {u: j for u, j in zip(uuids, store.jobs_bulk(uuids))
            if j is not None}
    for inst in matched:
        st = inst.start_time_ms
        job = jobs.get(inst.job_uuid)
        if job is None:
            continue
        if name_fn is not None and not name_fn(job.name):
            continue
        run_s = max(0, (inst.end_time_ms or now_ms) - st) / 1000.0
        users.append(job.user)
        reasons.append("" if inst.reason_code is None
                       else Reasons.by_code(inst.reason_code).name)
        run.append(run_s)
        cpu.append(run_s * job.resources.cpus)
        mem.append(run_s * job.resources.mem)
    cpu_a, mem_a, run_a = (np.asarray(cpu), np.asarray(mem),
                           np.asarray(run))
    user_a = np.asarray(users, dtype=object)
    by_user_and_reason: Dict[str, Dict] = {}
    leaders_cpu: Dict[str, float] = {}
    leaders_mem: Dict[str, float] = {}
    for u in sorted(set(users)):
        sel = user_a == u
        by_user_and_reason[u] = _stats_by(
            [r for r, s in zip(reasons, sel) if s],
            cpu_a[sel], mem_a[sel], run_a[sel])
        leaders_cpu[u] = float(cpu_a[sel].sum())
        leaders_mem[u] = float(mem_a[sel].sum())

    def top10(totals: Dict[str, float]) -> Dict[str, float]:
        return dict(sorted(totals.items(), key=lambda kv: -kv[1])[:10])

    return {"overall": _group_stats(cpu_a, mem_a, run_a),
            "by-reason": _stats_by(reasons, cpu_a, mem_a, run_a),
            "by-user-and-reason": by_user_and_reason,
            "leaders": {"cpu-seconds": top10(leaders_cpu),
                        "mem-seconds": top10(leaders_mem)}}
