"""Serving-plane request observability (docs/OBSERVABILITY.md).

ROADMAP item 1 turns the single-leader REST plane into a read fleet with
write admission batching — this module is the measurement prerequisite:
before that path can be optimized it must decompose.  Three concerns,
Borg/Dapper style (Verma et al., EuroSys '15; Sigelman et al., 2010):

1. **Endpoint templating.**  Metric labels must be path TEMPLATES
   (``/jobs/{uuid}``), never raw uuids — the label space is the route
   table plus one ``{unmatched}`` bucket for 404 garbage, so per-endpoint
   series stay bounded no matter what clients throw at the socket.  The
   utils/metrics.py cardinality guard backstops the template resolver.

2. **RED metrics.**  Per-endpoint request counts (by method and status
   code), duration histograms, an in-flight gauge, and the request-size /
   phase decomposition (journal append, fsync, replication ack wait) the
   tracing layer's per-request phase collector feeds — "why was this POST
   slow" is answerable from /metrics before anyone opens a trace.

3. **Slow-request capture.**  A bounded ring of recent requests plus a
   ring of requests over the slow threshold, each record carrying the
   request id, trace id, redacted query params, and the per-phase
   breakdown — served at ``GET /debug/requests`` with no external
   collector (zero-egress friendly).

The module-level :data:`request_log` singleton mirrors the repo's other
observability planes (``utils.flight.recorder``, ``utils.tracing.tracer``).
"""

from __future__ import annotations

import gzip as _gzip
import threading
import time as _time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from ..utils.metrics import registry

# endpoint label value for paths matching no registered route: the 404
# surface must be one bounded series, not one per probe/typo'd path
UNMATCHED = "{unmatched}"

# request-size histogram bounds (bytes): submissions range from one tiny
# job to multi-thousand-job batches
REQUEST_SIZE_BUCKETS = (256.0, 1024.0, 4096.0, 16384.0, 65536.0,
                        262144.0, 1048576.0, 4194304.0)

# the span names the phase decomposition publishes (the tracing phase
# collector records EVERY span; exporting them all as label values would
# let any future span silently widen a metric family).  journal.fsync is
# the group-commit stage's batched force, attributed back into each
# waiting request via tracer.record_finished (state/store.py).
PHASE_SPANS = ("journal.append", "journal.fsync", "repl.ack_wait",
               "remote.launch")

# query params whose values never reach the capture ring verbatim
_REDACT_KEYS = frozenset({"token", "password", "authorization", "secret"})
_PARAM_VALUE_CAP = 64


def endpoint_template(method: str, path: str) -> str:
    """Resolve a raw (method, path) to its route-table template
    (``POST /jobs/<uuid>`` -> ``/jobs/{uuid}``); anything not in the
    table — unknown paths AND wrong-method probes against known paths —
    folds to :data:`UNMATCHED` so hostile traffic cannot mint metric
    series or skew a real endpoint's error counts."""
    static, templated = _route_tables()
    if (method, path) in static:
        return path
    parts = tuple(p for p in path.split("/") if p)
    for tmethod, tparts, template in templated:
        if tmethod != method or len(tparts) != len(parts):
            continue
        if all(t.startswith("{") or t == p
               for t, p in zip(tparts, parts)):
            return template
    return UNMATCHED


_ROUTE_CACHE: Optional[Tuple[frozenset, Tuple]] = None


def _route_tables() -> Tuple[frozenset, Tuple]:
    """(static (method, path) set, ((method, template parts, template),
    ...)) derived from the API route table; imported lazily (api.py
    imports this module)."""
    global _ROUTE_CACHE
    if _ROUTE_CACHE is None:
        from .api import API_ROUTES
        static = set()
        templated = []
        for method, path, _summary, _leader in API_ROUTES:
            if "{" in path:
                tparts = tuple(p for p in path.split("/") if p)
                if (method, tparts, path) not in templated:
                    templated.append((method, tparts, path))
            else:
                static.add((method, path))
        _ROUTE_CACHE = (frozenset(static), tuple(templated))
    return _ROUTE_CACHE


def redact_params(params: Dict[str, List[str]]) -> Dict[str, List[str]]:
    """Query params safe for the capture ring: secret-bearing keys are
    masked, values truncated (a 10k-uuid batch query must not bloat the
    ring)."""
    out: Dict[str, List[str]] = {}
    for key, values in params.items():
        if key.lower() in _REDACT_KEYS:
            out[key] = ["[redacted]"] * len(values)
        else:
            out[key] = [v if len(v) <= _PARAM_VALUE_CAP
                        else v[:_PARAM_VALUE_CAP] + "…"
                        for v in values[:8]]
            if len(values) > 8:
                out[key].append(f"…+{len(values) - 8} more")
    return out


def wants_gzip(accept_encoding: Optional[str]) -> bool:
    """True when the client's Accept-Encoding admits gzip (q=0 opt-outs
    honored)."""
    for token in (accept_encoding or "").lower().split(","):
        name, _, qs = token.strip().partition(";")
        if name in ("gzip", "*"):
            q = qs.strip()
            if q.startswith("q="):
                try:
                    return float(q[2:]) > 0.0
                except ValueError:
                    return False
            return True
    return False


def gzip_body(data: bytes) -> bytes:
    # mtime pinned so identical payloads compress identically (test
    # determinism; nothing reads the gzip timestamp)
    return _gzip.compress(data, compresslevel=5, mtime=0)


class RequestObserver:
    """RED metrics + bounded request-capture rings for the REST plane."""

    def __init__(self, recent: int = 256, slow: int = 64,
                 slow_ms: float = 500.0):
        self._lock = threading.Lock()
        self.enabled = True
        #: brownout stage >= 1 (sched/admission.py) turns the capture
        #: rings off — RED metrics and SLO windows keep flowing (the
        #: signal the incident is diagnosed with must survive the
        #: incident); only the per-request detail records shed
        self.capture = True
        self.slow_ms = float(slow_ms)
        self._recent: deque = deque(maxlen=recent)
        self._slow: deque = deque(maxlen=slow)
        self._inflight = 0
        # per-endpoint (count, over-objective) since the last monitor
        # sweep — the endpoint-latency SLO's burn-rate input
        self._slo_window: Dict[str, List[int]] = {}
        # rolling totals for the ack-wait share gauge (what fraction of
        # cumulative request wall time was replication ack wait)
        self._total_s = 0.0
        self._phase_totals: Dict[str, float] = {}
        # endpoint labels are templates (bounded by construction); the
        # registry cap is the backstop the acceptance criteria name
        for metric in ("cook_http_requests",
                       "cook_http_request_duration_seconds",
                       "cook_http_phase_seconds"):
            registry.set_label_cap(metric, "endpoint", 64, scope=())

    def configure(self, http_cfg) -> None:
        """Apply config.HttpConfig (CookApi construction / daemon boot)."""
        self.enabled = bool(http_cfg.observe)
        self.slow_ms = float(http_cfg.slow_request_ms)
        with self._lock:
            if self._recent.maxlen != int(http_cfg.request_log):
                self._recent = deque(self._recent,
                                     maxlen=int(http_cfg.request_log))
            if self._slow.maxlen != int(http_cfg.slow_log):
                self._slow = deque(self._slow,
                                   maxlen=int(http_cfg.slow_log))

    # -------------------------------------------------------------- lifecycle
    def begin(self) -> None:
        with self._lock:
            self._inflight += 1
            n = self._inflight
        registry.gauge_set("cook_http_inflight", float(n))

    def end(self, *, method: str, endpoint: str, status: int,
            duration_s: float, phases: Dict[str, float],
            params: Dict[str, List[str]], request_id: str,
            trace_id: Optional[str], user: str, bytes_in: int,
            bytes_out: int, objective_s: Optional[float] = None) -> None:
        labels = {"endpoint": endpoint, "method": method}
        registry.counter_inc("cook_http_requests", 1.0,
                             {**labels, "code": str(status)})
        registry.observe("cook_http_request_duration_seconds",
                         duration_s, labels)
        if bytes_in:
            registry.observe("cook_http_request_bytes", float(bytes_in),
                             {"endpoint": endpoint},
                             buckets=REQUEST_SIZE_BUCKETS)
        phases_ms = {}
        for name in PHASE_SPANS:
            dt = phases.get(name)
            if dt:
                phases_ms[name] = round(dt * 1000.0, 3)
                registry.observe("cook_http_phase_seconds", dt,
                                 {**labels, "phase": name})
        record = {
            "ts": None,  # stamped below under the lock (one time() call)
            "method": method, "endpoint": endpoint, "status": status,
            "duration_ms": round(duration_s * 1000.0, 3),
            "phases_ms": phases_ms,
            "request_id": request_id,
            "user": user,
            "bytes_in": bytes_in, "bytes_out": bytes_out,
            "params": redact_params(params),
        }
        if trace_id:
            record["trace_id"] = trace_id
        record["ts"] = round(_time.time(), 3)
        ack_share = None
        with self._lock:
            self._inflight -= 1
            n = self._inflight
            if self.capture:
                self._recent.append(record)
                if record["duration_ms"] >= self.slow_ms:
                    self._slow.append(record)
            win = self._slo_window.setdefault(endpoint, [0, 0])
            win[0] += 1
            if objective_s is not None and duration_s > objective_s:
                win[1] += 1
            self._total_s += duration_s
            for name, dt in phases.items():
                if name in PHASE_SPANS:
                    self._phase_totals[name] = \
                        self._phase_totals.get(name, 0.0) + dt
            if self._total_s > 0:
                ack_share = (self._phase_totals.get("repl.ack_wait", 0.0)
                             / self._total_s)
        registry.gauge_set("cook_http_inflight", float(n))
        if ack_share is not None:
            registry.gauge_set("cook_http_ack_wait_share",
                               round(ack_share, 6))

    # ---------------------------------------------------------------- queries
    def snapshot(self, limit: int = 50) -> Dict[str, Any]:
        """The GET /debug/requests payload: newest-last recent ring slice,
        the slow ring, and the rolling phase-share totals."""
        with self._lock:
            # limit<=0 = totals only ([-0:] would be the WHOLE ring)
            recent = list(self._recent)[-limit:] if limit > 0 else []
            slow = list(self._slow)[-limit:] if limit > 0 else []
            totals = {"requests_s": round(self._total_s, 6),
                      "phases_s": {k: round(v, 6) for k, v
                                   in self._phase_totals.items()},
                      "inflight": self._inflight}
        return {"slow_threshold_ms": self.slow_ms, "capture": self.capture,
                "recent": recent, "slow": slow, "totals": totals}

    def drain_slo_window(self) -> Dict[str, Tuple[int, int]]:
        """Per-endpoint (requests, over-objective) since the last drain —
        consumed by the monitor sweep's endpoint-latency SLO burn rate."""
        with self._lock:
            window, self._slo_window = self._slo_window, {}
        return {k: (v[0], v[1]) for k, v in window.items()}

    def reset(self) -> None:
        with self._lock:
            self._recent.clear()
            self._slow.clear()
            self._slo_window.clear()
            self._phase_totals.clear()
            self._total_s = 0.0
            self._inflight = 0


request_log = RequestObserver()
