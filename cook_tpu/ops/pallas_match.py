"""Pallas TPU kernel for the match-cycle preference build.

The auction matcher (ops/match.py, replacing the reference's Fenzo
``scheduleOnce`` hot loop, scheduler.clj:617-687) starts by scoring every
(job, host) pair — feasibility under the offered resources plus the
cpuMemBinPacker fitness (config.clj:108) — and keeping each job's top-K
hosts.  Done naively that materializes an f32[J, H] score matrix in HBM:
at the BASELINE.md scale (1M jobs x 50k offers) that is ~200 GB of HBM
traffic, far past a v5e chip's budget.

This kernel computes the scores *blockwise in VMEM* and carries a running
top-K per job tile across host tiles, so HBM traffic is O(J*R + H*R + J*K)
— the inputs and the result, never the J x H cross product.  The host axis
is the innermost grid dimension; VMEM scratch persists across the
sequential TPU grid, which is what makes the running top-K merge legal.

Resource comparisons are unrolled over the (tiny, static) resource axis so
every op in the kernel is a 2-D [TJ, TH] VPU op; the top-K merge is K
unrolled selection passes over the concatenated [TJ, K+TH] candidate
buffer (max + first-argmax-via-iota + mask), avoiding any sort/top_k
primitive inside the kernel.

On CPU (tests, fallback deployments) the kernel runs in interpret mode;
parity with the plain-XLA formulation in ops/match.py is bit-exact and
asserted in tests/test_pallas.py.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# pltpu is importable on CPU builds too (needed even for interpret-mode
# scratch shapes); if this import fails the pallas path is unusable and the
# caller should select a plain-XLA matcher backend instead.
from jax.experimental.pallas import tpu as pltpu

NEG_INF = float("-inf")
_BIG = 2**31 - 1  # python literal: module-level jnp consts would be captured


def _cdiv(a: int, b: int) -> int:
    return -(-a // b)


def _kernel(res_ref, cmask_ref, avail_t_ref, cap_t_ref,
            out_fit_ref, out_host_ref, run_fit, run_host, *, n_res: int,
            k: int, tile_h: int):
    """One (job-tile, host-tile) grid step: score the tile, merge top-K."""
    h = pl.program_id(1)
    tj = cmask_ref.shape[0]

    @pl.when(h == 0)
    def _init():
        run_fit[:] = jnp.full((tj, k), NEG_INF, dtype=jnp.float32)
        run_host[:] = jnp.zeros((tj, k), dtype=jnp.int32)

    # --- score this [TJ, TH] tile; unrolled over the static resource axis.
    # The mask travels through HBM as int8 (1 byte/element); upcast in VMEM
    # before comparing — Mosaic lacks vector i8 compares on this target.
    feas = cmask_ref[:].astype(jnp.int32) > 0
    for r in range(n_res):
        need_col = res_ref[:, r:r + 1]            # [TJ, 1]
        avail_row = avail_t_ref[r:r + 1, :]       # [1, TH]
        feas &= avail_row >= need_col
    # cpuMemBinPacker fitness on resources 0 (cpus) and 1 (mem)
    fit = jnp.zeros(feas.shape, dtype=jnp.float32)
    for r in (0, 1):
        cap_row = jnp.maximum(cap_t_ref[r:r + 1, :], 1e-9)
        used_row = cap_t_ref[r:r + 1, :] - avail_t_ref[r:r + 1, :]
        fit += (used_row + res_ref[:, r:r + 1]) / cap_row
    score = jnp.where(feas, fit * 0.5, NEG_INF)   # [TJ, TH]

    tile_iota = jax.lax.broadcasted_iota(jnp.int32, score.shape, 1)
    host_idx = tile_iota + h * tile_h

    # --- merge running top-K with this tile's scores.  Previous top-K
    # entries sit at positions < TH entries, and run_fit is sorted
    # descending, so "first position achieving the max" reproduces
    # lax.top_k's lowest-host-index tie-breaking exactly.
    combined = jnp.concatenate([run_fit[:], score], axis=1)       # [TJ, K+TH]
    combined_idx = jnp.concatenate([run_host[:], host_idx], axis=1)
    pos = jax.lax.broadcasted_iota(jnp.int32, combined.shape, 1)
    for kk in range(k):
        m = jnp.max(combined, axis=1, keepdims=True)              # [TJ, 1]
        first = jnp.min(jnp.where(combined == m, pos, _BIG), axis=1,
                        keepdims=True)
        sel = pos == first
        run_fit[:, kk:kk + 1] = m
        run_host[:, kk:kk + 1] = jnp.sum(
            jnp.where(sel, combined_idx, 0), axis=1, keepdims=True)
        combined = jnp.where(sel, NEG_INF, combined)

    @pl.when(h == pl.num_programs(1) - 1)
    def _emit():
        out_fit_ref[:] = run_fit[:]
        out_host_ref[:] = run_host[:]


@functools.partial(jax.jit, static_argnames=("k", "tile_j", "tile_h",
                                             "interpret"))
def _topk_prefs_padded(job_res, cmask_i8, avail_t, cap_t, *, k: int,
                       tile_j: int, tile_h: int, interpret: bool):
    jp, n_res = job_res.shape
    hp = avail_t.shape[1]
    grid = (jp // tile_j, hp // tile_h)
    kernel = functools.partial(_kernel, n_res=n_res, k=k, tile_h=tile_h)
    out_shape = (jax.ShapeDtypeStruct((jp, k), jnp.float32),
                 jax.ShapeDtypeStruct((jp, k), jnp.int32))
    mem = {"memory_space": pltpu.VMEM}
    scratch = [pltpu.VMEM((tile_j, k), jnp.float32),
               pltpu.VMEM((tile_j, k), jnp.int32)]
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((tile_j, n_res), lambda j, h: (j, 0), **mem),
            pl.BlockSpec((tile_j, tile_h), lambda j, h: (j, h), **mem),
            pl.BlockSpec((n_res, tile_h), lambda j, h: (0, h), **mem),
            pl.BlockSpec((n_res, tile_h), lambda j, h: (0, h), **mem),
        ],
        out_specs=(
            pl.BlockSpec((tile_j, k), lambda j, h: (j, 0), **mem),
            pl.BlockSpec((tile_j, k), lambda j, h: (j, 0), **mem),
        ),
        out_shape=out_shape,
        scratch_shapes=scratch,
        interpret=interpret,
    )(job_res, cmask_i8, avail_t, cap_t)


def topk_prefs(job_res: jax.Array, constraint_mask: jax.Array,
               valid: jax.Array, avail: jax.Array, capacity: jax.Array,
               k: int = 16, *, tile_j: int = 128, tile_h: int = 128,
               interpret: Optional[bool] = None
               ) -> Tuple[jax.Array, jax.Array]:
    """Blockwise top-K host preferences per job.

    Args mirror ops.match.MatchInputs: job_res f32[J, R], constraint_mask
    bool[J, H], valid bool[J], avail/capacity f32[H, R].  Returns
    (pref_fit f32[J, K], pref_host i32[J, K]) identical to
    ``lax.top_k(score, K)`` over the full score matrix, without ever
    materializing it.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    j, n_res = job_res.shape
    h = avail.shape[0]
    k = min(k, h)
    jp, hp = _cdiv(j, tile_j) * tile_j, _cdiv(h, tile_h) * tile_h

    # int8, not f32: the padded mask is the only J x H array this path
    # touches, keep it at 1 byte/element
    cmask = constraint_mask & valid[:, None]
    cmask_i8 = jnp.zeros((jp, hp), jnp.int8).at[:j, :h].set(
        cmask.astype(jnp.int8))
    job_res_p = jnp.zeros((jp, n_res), jnp.float32).at[:j].set(job_res)
    # padded hosts: avail = -1 so nothing fits them, capacity = 1
    avail_p = jnp.full((hp, n_res), -1.0, jnp.float32).at[:h].set(avail)
    cap_p = jnp.ones((hp, n_res), jnp.float32).at[:h].set(capacity)

    fit, host = _topk_prefs_padded(
        job_res_p, cmask_i8, avail_p.T, cap_p.T, k=k, tile_j=tile_j,
        tile_h=tile_h, interpret=bool(interpret))
    return fit[:j], host[:j]
