"""Pallas TPU kernel for the match-cycle preference build.

The auction matcher (ops/match.py, replacing the reference's Fenzo
``scheduleOnce`` hot loop, scheduler.clj:617-687) starts by scoring every
(job, host) pair — feasibility under the offered resources plus the
cpuMemBinPacker fitness (config.clj:108) — and keeping each job's top-K
hosts.  Done naively that materializes an f32[J, H] score matrix in HBM:
at the BASELINE.md scale (1M jobs x 50k offers) that is ~200 GB of HBM
traffic, far past a v5e chip's budget.

This kernel computes the scores *blockwise in VMEM* and carries a running
top-K per job tile across host tiles, so HBM traffic is O(J*R + H*R + J*K)
— the inputs and the result, never the J x H cross product.  The host axis
is the innermost grid dimension; VMEM scratch persists across the
sequential TPU grid, which is what makes the running top-K merge legal.

Resource comparisons are unrolled over the (tiny, static) resource axis so
every op in the kernel is a 2-D [TJ, TH] VPU op; the top-K merge is K
unrolled selection passes over the concatenated [TJ, K+TH] candidate
buffer (max + first-argmax-via-iota + mask), avoiding any sort/top_k
primitive inside the kernel.

On CPU (tests, fallback deployments) the kernel runs in interpret mode;
parity with the plain-XLA formulation in ops/match.py is bit-exact and
asserted in tests/test_pallas.py.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# pltpu is importable on CPU builds too (needed even for interpret-mode
# scratch shapes); if this import fails the pallas path is unusable and the
# caller should select a plain-XLA matcher backend instead.
from jax.experimental.pallas import tpu as pltpu

NEG_INF = float("-inf")
_BIG = 2**31 - 1  # python literal: module-level jnp consts would be captured


def _cdiv(a: int, b: int) -> int:
    return -(-a // b)


def _pad_hosts(avail, capacity, hp: int, n_res: int):
    """Pad the host axis: padded hosts get avail=-1 (nothing fits) and
    capacity=1 (no divide-by-zero in the fitness)."""
    h = avail.shape[0]
    avail_p = jnp.full((hp, n_res), -1.0, jnp.float32).at[:h].set(avail)
    cap_p = jnp.ones((hp, n_res), jnp.float32).at[:h].set(capacity)
    return avail_p, cap_p


def _resource_feasible(feas, res_ref, avail_t_ref, n_res: int):
    """AND resource fit into ``feas``, unrolled over the static resource
    axis so every op stays a 2-D [TJ, TH] VPU op."""
    for r in range(n_res):
        feas &= avail_t_ref[r:r + 1, :] >= res_ref[:, r:r + 1]
    return feas


def _binpack_score(feas, res_ref, avail_t_ref, cap_t_ref):
    """cpuMemBinPacker fitness on resources 0 (cpus) and 1 (mem)
    (config.clj:108), NEG_INF where infeasible."""
    fit = jnp.zeros(feas.shape, dtype=jnp.float32)
    for r in (0, 1):
        cap_row = jnp.maximum(cap_t_ref[r:r + 1, :], 1e-9)
        used_row = cap_t_ref[r:r + 1, :] - avail_t_ref[r:r + 1, :]
        fit += (used_row + res_ref[:, r:r + 1]) / cap_row
    return jnp.where(feas, fit * 0.5, NEG_INF)


def _merge_running_topk(score, h, tile_h, run_fit, run_host,
                        out_fit_ref, out_host_ref, k: int):
    """Init (first host tile) / merge / emit (last host tile) of the
    running per-job top-K carried across the sequential host grid.
    SHARED by the dense and structured kernels — the tie-breaking merge
    must never drift between them (their parity is test-asserted).

    Previous top-K entries sit at positions < TH entries, and run_fit is
    sorted descending, so "first position achieving the max" reproduces
    lax.top_k's lowest-host-index tie-breaking exactly."""
    tj = score.shape[0]

    @pl.when(h == 0)
    def _init():
        run_fit[:] = jnp.full((tj, k), NEG_INF, dtype=jnp.float32)
        run_host[:] = jnp.zeros((tj, k), dtype=jnp.int32)

    host_idx = jax.lax.broadcasted_iota(jnp.int32, score.shape, 1) \
        + h * tile_h
    combined = jnp.concatenate([run_fit[:], score], axis=1)       # [TJ, K+TH]
    combined_idx = jnp.concatenate([run_host[:], host_idx], axis=1)
    pos = jax.lax.broadcasted_iota(jnp.int32, combined.shape, 1)
    for kk in range(k):
        m = jnp.max(combined, axis=1, keepdims=True)              # [TJ, 1]
        first = jnp.min(jnp.where(combined == m, pos, _BIG), axis=1,
                        keepdims=True)
        sel = pos == first
        run_fit[:, kk:kk + 1] = m
        run_host[:, kk:kk + 1] = jnp.sum(
            jnp.where(sel, combined_idx, 0), axis=1, keepdims=True)
        combined = jnp.where(sel, NEG_INF, combined)

    @pl.when(h == pl.num_programs(1) - 1)
    def _emit():
        out_fit_ref[:] = run_fit[:]
        out_host_ref[:] = run_host[:]


def _kernel(res_ref, cmask_ref, avail_t_ref, cap_t_ref,
            out_fit_ref, out_host_ref, run_fit, run_host, *, n_res: int,
            k: int, tile_h: int):
    """One (job-tile, host-tile) grid step: score the tile, merge top-K.
    The mask travels through HBM as int8 (1 byte/element); upcast in VMEM
    before comparing — Mosaic lacks vector i8 compares on this target."""
    h = pl.program_id(1)
    feas = _resource_feasible(cmask_ref[:].astype(jnp.int32) > 0,
                              res_ref, avail_t_ref, n_res)
    score = _binpack_score(feas, res_ref, avail_t_ref, cap_t_ref)
    _merge_running_topk(score, h, tile_h, run_fit, run_host,
                        out_fit_ref, out_host_ref, k)


@functools.partial(jax.jit, static_argnames=("k", "tile_j", "tile_h",
                                             "interpret"))
def _topk_prefs_padded(job_res, cmask_i8, avail_t, cap_t, *, k: int,
                       tile_j: int, tile_h: int, interpret: bool):
    jp, n_res = job_res.shape
    hp = avail_t.shape[1]
    grid = (jp // tile_j, hp // tile_h)
    kernel = functools.partial(_kernel, n_res=n_res, k=k, tile_h=tile_h)
    out_shape = (jax.ShapeDtypeStruct((jp, k), jnp.float32),
                 jax.ShapeDtypeStruct((jp, k), jnp.int32))
    mem = {"memory_space": pltpu.VMEM}
    scratch = [pltpu.VMEM((tile_j, k), jnp.float32),
               pltpu.VMEM((tile_j, k), jnp.int32)]
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((tile_j, n_res), lambda j, h: (j, 0), **mem),
            pl.BlockSpec((tile_j, tile_h), lambda j, h: (j, h), **mem),
            pl.BlockSpec((n_res, tile_h), lambda j, h: (0, h), **mem),
            pl.BlockSpec((n_res, tile_h), lambda j, h: (0, h), **mem),
        ],
        out_specs=(
            pl.BlockSpec((tile_j, k), lambda j, h: (j, 0), **mem),
            pl.BlockSpec((tile_j, k), lambda j, h: (j, 0), **mem),
        ),
        out_shape=out_shape,
        scratch_shapes=scratch,
        interpret=interpret,
    )(job_res, cmask_i8, avail_t, cap_t)


def _structured_kernel(res_ref, valid_ref, eid_ref, hostgpu_ref, hostok_ref,
                       exc_ref, avail_t_ref, cap_t_ref,
                       out_fit_ref, out_host_ref, run_fit, run_host, *,
                       n_res: int, k: int, tile_h: int):
    """Like _kernel, but the constraint mask is COMPOSED IN VMEM from the
    structured form (host vectors + exception rows) — no [J, H] array ever
    exists, in HBM or anywhere: gpu bidirectional isolation from the job's
    gpu demand column, host blocks from a [1, TH] vector, and exception
    rows selected with a one-hot [TJ, E] x [E, TH] matmul (MXU-friendly;
    per-row dynamic gathers are not)."""
    h = pl.program_id(1)
    tj = res_ref.shape[0]
    n_exc = exc_ref.shape[0]

    # mask algebra stays in i32 end-to-end: Mosaic rejects i1-vector
    # selects ("unsupported target bitwidth for truncation"), so select is
    # expressed as 0/1 arithmetic
    gpu_i = (res_ref[:, 2:3] > 0.0).astype(jnp.int32)         # [TJ, 1]
    hg_i = hostgpu_ref[:].astype(jnp.int32)                   # [1, TH]
    base_i = (gpu_i * hg_i + (1 - gpu_i) * (1 - hg_i)) \
        * hostok_ref[:].astype(jnp.int32)                     # [TJ, TH]
    eid = eid_ref[:]                                          # [TJ, 1]
    onehot = (jax.lax.broadcasted_iota(jnp.int32, (tj, n_exc), 1)
              == eid).astype(jnp.float32)
    exc_i = (jnp.dot(onehot, exc_ref[:].astype(jnp.float32),
                     preferred_element_type=jnp.float32)
             > 0.5).astype(jnp.int32)
    has_exc = (eid >= 0).astype(jnp.int32)                    # [TJ, 1]
    feas_i = (has_exc * exc_i + (1 - has_exc) * base_i) \
        * valid_ref[:].astype(jnp.int32)
    feas = _resource_feasible(feas_i > 0, res_ref, avail_t_ref, n_res)
    score = _binpack_score(feas, res_ref, avail_t_ref, cap_t_ref)
    _merge_running_topk(score, h, tile_h, run_fit, run_host,
                        out_fit_ref, out_host_ref, k)


@functools.partial(jax.jit, static_argnames=("k", "tile_j", "tile_h",
                                             "interpret"))
def _topk_structured_padded(job_res, valid_i8, exc_id, host_gpu_i8,
                            host_ok_i8, exc_i8, avail_t, cap_t, *, k: int,
                            tile_j: int, tile_h: int, interpret: bool):
    jp, n_res = job_res.shape
    hp = avail_t.shape[1]
    n_exc = exc_i8.shape[0]
    grid = (jp // tile_j, hp // tile_h)
    kernel = functools.partial(_structured_kernel, n_res=n_res, k=k,
                               tile_h=tile_h)
    out_shape = (jax.ShapeDtypeStruct((jp, k), jnp.float32),
                 jax.ShapeDtypeStruct((jp, k), jnp.int32))
    mem = {"memory_space": pltpu.VMEM}
    scratch = [pltpu.VMEM((tile_j, k), jnp.float32),
               pltpu.VMEM((tile_j, k), jnp.int32)]
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((tile_j, n_res), lambda j, h: (j, 0), **mem),
            pl.BlockSpec((tile_j, 1), lambda j, h: (j, 0), **mem),
            pl.BlockSpec((tile_j, 1), lambda j, h: (j, 0), **mem),
            pl.BlockSpec((1, tile_h), lambda j, h: (0, h), **mem),
            pl.BlockSpec((1, tile_h), lambda j, h: (0, h), **mem),
            pl.BlockSpec((n_exc, tile_h), lambda j, h: (0, h), **mem),
            pl.BlockSpec((n_res, tile_h), lambda j, h: (0, h), **mem),
            pl.BlockSpec((n_res, tile_h), lambda j, h: (0, h), **mem),
        ],
        out_specs=(
            pl.BlockSpec((tile_j, k), lambda j, h: (j, 0), **mem),
            pl.BlockSpec((tile_j, k), lambda j, h: (j, 0), **mem),
        ),
        out_shape=out_shape,
        scratch_shapes=scratch,
        interpret=interpret,
    )(job_res, valid_i8, exc_id, host_gpu_i8, host_ok_i8, exc_i8,
      avail_t, cap_t)


# recompile telemetry per kernel (see ops/telemetry.py): the pallas
# entry points count like every other jitted kernel, so a tile/shape
# bucket churn shows up on cook_jit_compile_total instead of as a
# silent on-chip p99 blip
from . import telemetry as _telemetry  # noqa: E402

_topk_prefs_padded = _telemetry.instrument_jit(
    "pallas.topk_prefs", _topk_prefs_padded)
_topk_structured_padded = _telemetry.instrument_jit(
    "pallas.topk_structured", _topk_structured_padded)


def topk_prefs_structured(job_res: jax.Array, valid: jax.Array,
                          host_gpu: jax.Array, host_blocked: jax.Array,
                          exc_id: jax.Array, exc_mask: jax.Array,
                          avail: jax.Array, capacity: jax.Array,
                          k: int = 16, *, tile_j: int = 128,
                          tile_h: int = 128,
                          interpret: Optional[bool] = None
                          ) -> Tuple[jax.Array, jax.Array]:
    """Top-K host preferences from the STRUCTURED constraint-mask form
    (parallel/sharded.StructuredPoolCycleInputs semantics: per-host gpu /
    blocked vectors + full exception rows for the complex-job minority).

    Unlike :func:`topk_prefs`, no [J, H] array exists anywhere — not even
    as an input — so this is the preference build that actually runs at
    the BASELINE scale (1M x 50k would need a 50 GB mask input otherwise).
    Total HBM traffic: O(J*R + H + E*H + J*K).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    j, n_res = job_res.shape
    h = avail.shape[0]
    e = exc_mask.shape[0]
    k = min(k, h)
    jp, hp = _cdiv(j, tile_j) * tile_j, _cdiv(h, tile_h) * tile_h
    # exceptions pad to a full 128 lane group: the one-hot selector's
    # [TJ, E] shape needs a lane-aligned E for Mosaic, and the [E, TH]
    # block rides the MXU as a matmul operand
    ep = max(128, _cdiv(e, 128) * 128)

    job_res_p = jnp.zeros((jp, n_res), jnp.float32).at[:j].set(job_res)
    valid_p = jnp.zeros((jp, 1), jnp.int8).at[:j, 0].set(
        valid.astype(jnp.int8))
    eid_p = jnp.full((jp, 1), -1, jnp.int32).at[:j, 0].set(exc_id)
    hg_p = jnp.zeros((1, hp), jnp.int8).at[0, :h].set(
        host_gpu.astype(jnp.int8))
    # padded hosts stay blocked (ok=0); real hosts ok unless blocked
    hok_p = jnp.zeros((1, hp), jnp.int8).at[0, :h].set(
        (~host_blocked).astype(jnp.int8))
    exc_p = jnp.zeros((ep, hp), jnp.int8).at[:e, :h].set(
        exc_mask.astype(jnp.int8))
    avail_p, cap_p = _pad_hosts(avail, capacity, hp, n_res)

    fit, host = _topk_structured_padded(
        job_res_p, valid_p, eid_p, hg_p, hok_p, exc_p, avail_p.T, cap_p.T,
        k=k, tile_j=tile_j, tile_h=tile_h, interpret=bool(interpret))
    return fit[:j], host[:j]


def topk_prefs(job_res: jax.Array, constraint_mask: jax.Array,
               valid: jax.Array, avail: jax.Array, capacity: jax.Array,
               k: int = 16, *, tile_j: int = 128, tile_h: int = 128,
               interpret: Optional[bool] = None
               ) -> Tuple[jax.Array, jax.Array]:
    """Blockwise top-K host preferences per job.

    Args mirror ops.match.MatchInputs: job_res f32[J, R], constraint_mask
    bool[J, H], valid bool[J], avail/capacity f32[H, R].  Returns
    (pref_fit f32[J, K], pref_host i32[J, K]) identical to
    ``lax.top_k(score, K)`` over the full score matrix, without ever
    materializing it.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    j, n_res = job_res.shape
    h = avail.shape[0]
    k = min(k, h)
    jp, hp = _cdiv(j, tile_j) * tile_j, _cdiv(h, tile_h) * tile_h

    # int8, not f32: the padded mask is the only J x H array this path
    # touches, keep it at 1 byte/element
    cmask = constraint_mask & valid[:, None]
    cmask_i8 = jnp.zeros((jp, hp), jnp.int8).at[:j, :h].set(
        cmask.astype(jnp.int8))
    job_res_p = jnp.zeros((jp, n_res), jnp.float32).at[:j].set(job_res)
    avail_p, cap_p = _pad_hosts(avail, capacity, hp, n_res)

    fit, host = _topk_prefs_padded(
        job_res_p, cmask_i8, avail_p.T, cap_p.T, k=k, tile_j=tile_j,
        tile_h=tile_h, interpret=bool(interpret))
    return fit[:j], host[:j]
