"""Segmented scan primitives shared by the rank/match/rebalance kernels.

A segmented prefix sum with restart flags is the workhorse that replaces the
reference's per-user / per-host ``reductions`` loops (dru.clj:43-48,
rebalancer.clj:380-404).  Implemented as an associative scan over
(value, segment-start-flag) pairs — unlike ``cumsum(x) - cumsum(x)[base]``,
no cross-segment cancellation occurs, so precision stays at the scale of a
single segment's values even with millions of rows in float32.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _combine(a, b):
    av, af = a
    bv, bf = b
    return jnp.where(bf, bv, av + bv), af | bf


def segmented_cumsum(x: jax.Array, start_flags: jax.Array) -> jax.Array:
    """Per-segment inclusive prefix sum.

    ``start_flags`` bool[T] marks the first element of each segment (element 0
    should be marked).  Works for any trailing shape of ``x``.
    """
    flags = start_flags.reshape((x.shape[0],) + (1,) * (x.ndim - 1))
    out, _ = jax.lax.associative_scan(_combine, (x, flags), axis=0)
    return out


def segmented_cumsum_by_first_idx(x: jax.Array, first_idx: jax.Array) -> jax.Array:
    """Segmented prefix sum where ``first_idx[t]`` is the index of t's segment
    start (the contiguous-segment encoding used by the rank kernel)."""
    t = jnp.arange(x.shape[0], dtype=first_idx.dtype)
    return segmented_cumsum(x, t == first_idx)
