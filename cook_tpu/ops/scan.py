"""Segmented scan primitives shared by the rank/match/rebalance kernels.

A segmented prefix sum with restart flags is the workhorse that replaces the
reference's per-user / per-host ``reductions`` loops (dru.clj:43-48,
rebalancer.clj:380-404).  Implemented as an associative scan over
(value, segment-start-flag) pairs — unlike ``cumsum(x) - cumsum(x)[base]``,
no cross-segment cancellation occurs, so precision stays at the scale of a
single segment's values even with millions of rows in float32.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _combine(a, b):
    av, af = a
    bv, bf = b
    return jnp.where(bf, bv, av + bv), af | bf


def segmented_cumsum(x: jax.Array, start_flags: jax.Array) -> jax.Array:
    """Per-segment inclusive prefix sum.

    ``start_flags`` bool[T] marks the first element of each segment (element 0
    should be marked).  Works for any trailing shape of ``x``.
    """
    flags = start_flags.reshape((x.shape[0],) + (1,) * (x.ndim - 1))
    out, _ = jax.lax.associative_scan(_combine, (x, flags), axis=0)
    return out


def segmented_cumsum_by_first_idx(x: jax.Array, first_idx: jax.Array) -> jax.Array:
    """Segmented prefix sum where ``first_idx[t]`` is the index of t's segment
    start (the contiguous-segment encoding used by the rank kernel)."""
    t = jnp.arange(x.shape[0], dtype=first_idx.dtype)
    return segmented_cumsum(x, t == first_idx)


def user_segments_from_flags(is_first: jax.Array, axis: int = -1):
    """Derive (user_rank, first_idx) from the wire form's USER_FIRST
    segment-boundary bits — the decision-critical recipe shared by the
    fused cycle's device-side expansion (parallel/sharded.expand_compact)
    and the compact rank kernel (ops/dru.rank_kernel_compact), kept in
    ONE place so the two paths cannot silently diverge.  Padding rows
    (flags 0) inherit the last segment, inert downstream because their
    valid bit is 0."""
    if axis < 0:
        axis += is_first.ndim
    T = is_first.shape[axis]
    user_rank = jnp.cumsum(is_first.astype(jnp.int32), axis=axis) - 1
    shape = [1] * is_first.ndim
    shape[axis] = T
    iota = jnp.arange(T, dtype=jnp.int32).reshape(shape)
    first_idx = jax.lax.cummax(
        jnp.where(is_first, iota, 0), axis=axis)
    return user_rank, first_idx
