"""DRU (Dominant Resource Usage) fair-share ranking as jitted tensor kernels.

Re-expresses the reference's rank hot loop (SURVEY.md HOT LOOP #1;
reference: scheduler/src/cook/scheduler/dru.clj:43-126 and
scheduler.clj sort-jobs-by-dru-helper/limit-over-quota-jobs :2057-2099) as
segmented prefix sums + one global sort, instead of per-user lazy lists merged
through a priority queue:

  per user u, tasks sorted by the user's task order (running first, then
  priority/submit order):
      cum[u,i]   = sum of resources of tasks 0..i of u          (prefix sum)
      dru[u,i]   = max(cum_mem/share_mem, cum_cpus/share_cpus)  (default mode)
                 |  cum_gpus/share_gpus                         (gpu mode)
  global rank = all tasks sorted ascending by dru.

Tasks from all users are laid out contiguously per user in one padded array;
segment starts are carried as `first_idx` (index of the first task of this
task's user), which turns per-user prefix sums into
``cumsum(x) - cumsum(x)[first_idx-1]`` — an O(T) computation with no
data-dependent control flow, so XLA maps it to a handful of fused loops.

Quota enforcement at rank time is folded in as masks:
  * per-user over-quota limiting (reference: limit-over-quota-jobs
    scheduler.clj:2057): tasks after the Nth over-quota task are dropped;
  * pool-level quota (reference: filter-based-on-pool-quota tools.clj:917) is
    a cumsum + compare over the ranked pending jobs.

Ties: the reference explicitly allows any order for equal DRUs
(dru.clj:114-116 docstring); we fix (dru, user_rank, position) ordering so the
kernel and the CPU fallback agree bit-for-bit.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from . import scan as scanlib

# Column layout of the per-task usage matrix fed to the quota mask.
USAGE_DIMS = ("cpus", "mem", "gpus", "count")


class RankInputs(NamedTuple):
    """Padded device inputs for one pool's rank cycle.

    All tasks (running tasks first within each user, then that user's pending
    jobs in priority order) are grouped contiguously by user.
    """

    usage: jax.Array       # f32[T, 4] per-task (cpus, mem, gpus, count=1)
    quota: jax.Array       # f32[T, 4] the task's user's quota, inf = unlimited
    shares: jax.Array      # f32[T, 3] the user's DRU divisors (cpus, mem, gpus)
    first_idx: jax.Array   # i32[T] index of first task of this task's user
    user_rank: jax.Array   # i32[T] dense rank of the user (sorted by name)
    pending: jax.Array     # bool[T] True for pending (virtual) tasks
    valid: jax.Array       # bool[T] False for padding


class RankResult(NamedTuple):
    order: jax.Array       # i32[T] task indices; ranked pending jobs first
    dru: jax.Array         # f32[T] per-task DRU score (+inf for dropped/padding)
    keep: jax.Array        # bool[T] survived over-quota limiting
    num_ranked: jax.Array  # i32[] number of ranked pending tasks


def segment_cumsum(x: jax.Array, first_idx: jax.Array) -> jax.Array:
    """Per-segment inclusive prefix sum for contiguous segments.

    ``first_idx[t]`` is the index of the first element of t's segment.
    Uses a restart-flag associative scan, not cumsum-minus-base, so float32
    precision is bounded by per-segment magnitudes (no cross-user
    cancellation at production scale).
    """
    return scanlib.segmented_cumsum_by_first_idx(x, first_idx)


def rank_body(usage, quota, shares, first_idx, user_rank, pending, valid,
              gpu_mode: bool, max_over_quota_jobs: int):
    """Pure rank math (jit/vmap-composable): returns
    (order, num_ranked, dru, keep, rankable).  Single source of truth shared
    by :func:`rank_kernel` and the pool-sharded cycle."""
    usage = usage * valid[:, None]

    # --- per-user over-quota limiting (limit-over-quota-jobs) --------------
    cum_all = segment_cumsum(usage, first_idx)
    over = jnp.any(cum_all > quota, axis=-1) & valid
    over_cnt = segment_cumsum(over.astype(jnp.int32), first_idx)
    keep = valid & (over_cnt <= max_over_quota_jobs)

    # --- segmented prefix sums over surviving tasks ------------------------
    cum = segment_cumsum(usage * keep[:, None], first_idx)
    if gpu_mode:
        dru = cum[:, 2] / shares[:, 2]
    else:
        dru = jnp.maximum(cum[:, 1] / shares[:, 1],
                          cum[:, 0] / shares[:, 0])

    # --- global ascending sort over pending survivors ----------------------
    rankable = keep & pending
    sort_dru = jnp.where(rankable, dru, jnp.inf)
    position = jnp.arange(dru.shape[0], dtype=jnp.int32)
    order = jnp.lexsort((position, user_rank, sort_dru)).astype(jnp.int32)
    num_ranked = jnp.sum(rankable.astype(jnp.int32))
    return order, num_ranked, dru, keep, rankable


@functools.partial(jax.jit, static_argnames=("gpu_mode", "max_over_quota_jobs"))
def rank_kernel(inp: RankInputs, *, gpu_mode: bool = False,
                max_over_quota_jobs: int = 100) -> RankResult:
    """Rank one pool's tasks by DRU. Returns ranked order over pending tasks.

    Matches the semantics of sort-jobs-by-dru-helper (scheduler.clj:2073-2099)
    with dru-mode default|gpu (dru.clj:50-80,106-126).
    """
    order, num_ranked, dru, keep, _rankable = rank_body(
        inp.usage, inp.quota, inp.shares, inp.first_idx, inp.user_rank,
        inp.pending, inp.valid, gpu_mode, max_over_quota_jobs)
    return RankResult(order=order, dru=jnp.where(keep, dru, jnp.inf),
                      keep=keep, num_ranked=num_ranked)


class CompactRankInputs(NamedTuple):
    """Minimum-transfer wire form of RankInputs (the split rank path's
    twin of parallel/sharded.CompactPoolCycleInputs): the per-cycle
    per-task upload is the sorted row permutation + one flags byte;
    resource columns live in the device-resident base mirror
    (ops/delta.DeviceBaseMirror) and the per-task share/quota columns
    are gathered ON DEVICE from per-user tables via the USER_FIRST
    segment bit.  At the 1M design point this replaces ~60 MB of host
    broadcast + upload per rank cycle with ~5 B/task."""

    rows: jax.Array       # i32[T] absolute base row per sorted position
    flags: jax.Array      # u8[T] ops/delta FLAG_* bits
    res_base: jax.Array   # f32[N, 4] (cpus, mem, gpus, 1) base mirror
    shares_u: jax.Array   # f32[U, 3] per-user DRU divisors
    quota_u: jax.Array    # f32[U, 4] per-user quota


@functools.partial(jax.jit, static_argnames=("gpu_mode",
                                             "max_over_quota_jobs"))
def rank_kernel_compact(inp: CompactRankInputs, *, gpu_mode: bool = False,
                        max_over_quota_jobs: int = 100) -> RankResult:
    """rank_kernel over the compact wire form: usage gathered from the
    resident base mirror, first_idx/user_rank re-derived from the
    USER_FIRST segment boundaries, shares/quota from per-user tables.
    Decision-identical to rank_kernel on the expanded arrays."""
    from .delta import FLAG_PENDING, FLAG_USER_FIRST, FLAG_VALID
    from .scan import user_segments_from_flags
    usage = inp.res_base[inp.rows]
    flags = inp.flags
    pending = (flags & FLAG_PENDING) != 0
    valid = (flags & FLAG_VALID) != 0
    is_first = (flags & FLAG_USER_FIRST) != 0
    user_rank, first_idx = user_segments_from_flags(is_first)
    ur = jnp.clip(user_rank, 0, inp.shares_u.shape[0] - 1)
    shares = inp.shares_u[ur]
    quota = inp.quota_u[ur]
    order, num_ranked, dru, keep, _rankable = rank_body(
        usage, quota, shares, first_idx, user_rank, pending, valid,
        gpu_mode, max_over_quota_jobs)
    return RankResult(order=order, dru=jnp.where(keep, dru, jnp.inf),
                      keep=keep, num_ranked=num_ranked)


@jax.jit
def pool_quota_mask(job_usage: jax.Array, base_usage: jax.Array,
                    quota: jax.Array, valid: jax.Array) -> jax.Array:
    """Pool-level quota filter over the ranked pending queue.

    ``job_usage`` f32[J, 4] in ranked order; ``base_usage``/``quota`` f32[4]
    are the pool's current running usage and cap.  A job is kept when the
    cumulative usage of *all* jobs ahead of it (kept or not) plus base stays
    below quota — matching filter-based-on-pool-quota (tools.clj:917-933),
    whose accumulator includes filtered jobs.
    """
    cum = jnp.cumsum(job_usage * valid[:, None], axis=0) + base_usage[None, :]
    return valid & jnp.all(cum <= quota[None, :], axis=-1)


@jax.jit
def user_quota_mask(job_usage: jax.Array, user_rank: jax.Array,
                    first_idx: jax.Array, base_usage: jax.Array,
                    quota: jax.Array, valid: jax.Array) -> jax.Array:
    """Per-user quota filter over a user-contiguous job list.

    ``base_usage`` f32[U, 4] running usage per user id; ``quota`` f32[J, 4]
    per job.  Used by the considerable-jobs filter at match time
    (reference: filter-pending-jobs-for-quota tools.clj:899-915).
    """
    cum = segment_cumsum(job_usage * valid[:, None], first_idx)
    total = cum + base_usage[user_rank]
    return valid & jnp.all(total <= quota, axis=-1)


# recompile telemetry per kernel (see ops/telemetry.py)
from . import telemetry as _telemetry  # noqa: E402

rank_kernel = _telemetry.instrument_jit("dru.rank", rank_kernel)
rank_kernel_compact = _telemetry.instrument_jit(
    "dru.rank_compact", rank_kernel_compact)
pool_quota_mask = _telemetry.instrument_jit(
    "dru.pool_quota_mask", pool_quota_mask)
user_quota_mask = _telemetry.instrument_jit(
    "dru.user_quota_mask", user_quota_mask)
