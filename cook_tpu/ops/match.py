"""Batched jobs x offers bin-packing assignment kernels.

Replaces the reference's Fenzo hot loop (SURVEY.md HOT LOOP #2; reference:
fenzo.scheduleOnce called from scheduler.clj:617-687, default fitness
cpuMemBinPacker per config.clj:108) with two TPU formulations:

* :func:`greedy_match_kernel` — ``lax.scan`` over jobs in rank order; each
  step evaluates the full host axis (feasibility + fitness) as wide vector
  ops and commits one assignment.  Bit-exact parity with the sequential CPU
  fallback (``reference_impl.greedy_match``); the sequential carry is only
  the H x R availability matrix.

* :func:`auction_match_kernel` — refresh passes of "rebuild every unassigned
  job's top-K preferred hosts against current availability, then K rounds of
  propose + per-host prefix-sum admission in rank order".  One refresh is
  O(J*H) fully-parallel work, so XLA tiles it onto the MXU/VPU without a
  J-length dependency chain; placement-count parity with greedy is asserted
  statistically in tests (>=99.9% per BASELINE.md).

* :func:`waterfill_match_kernel` — prefix-packing over hosts sorted
  tightest-first; NO J x H work at all (O(H log H + J log J) per round), the
  mode for very large considerable sets where even one J x H pass is heavy.

Both kernels take a precompiled constraint mask (bool[J, H]) — the host-side
constraint compiler (cook_tpu.sched.constraints) lowers the reference's
constraint zoo (constraints.clj) into it.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from . import scan as scanlib

NEG_INF = -jnp.inf


class MatchInputs(NamedTuple):
    job_res: jax.Array          # f32[J, R] demands in rank order
    constraint_mask: jax.Array  # bool[J, H]
    avail: jax.Array            # f32[H, R] offered (spare) resources
    capacity: jax.Array         # f32[H, R] total capacity (for fitness)
    valid: jax.Array            # bool[J] False for padding


def _fitness(need: jax.Array, avail: jax.Array, capacity: jax.Array) -> jax.Array:
    """cpuMemBinPacker: mean post-assignment utilization of cpus+mem.
    Higher is better (pack tight, leave big holes elsewhere)."""
    used = capacity - avail
    cap = jnp.maximum(capacity, 1e-9)
    f_cpu = (used[:, 0] + need[0]) / cap[:, 0]
    f_mem = (used[:, 1] + need[1]) / cap[:, 1]
    return (f_cpu + f_mem) * 0.5


def greedy_assign(job_res, constraint_mask, valid, avail, capacity):
    """Pure greedy-scan math (jit/vmap-composable); single source of truth
    shared by :func:`greedy_match_kernel` and the pool-sharded cycle.
    Returns (assign i32[J], remaining avail f32[H, R])."""

    def step(avail, xs):
        need, cmask, ok = xs
        feasible = jnp.all(avail >= need[None, :], axis=1) & cmask & ok
        fitness = jnp.where(feasible, _fitness(need, avail, capacity), NEG_INF)
        host = jnp.argmax(fitness)  # ties -> lowest index, as in the fallback
        found = feasible[host]
        onehot = (jnp.arange(avail.shape[0]) == host)[:, None]
        avail = avail - jnp.where(found, need[None, :] * onehot, 0.0)
        return avail, jnp.where(found, host, -1).astype(jnp.int32)

    avail, assign = jax.lax.scan(step, avail, (job_res, constraint_mask, valid))
    return assign, avail


@jax.jit
def greedy_match_kernel(inp: MatchInputs) -> Tuple[jax.Array, jax.Array]:
    """Sequential-greedy assignment, one job per scan step.

    Returns (assign i32[J] host index or -1, remaining avail f32[H, R]).
    """
    return greedy_assign(inp.job_res, inp.constraint_mask, inp.valid,
                         inp.avail, inp.capacity)


def _prefix_admit(proposes: jax.Array, cand: jax.Array, job_res: jax.Array,
                  avail: jax.Array, rank: jax.Array, H: int
                  ) -> Tuple[jax.Array, jax.Array]:
    """Per-host rank-order prefix admission, shared by the auction rounds,
    the waterfill rounds, and waterfill compaction.

    Proposals are grouped per candidate host (one lexsort); within a host,
    jobs are admitted in rank order while the cumulative demand prefix
    fits the host's CURRENT availability.  Returns (admitted bool[J],
    consumed f32[H, R])."""
    J = proposes.shape[0]
    choice = jnp.where(proposes, cand, H)
    order = jnp.lexsort((rank, choice))
    sorted_choice = choice[order]
    sorted_res = job_res[order] * (sorted_choice < H)[:, None]
    first = jnp.concatenate(
        [jnp.ones((1,), dtype=bool),
         sorted_choice[1:] != sorted_choice[:-1]])
    seg_cum = scanlib.segmented_cumsum(sorted_res, first)
    host_avail = avail[jnp.minimum(sorted_choice, H - 1)]
    fits_prefix = (jnp.all(seg_cum <= host_avail, axis=1)
                   & (sorted_choice < H))
    admitted = jnp.zeros((J,), dtype=bool).at[order].set(fits_prefix)
    consumed = jax.ops.segment_sum(
        job_res * admitted[:, None], jnp.minimum(choice, H - 1),
        num_segments=H)
    return admitted, consumed


def _build_prefs(inp: MatchInputs, assign: jax.Array, avail: jax.Array,
                 K: int) -> Tuple[jax.Array, jax.Array]:
    """Top-K hosts per unassigned job by bin-packing fitness against the
    CURRENT availability (one J x H pass, MXU/VPU-friendly).

    Equal fitness scores are broken by a DETERMINISTIC per-(job, host)
    tie-break: on a perfectly uniform fleet every host ties, and without
    it all jobs rank the same K hosts (the herding caveat,
    docs/PLACEMENT_QUALITY.md) — each refresh pass then admits only ~K
    jobs.  The ranking key is INTEGER-packed (fitness quantized to 22
    bits, 8 per-(job, host) hash bits below it) rather than a float
    epsilon: an additive float32 jitter small enough to sit below real
    fitness differences falls below one ulp once fit >= 0.5 and
    collapses to a handful of values, silently resurrecting the herd.
    The 2^-22 fitness quantization (~2.4e-7 of the [0, 1] score) is far
    below any meaningful tightness difference (host resource
    granularity puts those at ~1e-4)."""
    J, H = inp.constraint_mask.shape
    feasible = (jnp.all(avail[None, :, :] >= inp.job_res[:, None, :], axis=2)
                & inp.constraint_mask & inp.valid[:, None]
                & (assign < 0)[:, None])
    used = inp.capacity - avail
    cap = jnp.maximum(inp.capacity, 1e-9)
    fit = (used[None, :, 0] + inp.job_res[:, 0:1]) / cap[None, :, 0] \
        + (used[None, :, 1] + inp.job_res[:, 1:2]) / cap[None, :, 1]
    jj = jnp.arange(J, dtype=jnp.uint32)[:, None]
    hh = jnp.arange(H, dtype=jnp.uint32)[None, :]
    mix = (jj * jnp.uint32(2654435761)) ^ (hh * jnp.uint32(0x9E3779B9))
    q = (jnp.clip(fit * 0.5, 0.0, 1.0)
         * jnp.float32(1 << 22)).astype(jnp.int32) << 8
    key_int = q | (mix & jnp.uint32(0xFF)).astype(jnp.int32)
    # bitcast, don't convert: float32 can only represent 24 bits of the
    # 30-bit key, so astype would drop exactly the jitter bits — but for
    # POSITIVE floats the IEEE bit-pattern order equals the value order,
    # so the bitcast view preserves the full integer ranking while
    # keeping top_k on the fast float path (int top_k measured ~80x
    # slower in XLA CPU).
    key = jnp.where(feasible,
                    jax.lax.bitcast_convert_type(key_int, jnp.float32),
                    NEG_INF)
    return jax.lax.top_k(key, K)                       # [J, K] each


@functools.partial(jax.jit,
                   static_argnames=("num_prefs", "num_rounds",
                                    "num_refresh", "min_refresh_gain"))
def auction_match_kernel(inp: MatchInputs, *, num_prefs: int = 16,
                         num_rounds: int = 8, num_refresh: int = 64,
                         min_refresh_gain: int = 16
                         ) -> Tuple[jax.Array, jax.Array]:
    """Parallel top-K auction assignment for large J.

    Up to ``num_refresh`` outer passes; each rebuilds every unassigned
    job's ``num_prefs`` best hosts against the *current* availability,
    then runs ``num_rounds`` rounds of:

      1. every unassigned job proposes to its current preference;
      2. proposals are grouped per host (one lexsort) and admitted in rank
         order while the cumulative demand prefix fits the host's
         availability;
      3. jobs whose preferred host can no longer fit them *individually*
         advance their preference pointer (availability only shrinks within a
         cycle, so advancing is safe); contended-but-feasible jobs retry.

    The refresh pass is what makes the kernel converge under bin-packing
    fitness: all jobs rank the same tightest hosts, so a single static
    preference list herds onto (and exhausts) K hosts; rebuilding against
    post-admission availability moves the herd to the next-tightest hosts
    exactly the way the sequential greedy's evolving fitness does.

    The refresh loop is ADAPTIVE (a ``lax.while_loop``): it exits once a
    full pass admits fewer than ``min_refresh_gain`` new jobs — a fixed
    small budget under-places exactly when the workload is hardest
    (placement grows per pass under contention, docs/PLACEMENT_QUALITY),
    while a strict no-progress exit would crawl through tail placements
    one pass at a time now that the tie-break keeps every pass finding a
    few; the production path's waterfill tail places those leftovers at
    no J x H cost.
    Placement decisions can still deviate from greedy (tests bound them
    statistically); the greedy kernel remains the bit-exact parity mode.
    """
    J, H = inp.constraint_mask.shape
    K = min(num_prefs, H)

    def placed(assign):
        return jnp.sum(assign >= 0)

    def cond(state):
        assign, _avail, prev_placed, passes = state
        # the (passes == 0) term is what guarantees the first pass runs:
        # the -1 sentinel alone yields gain=1, below min_refresh_gain.
        # min_refresh_gain: with the r5 per-job tie-break, a contended
        # pass almost always admits SOMETHING, so an exact no-progress
        # exit would burn the whole num_refresh budget crawling through
        # tail placements the production waterfill tail covers anyway —
        # stop once a full pass stops paying for its J x H rebuild.
        gain = placed(assign) - prev_placed
        return ((passes == 0) | (gain >= min_refresh_gain)) \
            & (passes < num_refresh)

    def body(state):
        assign, avail, _prev, passes = state
        before = placed(assign)
        pref_fit, pref_host = _build_prefs(inp, assign, avail, K)
        assign, avail = _auction_rounds(inp, pref_fit, pref_host, num_rounds,
                                        assign=assign, avail=avail)
        return (assign, avail, before, passes + 1)

    init = (jnp.full((J,), -1, dtype=jnp.int32), inp.avail,
            jnp.int32(-1), jnp.int32(0))
    assign, avail, _, _ = jax.lax.while_loop(cond, body, init)
    return assign, avail


# auction_match_pallas (a dense-mask auction whose preference build ran
# as a blockwise Pallas kernel) was REMOVED in round 5: across three
# rounds of on-chip measurement it never beat the XLA auction at any
# scale that fits a dense mask (r4 capture: 295 ms vs 50 ms p50 at
# 1k x 50k; 2550 ms vs 736 ms compiled at 10k x 50k) and its ~20 s
# first-call compile burned bench deadline every round.  The regime a
# dense kernel cannot reach at all (structured masks at 100k-1M jobs)
# is served by pallas_match.topk_prefs_structured, which stays.


def _auction_rounds(inp: MatchInputs, pref_fit: jax.Array,
                    pref_host: jax.Array, num_rounds: int,
                    assign: jax.Array, avail: jax.Array
                    ) -> Tuple[jax.Array, jax.Array]:
    J, H = inp.constraint_mask.shape
    job_idx = jnp.arange(J, dtype=jnp.int32)
    K = pref_host.shape[1]
    pref_ok = pref_fit > NEG_INF

    def one_round(state, _):
        assign, avail, ptr = state
        active = (assign < 0) & inp.valid & (ptr < K)
        safe_ptr = jnp.minimum(ptr, K - 1)
        cand = jnp.take_along_axis(pref_host, safe_ptr[:, None], axis=1)[:, 0]
        cand_ok = jnp.take_along_axis(pref_ok, safe_ptr[:, None], axis=1)[:, 0]
        fits_alone = jnp.all(avail[cand] >= inp.job_res, axis=1) & cand_ok
        proposes = active & fits_alone
        # a host that can't fit the job individually never will again
        ptr = jnp.where(active & ~fits_alone, ptr + 1, ptr)

        admitted, consumed = _prefix_admit(proposes, cand, inp.job_res,
                                           avail, job_idx, H)
        assign = jnp.where(admitted, cand, assign)
        avail = avail - consumed
        return (assign, avail, ptr), None

    init = (assign, avail, jnp.zeros((J,), dtype=jnp.int32))
    (assign, avail, _), _ = jax.lax.scan(one_round, init, None,
                                         length=num_rounds)
    return assign, avail


@functools.partial(jax.jit,
                   static_argnames=("num_rounds", "num_compaction"))
def waterfill_match_kernel(inp: MatchInputs, *, num_rounds: int = 32,
                           num_compaction: int = 16
                           ) -> Tuple[jax.Array, jax.Array]:
    """Prefix-packing ("waterfill") assignment: the large-J kernel.

    The sequential greedy under bin-packing fitness fills hosts one at a
    time in tightness order — job j lands roughly where its cumulative
    demand prefix falls across the cumulative spare capacity of hosts
    sorted tightest-first.  This kernel computes that correspondence
    directly each round:

      1. sort hosts by current utilization (tightest first) -> sigma;
      2. cum_cap = cumsum(avail[sigma]); cum_dem = cumsum over still-active
         jobs in rank order; job j proposes to sigma[k_j] with
         k_j = max over resources of searchsorted(cum_cap_r, cum_dem_jr)
         (the binding resource decides), plus a per-job skip offset that
         advances past hosts rejected by the constraint mask or an
         individual-fit check;
      3. per-host prefix admission in rank order (as in the auction
         kernel); losers retry next round against updated availability.

    One round is O(H log H + J log J) with NO J x H work at all, and jobs
    spread across *many* hosts per round (the auction/greedy formulations
    admit ~one host's worth per sequential step).  Decisions deviate from
    greedy only at host boundaries and constraint holes; tests bound the
    deviation statistically, and the greedy kernel remains the bit-exact
    mode.  Reference being replaced: the Fenzo scheduleOnce loop,
    scheduler.clj:617-687.

    Constraint-mask scope: the mask is a SAFETY guarantee (a masked host is
    never assigned — admission checks it), not a completeness one.  The
    exponential probe can step over a sparse row's few allowed hosts, so a
    job restricted to specific hosts may go unplaced even with capacity
    free.  The production ``auto`` backend therefore routes sparse-mask
    jobs to the exact greedy scan and only bulk dense-mask jobs here
    (sched/matcher.py).
    """
    J, H = inp.constraint_mask.shape
    R = inp.job_res.shape[1]
    rank = jnp.arange(J, dtype=jnp.int32)
    cap = jnp.maximum(inp.capacity, 1e-9)

    def one_round(state):
        assign, avail, skip, rnd, _changed = state
        skip_before = skip
        active = (assign < 0) & inp.valid & (skip < H)
        util = ((cap[:, 0] - avail[:, 0]) / cap[:, 0]
                + (cap[:, 1] - avail[:, 1]) / cap[:, 1]) * 0.5
        sigma = jnp.argsort(-util)                     # tightest first
        cum_cap = jnp.cumsum(avail[sigma], axis=0)     # [H, R]
        dem = jnp.where(active[:, None], inp.job_res, 0.0)
        cum_dem = jnp.cumsum(dem, axis=0)              # [J, R]
        k = jnp.zeros((J,), dtype=jnp.int32)
        for r in range(R):                             # R is static (4)
            k = jnp.maximum(k, jnp.searchsorted(
                cum_cap[:, r], cum_dem[:, r], side="left").astype(jnp.int32))
        k = jnp.clip(k + skip, 0, H - 1)
        cand = sigma[k]
        fits = (jnp.all(avail[cand] >= inp.job_res, axis=1)
                & inp.constraint_mask[rank, cand])
        proposes = active & fits
        # exponential probe on rejection: hosts later in sigma are emptier
        # and more likely to fit, and a +1 crawl converges one host per
        # round; doubling reaches a fitting host in O(log H) rounds
        skip = jnp.where(active & ~fits, skip * 2 + 1, skip)
        # a successful admission resets the probe for the next proposal
        skip = jnp.where(proposes, 0, skip)

        admitted, consumed = _prefix_admit(proposes, cand, inp.job_res,
                                           avail, rank, H)
        assign = jnp.where(admitted, cand, assign)
        avail = avail - consumed
        # fixed point: nothing admitted and no probe advanced means every
        # later round would recompute the identical state — stop paying
        # for it (exact-result-preserving early exit)
        changed = admitted.any() | (skip != skip_before).any()
        return assign, avail, skip, rnd + 1, changed

    init = (jnp.full((J,), -1, dtype=jnp.int32), inp.avail,
            jnp.zeros((J,), dtype=jnp.int32), jnp.int32(0),
            jnp.bool_(True))
    assign, avail, _, _, _ = jax.lax.while_loop(
        lambda s: (s[3] < num_rounds) & s[4], one_round, init)

    # ---- compaction: tightness-improving migrations -------------------
    # The prefix mapping spreads jobs across many hosts per round, which
    # is what makes the kernel fast but also what packs ~19% looser than
    # greedy (docs/PLACEMENT_QUALITY.md).  Each compaction round lets
    # jobs sitting on looser-than-average hosts re-propose — via the same
    # O(H log H + J log J) prefix machinery, no J x H work — onto
    # hosts tighter (pre-round) than their own, moving only when admitted
    # there.  A move frees the old host and consumes the new one
    # atomically per round; a job that isn't admitted stays where it
    # was, so placements are never lost and capacity is never
    # oversubscribed.  Tightness improves in aggregate (measured
    # 0.783 -> 0.822 mean util at 10k x 50k); rounds are bounded and
    # exit early when no move lands.
    def compact_round(state):
        assign, avail, rnd, _changed = state
        placed = assign >= 0
        util = ((cap[:, 0] - avail[:, 0]) / cap[:, 0]
                + (cap[:, 1] - avail[:, 1]) / cap[:, 1]) * 0.5
        job_host = jnp.maximum(assign, 0)
        job_util = util[job_host]
        holds = jnp.zeros((H,), dtype=bool).at[job_host].max(placed)
        n_used = jnp.maximum(jnp.sum(holds), 1)
        mean_used_util = jnp.sum(jnp.where(holds, util, 0.0)) / n_used
        movers = placed & (job_util < mean_used_util)

        sigma = jnp.argsort(-util)                    # tightest first
        cum_cap = jnp.cumsum(avail[sigma], axis=0)
        dem = jnp.where(movers[:, None], inp.job_res, 0.0)
        cum_dem = jnp.cumsum(dem, axis=0)
        k = jnp.zeros((J,), dtype=jnp.int32)
        for r in range(R):
            k = jnp.maximum(k, jnp.searchsorted(
                cum_cap[:, r], cum_dem[:, r],
                side="left").astype(jnp.int32))
        cand = sigma[jnp.clip(k, 0, H - 1)]
        # tightness gate against PRE-round utilization: within-round
        # interactions (another mover draining the destination) can
        # occasionally make an individual move non-improving, so
        # tightness is an aggregate tendency, not a per-move invariant —
        # the HARD invariants are that no placement is ever lost (a job
        # not admitted stays put) and no host is ever oversubscribed
        # (admission checks current avail; frees apply after).
        # Termination is the round bound plus the no-move exit.
        fits = (jnp.all(avail[cand] >= inp.job_res, axis=1)
                & inp.constraint_mask[rank, cand]
                & (util[cand] > job_util + 1e-6)
                & (cand != assign))
        proposes = movers & fits

        moved, consumed = _prefix_admit(proposes, cand, inp.job_res,
                                        avail, rank, H)
        freed = jax.ops.segment_sum(
            inp.job_res * moved[:, None], job_host, num_segments=H)
        avail = avail + freed - consumed
        assign = jnp.where(moved, cand, assign)
        return assign, avail, rnd + 1, moved.any()

    assign, avail, _, _ = jax.lax.while_loop(
        lambda s: (s[2] < num_compaction) & s[3], compact_round,
        (assign, avail, jnp.int32(0), jnp.bool_(True)))
    return assign, avail


# Per-kernel recompile telemetry (ops/telemetry.py): a shape change or new
# static-arg combination shows up as cook_jit_compile_total{kernel=...} and
# a tag on the owning cycle's flight record instead of a silent p99 blip.
from . import telemetry as _telemetry  # noqa: E402

greedy_match_kernel = _telemetry.instrument_jit(
    "match.greedy", greedy_match_kernel)
auction_match_kernel = _telemetry.instrument_jit(
    "match.auction", auction_match_kernel)
waterfill_match_kernel = _telemetry.instrument_jit(
    "match.waterfill", waterfill_match_kernel)

# Backwards-compatible alias; the auction formulation superseded the naive
# every-job-argmax multipass, which converged one host per pass.
multipass_match_kernel = auction_match_kernel
