"""Batched jobs x offers bin-packing assignment kernels.

Replaces the reference's Fenzo hot loop (SURVEY.md HOT LOOP #2; reference:
fenzo.scheduleOnce called from scheduler.clj:617-687, default fitness
cpuMemBinPacker per config.clj:108) with two TPU formulations:

* :func:`greedy_match_kernel` — ``lax.scan`` over jobs in rank order; each
  step evaluates the full host axis (feasibility + fitness) as wide vector
  ops and commits one assignment.  Bit-exact parity with the sequential CPU
  fallback (``reference_impl.greedy_match``); the sequential carry is only
  the H x R availability matrix.

* :func:`multipass_match_kernel` — K rounds of "every unassigned job picks
  its best host in parallel, then per-host prefix-sum conflict resolution in
  rank order".  One round is O(J*H) fully-parallel work, so XLA tiles it onto
  the MXU/VPU without a J-length dependency chain; a handful of rounds
  converges to the greedy answer for real offer distributions (parity is
  asserted statistically in tests, >=99.9% per BASELINE.md).

Both kernels take a precompiled constraint mask (bool[J, H]) — the host-side
constraint compiler (cook_tpu.sched.constraints) lowers the reference's
constraint zoo (constraints.clj) into it.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from . import scan as scanlib

NEG_INF = -jnp.inf


class MatchInputs(NamedTuple):
    job_res: jax.Array          # f32[J, R] demands in rank order
    constraint_mask: jax.Array  # bool[J, H]
    avail: jax.Array            # f32[H, R] offered (spare) resources
    capacity: jax.Array         # f32[H, R] total capacity (for fitness)
    valid: jax.Array            # bool[J] False for padding


def _fitness(need: jax.Array, avail: jax.Array, capacity: jax.Array) -> jax.Array:
    """cpuMemBinPacker: mean post-assignment utilization of cpus+mem.
    Higher is better (pack tight, leave big holes elsewhere)."""
    used = capacity - avail
    cap = jnp.maximum(capacity, 1e-9)
    f_cpu = (used[:, 0] + need[0]) / cap[:, 0]
    f_mem = (used[:, 1] + need[1]) / cap[:, 1]
    return (f_cpu + f_mem) * 0.5


def greedy_assign(job_res, constraint_mask, valid, avail, capacity):
    """Pure greedy-scan math (jit/vmap-composable); single source of truth
    shared by :func:`greedy_match_kernel` and the pool-sharded cycle.
    Returns (assign i32[J], remaining avail f32[H, R])."""

    def step(avail, xs):
        need, cmask, ok = xs
        feasible = jnp.all(avail >= need[None, :], axis=1) & cmask & ok
        fitness = jnp.where(feasible, _fitness(need, avail, capacity), NEG_INF)
        host = jnp.argmax(fitness)  # ties -> lowest index, as in the fallback
        found = feasible[host]
        onehot = (jnp.arange(avail.shape[0]) == host)[:, None]
        avail = avail - jnp.where(found, need[None, :] * onehot, 0.0)
        return avail, jnp.where(found, host, -1).astype(jnp.int32)

    avail, assign = jax.lax.scan(step, avail, (job_res, constraint_mask, valid))
    return assign, avail


@jax.jit
def greedy_match_kernel(inp: MatchInputs) -> Tuple[jax.Array, jax.Array]:
    """Sequential-greedy assignment, one job per scan step.

    Returns (assign i32[J] host index or -1, remaining avail f32[H, R]).
    """
    return greedy_assign(inp.job_res, inp.constraint_mask, inp.valid,
                         inp.avail, inp.capacity)


@functools.partial(jax.jit, static_argnames=("num_prefs", "num_rounds"))
def auction_match_kernel(inp: MatchInputs, *, num_prefs: int = 16,
                         num_rounds: int = 24) -> Tuple[jax.Array, jax.Array]:
    """Parallel top-K auction assignment for large J.

    Every job precomputes its ``num_prefs`` best hosts by bin-packing fitness
    (one J x H pass, MXU/VPU-friendly), then ``num_rounds`` rounds of:

      1. every unassigned job proposes to its current preference;
      2. proposals are grouped per host (one lexsort) and admitted in rank
         order while the cumulative demand prefix fits the host's
         availability;
      3. jobs whose preferred host can no longer fit them *individually*
         advance their preference pointer (availability only shrinks within a
         cycle, so advancing is safe); contended-but-feasible jobs retry.

    The first-ranked feasible proposer on a host always fits its own prefix,
    so every contended host admits at least one job per round.  This trades
    the greedy kernel's J-step dependency chain for ~num_rounds data-parallel
    steps; placement decisions can deviate from greedy (fitness is computed
    against the cycle-start availability), which the tests bound
    statistically — the greedy kernel remains the bit-exact parity mode.
    """
    J, H = inp.constraint_mask.shape
    feasible0 = (jnp.all(inp.avail[None, :, :] >= inp.job_res[:, None, :], axis=2)
                 & inp.constraint_mask & inp.valid[:, None])
    used = inp.capacity - inp.avail
    cap = jnp.maximum(inp.capacity, 1e-9)
    fit = (used[None, :, 0] + inp.job_res[:, 0:1]) / cap[None, :, 0] \
        + (used[None, :, 1] + inp.job_res[:, 1:2]) / cap[None, :, 1]
    fit = jnp.where(feasible0, fit * 0.5, NEG_INF)
    K = min(num_prefs, H)
    pref_fit, pref_host = jax.lax.top_k(fit, K)        # [J, K]
    return _auction_rounds(inp, pref_fit, pref_host, num_rounds)


def auction_match_pallas(inp: MatchInputs, *, num_prefs: int = 16,
                         num_rounds: int = 24, interpret=None
                         ) -> Tuple[jax.Array, jax.Array]:
    """Auction assignment whose preference build runs as a blockwise Pallas
    kernel (ops/pallas_match.py) — same result as
    :func:`auction_match_kernel`, but the J x H score matrix never touches
    HBM.  Preferred on TPU at large J x H."""
    from . import pallas_match
    pref_fit, pref_host = pallas_match.topk_prefs(
        inp.job_res, inp.constraint_mask, inp.valid, inp.avail, inp.capacity,
        k=num_prefs, interpret=interpret)
    return _auction_rounds_jit(inp, pref_fit, pref_host,
                               num_rounds=num_rounds)


@functools.partial(jax.jit, static_argnames=("num_rounds",))
def _auction_rounds_jit(inp, pref_fit, pref_host, *, num_rounds):
    return _auction_rounds(inp, pref_fit, pref_host, num_rounds)


def _auction_rounds(inp: MatchInputs, pref_fit: jax.Array,
                    pref_host: jax.Array, num_rounds: int
                    ) -> Tuple[jax.Array, jax.Array]:
    J, H = inp.constraint_mask.shape
    job_idx = jnp.arange(J, dtype=jnp.int32)
    K = pref_host.shape[1]
    pref_ok = pref_fit > NEG_INF

    def one_round(state, _):
        assign, avail, ptr = state
        active = (assign < 0) & inp.valid & (ptr < K)
        safe_ptr = jnp.minimum(ptr, K - 1)
        cand = jnp.take_along_axis(pref_host, safe_ptr[:, None], axis=1)[:, 0]
        cand_ok = jnp.take_along_axis(pref_ok, safe_ptr[:, None], axis=1)[:, 0]
        fits_alone = jnp.all(avail[cand] >= inp.job_res, axis=1) & cand_ok
        proposes = active & fits_alone
        # a host that can't fit the job individually never will again
        ptr = jnp.where(active & ~fits_alone, ptr + 1, ptr)

        choice = jnp.where(proposes, cand, H)
        order = jnp.lexsort((job_idx, choice))
        sorted_choice = choice[order]
        sorted_res = inp.job_res[order] * (sorted_choice < H)[:, None]
        first_of_seg = jnp.concatenate(
            [jnp.ones((1,), dtype=bool), sorted_choice[1:] != sorted_choice[:-1]])
        seg_cum = scanlib.segmented_cumsum(sorted_res, first_of_seg)
        host_avail = avail[jnp.minimum(sorted_choice, H - 1)]
        fits_prefix = (jnp.all(seg_cum <= host_avail, axis=1)
                       & (sorted_choice < H))
        admitted = jnp.zeros((J,), dtype=bool).at[order].set(fits_prefix)
        assign = jnp.where(admitted, choice, assign)
        consumed = jax.ops.segment_sum(
            inp.job_res * admitted[:, None], jnp.minimum(choice, H - 1),
            num_segments=H)
        avail = avail - consumed
        return (assign, avail, ptr), None

    init = (jnp.full((J,), -1, dtype=jnp.int32), inp.avail,
            jnp.zeros((J,), dtype=jnp.int32))
    (assign, avail, _), _ = jax.lax.scan(one_round, init, None,
                                         length=num_rounds)
    return assign, avail


# Backwards-compatible alias; the auction formulation superseded the naive
# every-job-argmax multipass, which converged one host per pass.
multipass_match_kernel = auction_match_kernel
