"""Preemption victim-selection kernel.

Re-expresses the reference's rebalancer inner loop (SURVEY.md HOT LOOP #3b;
reference: compute-preemption-decision rebalancer.clj:320-407) as one batched
computation: tasks pre-sorted by (host, dru descending) with per-host spare
resources; the kernel evaluates every "preempt the k highest-DRU eligible
tasks on host h" prefix simultaneously via a segmented prefix sum and takes
the global argmax of decision quality (= minimum victim DRU; spare-only
placements score +inf, the reference's Double/MAX_VALUE rows).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from . import scan as scanlib


class RebalanceInputs(NamedTuple):
    """Padded inputs. Tasks sorted by (host_idx, -dru); padding rows have
    eligible=False and host_idx pointing at a real host (clamped)."""

    task_dru: jax.Array      # f32[T]
    task_res: jax.Array      # f32[T, R]
    task_host: jax.Array     # i32[T]
    host_start: jax.Array    # bool[T] first row of its host segment
    eligible: jax.Array      # bool[T] passes dru/quota/self filters
    spare: jax.Array         # f32[H, R] spare resources per host
    host_ok: jax.Array       # bool[H] passes the pending job's constraints
    demand: jax.Array        # f32[R] pending job resources


class RebalanceDecision(NamedTuple):
    found: jax.Array         # bool[]
    spare_only: jax.Array    # bool[] no preemption needed, spare suffices
    host: jax.Array          # i32[] winning host index
    victim_mask: jax.Array   # bool[T] tasks to preempt
    decision_dru: jax.Array  # f32[] min victim dru (inf when spare_only)


@jax.jit
def preemption_kernel(inp: RebalanceInputs) -> RebalanceDecision:
    T = inp.task_dru.shape[0]
    t_idx = jnp.arange(T, dtype=jnp.int32)

    res_eligible = inp.task_res * inp.eligible[:, None]
    seg_cum = scanlib.segmented_cumsum(res_eligible, inp.host_start)
    total = inp.spare[inp.task_host] + seg_cum
    task_host_ok = inp.host_ok[inp.task_host]
    feasible = (jnp.all(total >= inp.demand[None, :], axis=1)
                & inp.eligible & task_host_ok)
    # decision quality = dru of the last (lowest-dru) victim in the prefix;
    # within a host the first feasible row IS the best prefix (dru sorted
    # descending), and argmax over dru picks exactly that row.
    score = jnp.where(feasible, inp.task_dru, -jnp.inf)

    # spare-only solutions (reference: MAX_VALUE rows) dominate everything
    spare_feasible = (jnp.all(inp.spare >= inp.demand[None, :], axis=1)
                      & inp.host_ok)
    any_spare = jnp.any(spare_feasible)
    spare_host = jnp.argmax(spare_feasible)  # lowest index among feasible

    best_t = jnp.argmax(score)
    best_score = score[best_t]
    any_task = best_score > -jnp.inf  # note: an unset-share user's dru is +inf
    best_host = inp.task_host[best_t]

    found = any_spare | any_task
    spare_only = any_spare
    host = jnp.where(any_spare, spare_host, best_host).astype(jnp.int32)
    victim_mask = (~spare_only & inp.eligible
                   & (inp.task_host == host) & (t_idx <= best_t))
    decision_dru = jnp.where(spare_only, jnp.inf, best_score)
    return RebalanceDecision(found=found, spare_only=spare_only, host=host,
                             victim_mask=victim_mask,
                             decision_dru=decision_dru)


# recompile telemetry per kernel (see ops/telemetry.py)
from . import telemetry as _telemetry  # noqa: E402

preemption_kernel = _telemetry.instrument_jit(
    "rebalance.preemption", preemption_kernel)
