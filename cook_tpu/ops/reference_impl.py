"""CPU fallback implementations with the reference's sequential semantics.

These are deliberately *structured like the reference* (per-user lazy prefix
sums merged through a heap — dru.clj:43-126; one-task-at-a-time greedy fit —
Fenzo scheduleOnce; per-host prefix aggregation — rebalancer.clj:320-407)
rather than like the TPU kernels, so they serve two roles:

1. the in-process matcher when no accelerator is present (the reference keeps
   a Fenzo path for exactly this, BASELINE.json north star), and
2. the independent golden for kernel parity tests (SURVEY.md section 7 step 2/3).

All arithmetic is float32 to match on-device precision, keeping decision
parity bit-exact.
"""

from __future__ import annotations

import heapq
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

F32 = np.float32


# --------------------------------------------------------------------------
# DRU ranking (reference: dru.clj + scheduler.clj:2057-2099)
# --------------------------------------------------------------------------

class UserTasks:
    """One user's tasks in that user's sort order (running first, then
    pending by priority/submit-time — tools.clj same-user-task-comparator)."""

    def __init__(self, user: str, task_ids: Sequence[int],
                 usage: np.ndarray, pending: Sequence[bool]):
        self.user = user
        self.task_ids = list(task_ids)     # global task identifiers
        self.usage = np.asarray(usage, dtype=F32)  # [n, 4] cpus, mem, gpus, count
        self.pending = list(pending)


def limit_over_quota(tasks: UserTasks, quota: np.ndarray,
                     max_over_quota_jobs: int) -> UserTasks:
    """Drop tasks after the Nth whose cumulative usage exceeds quota
    (reference: limit-over-quota-jobs scheduler.clj:2057-2071)."""
    quota = np.asarray(quota, dtype=F32)
    total = np.zeros(4, dtype=F32)
    kept_ids, kept_usage, kept_pending = [], [], []
    over_count = 0
    for i in range(len(tasks.task_ids)):
        total = total + tasks.usage[i]
        if np.any(total > quota):
            over_count += 1
        if over_count > max_over_quota_jobs:
            break
        kept_ids.append(tasks.task_ids[i])
        kept_usage.append(tasks.usage[i])
        kept_pending.append(tasks.pending[i])
    usage = np.array(kept_usage, dtype=F32).reshape(len(kept_ids), 4)
    return UserTasks(tasks.user, kept_ids, usage, kept_pending)


def rank_by_dru(users: List[UserTasks],
                shares: Dict[str, Tuple[float, float, float]],
                quotas: Dict[str, np.ndarray],
                gpu_mode: bool = False,
                max_over_quota_jobs: int = 100) -> List[Tuple[int, float]]:
    """Rank pending tasks ascending by DRU.

    Returns [(task_id, dru)] for pending tasks only, in rank order.  Per-user
    streams of (dru, user_rank, position) are merged through a heap, mirroring
    sorted-merge (dru.clj:82-104); users are processed in name order like the
    reference's ``(sort-by first)`` (dru.clj:123).
    """
    streams = []
    for user_rank, ut in enumerate(sorted(users, key=lambda u: u.user)):
        ut = limit_over_quota(ut, quotas[ut.user], max_over_quota_jobs)
        share = np.asarray(shares[ut.user], dtype=F32)
        cum = np.zeros(3, dtype=F32)
        stream = []
        for pos in range(len(ut.task_ids)):
            cum = cum + ut.usage[pos, :3]
            if gpu_mode:
                dru = F32(cum[2] / share[2])
            else:
                dru = F32(max(cum[1] / share[1], cum[0] / share[0]))
            if ut.pending[pos]:
                stream.append((dru, user_rank, pos, ut.task_ids[pos]))
        streams.append(stream)
    merged = heapq.merge(*streams)
    return [(task_id, dru) for dru, _ur, _pos, task_id in merged]


def filter_pool_quota(job_usage: np.ndarray, base_usage: np.ndarray,
                      quota: Optional[np.ndarray]) -> np.ndarray:
    """Pool-quota keep mask over a ranked queue (tools.clj:917-933): the
    accumulator includes filtered jobs."""
    n = job_usage.shape[0]
    keep = np.ones(n, dtype=bool)
    if quota is None:
        return keep
    total = np.asarray(base_usage, dtype=F32).copy()
    for i in range(n):
        total = total + job_usage[i]
        keep[i] = bool(np.all(total <= quota))
    return keep


# --------------------------------------------------------------------------
# Greedy bin-packing match (reference: Fenzo scheduleOnce via
# scheduler.clj:617-687; fitness = cpuMemBinPacker, config.clj:108)
# --------------------------------------------------------------------------

def binpack_fitness(need: np.ndarray, avail: np.ndarray,
                    capacity: np.ndarray) -> np.ndarray:
    """cpuMemBinPacker: mean of post-assignment cpu and mem utilization."""
    used = capacity - avail
    cap = np.maximum(capacity, F32(1e-9))
    f_cpu = (used[:, 0] + need[0]) / cap[:, 0]
    f_mem = (used[:, 1] + need[1]) / cap[:, 1]
    return ((f_cpu + f_mem) / F32(2.0)).astype(F32)


def greedy_match(job_res: np.ndarray, constraint_mask: np.ndarray,
                 avail: np.ndarray, capacity: np.ndarray) -> np.ndarray:
    """Assign jobs (in rank order) one at a time to the feasible host with the
    highest bin-packing fitness; ties -> lowest host index. Returns i32[J]
    host index or -1.  Mutates nothing; works on copies."""
    job_res = np.asarray(job_res, dtype=F32)
    avail = np.asarray(avail, dtype=F32).copy()
    capacity = np.asarray(capacity, dtype=F32)
    J = job_res.shape[0]
    assign = np.full(J, -1, dtype=np.int32)
    for j in range(J):
        need = job_res[j]
        feasible = np.all(avail >= need[None, :], axis=1) & constraint_mask[j]
        if not feasible.any():
            continue
        fitness = binpack_fitness(need, avail, capacity)
        fitness = np.where(feasible, fitness, -np.inf)
        h = int(np.argmax(fitness))
        assign[j] = h
        avail[h] = avail[h] - need
    return assign


# --------------------------------------------------------------------------
# Gang all-or-nothing reduction (docs/GANG.md; the host golden for
# ops/gang.gang_reduce_kernel)
# --------------------------------------------------------------------------

def gang_reduce(assign: np.ndarray, gang_id: np.ndarray,
                gang_size: np.ndarray, gang_attr: np.ndarray,
                host_topo: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Zero out partial gangs in a match assignment.

    A gang is complete when (a) at least ``gang_size[g]`` of its members
    hold assignments and (b), for gangs with a topology request
    (``gang_attr[g] > 0``), every matched member landed on hosts sharing
    one known topology code.  Members of incomplete gangs are reset to
    -1 (they retry next cycle; the freed capacity is re-offered by the
    caller's refill pass).

    ``assign`` i32[J] host index or -1; ``gang_id`` i32[J] segment id or
    -1 for non-gang rows; ``gang_size`` i32[G]; ``gang_attr`` i32[G]
    row into ``host_topo`` (0 = no topology requirement); ``host_topo``
    i32[A, H] topology code per host (-1 = attribute absent).

    Returns (assign', dropped bool[J]).
    """
    assign = np.asarray(assign, dtype=np.int32)
    gang_id = np.asarray(gang_id, dtype=np.int32)
    G = int(gang_size.shape[0])
    member = gang_id >= 0
    matched = member & (assign >= 0)
    cnt = np.bincount(gang_id[matched], minlength=G)[:G]
    complete = cnt >= np.asarray(gang_size, dtype=np.int64)
    topo_required = np.asarray(gang_attr) > 0
    if topo_required.any():
        for g in np.flatnonzero(topo_required):
            rows = matched & (gang_id == g)
            if not rows.any():
                continue
            codes = host_topo[int(gang_attr[g])][assign[rows]]
            if codes.min() < 0 or codes.min() != codes.max():
                complete[g] = False
    dropped = matched & ~complete[np.where(member, gang_id, 0)]
    out = np.where(dropped, np.int32(-1), assign)
    return out, dropped


# --------------------------------------------------------------------------
# Preemption decision (reference: rebalancer.clj compute-preemption-decision
# :320-407)
# --------------------------------------------------------------------------

def preemption_decision(task_dru: np.ndarray, task_res: np.ndarray,
                        task_host: np.ndarray, eligible: np.ndarray,
                        spare: np.ndarray, host_ok: np.ndarray,
                        demand: np.ndarray) -> Optional[Tuple[int, List[int], float]]:
    """Pick (host, victim task indices, decision dru) maximizing the minimum
    DRU among preempted tasks; spare-only solutions score +inf ("MAX_VALUE"
    rows in the reference).  Tasks must be pre-filtered by the caller's
    eligibility rules (safe-dru-threshold, min-dru-diff, quota/self) and are
    scanned per host in descending-DRU order; ties -> lowest host index.
    """
    task_dru = np.asarray(task_dru, dtype=F32)
    task_res = np.asarray(task_res, dtype=F32)
    spare = np.asarray(spare, dtype=F32)
    demand = np.asarray(demand, dtype=F32)
    H = spare.shape[0]
    best: Optional[Tuple[float, int, List[int]]] = None  # (score, host, victims)

    def consider(score: float, host: int, victims: List[int]):
        nonlocal best
        if best is None or score > best[0] or (score == best[0] and host < best[1]):
            best = (score, host, victims)

    for h in range(H):
        if not host_ok[h]:
            continue
        if np.all(spare[h] >= demand):
            consider(np.inf, h, [])
            continue
        idx = [t for t in np.nonzero((task_host == h) & eligible)[0]]
        idx.sort(key=lambda t: (-task_dru[t], t))
        freed = spare[h].copy()
        for k, t in enumerate(idx):
            freed = freed + task_res[t]
            if np.all(freed >= demand):
                consider(float(task_dru[t]), h, [int(x) for x in idx[:k + 1]])
                break
    if best is None:
        return None
    score, host, victims = best
    return host, victims, score


def apply_pack_delta(rows_buf: np.ndarray, flags_buf: np.ndarray,
                     idx: np.ndarray, rows_vals: np.ndarray,
                     flags_vals: np.ndarray):
    """Host reference of ops/delta.PackDeltaApplier.apply: scatter the
    delta batch (flat positions; entries == buffer size are padding and
    dropped) into copies of the resident rows/flags buffers."""
    n_flat = rows_buf.size
    keep = np.asarray(idx) < n_flat
    idx = np.asarray(idx)[keep]
    rows = np.array(rows_buf, copy=True)
    flags = np.array(flags_buf, copy=True)
    rows.reshape(-1)[idx] = np.asarray(rows_vals)[keep]
    flags.reshape(-1)[idx] = np.asarray(flags_vals)[keep]
    return rows, flags
