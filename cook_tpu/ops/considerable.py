"""Device-side considerable-job selection: the match-time admission filters
of the reference, computed in rank order on device.

The reference walks the ranked queue one job at a time
(pending-jobs->considerable-jobs, reference:
scheduler/src/cook/scheduler/scheduler.clj:729 + the quota/rate-limit
accumulators in tools.clj:899-970) accumulating per-user usage and
launch-rate tokens.  Here the same admission logic is a handful of
segmented prefix sums so the fused pool cycle can go rank -> considerable
-> match without a host round trip:

  1. pool quota / quota-group caps over the ranked pending prefix
     (filter-based-on-quota scheduler.clj:2134; the cumulative-usage
     accumulator includes filtered jobs, tools.clj:917-933);
  2. per-user quota over running + earlier-queued jobs (accumulator
     includes jobs that fail the check, tools.clj:899-915);
  3. per-user launch-rate token caps — a user's k-th quota-passing job is
     admitted iff k <= floor(tokens) (filter-pending-jobs-for-ratelimit
     tools.clj:940-970);
  4. host-computed launch-plugin verdicts (launch_ok) — the escape hatch
     for arbitrary host predicates (plugins/launch.clj:140);
  5. the head-of-queue backoff cap: at most ``num_considerable`` admitted
     jobs per cycle (scheduler.clj:1613-1651), passed as a traced scalar so
     backoff changes never recompile.

Users are NOT contiguous in rank order, so per-user prefix sums go through
one lexsort to user-major order and back (O(T log T) on device, no host
work).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from . import scan as scanlib


class ConsiderableResult(NamedTuple):
    match_valid: jax.Array   # bool[T] admitted for matching (rank order)
    queue_ok: jax.Array      # bool[T] survived pool/group quota + enqueue
    accepted: jax.Array      # bool[T] admitted before the cap (rank order)


def per_user_prefix(user: jax.Array, x: jax.Array,
                    include: jax.Array) -> jax.Array:
    """Inclusive per-user prefix sum of ``x`` over rows where ``include``,
    evaluated in the CURRENT row order (rows of one user need not be
    contiguous).  Returns an array aligned with the input order."""
    T = user.shape[0]
    pos = jnp.arange(T, dtype=jnp.int32)
    perm = jnp.lexsort((pos, user))  # user-major, stable in current order
    inc = include[perm]
    vals = x[perm] * inc.astype(x.dtype).reshape((T,) + (1,) * (x.ndim - 1))
    u_sorted = user[perm]
    first = jnp.concatenate(
        [jnp.ones((1,), dtype=bool), u_sorted[1:] != u_sorted[:-1]])
    cum = scanlib.segmented_cumsum(vals, first)
    out = jnp.zeros_like(cum).at[perm].set(cum)
    return out


def considerable_body(usage_r: jax.Array, quota_r: jax.Array,
                      user_r: jax.Array, run_base_r: jax.Array,
                      tokens_r: jax.Array, launch_ok_r: jax.Array,
                      enqueue_ok_r: jax.Array, rankable_r: jax.Array,
                      pool_base: jax.Array, pool_quota: jax.Array,
                      group_base: jax.Array, group_quota: jax.Array,
                      num_considerable: jax.Array) -> ConsiderableResult:
    """All inputs are in RANK order (suffix _r).

    usage_r      f32[T, 4] per-task (cpus, mem, gpus, count)
    quota_r      f32[T, 4] the task's user's quota
    user_r       i32[T]    user rank ids
    run_base_r   f32[T, 4] the task's user's running usage in this pool
    tokens_r     f32[T]    the user's launch-rate token budget (inf = off)
    launch_ok_r  bool[T]   host plugin verdicts
    enqueue_ok_r bool[T]   False for host-stifled (offensive) jobs
    rankable_r   bool[T]   pending tasks that survived over-quota limiting
    pool_base    f32[4]    pool running usage;  pool_quota f32[4] (inf=off)
    group_base   f32[4]    quota-group running usage; group_quota f32[4]
    num_considerable i32[] backoff cap on admitted jobs
    """
    # 1. pool + quota-group caps over the ranked pending prefix; the
    #    cumulative accumulator includes every rankable job (kept or not)
    pend_usage = usage_r * rankable_r[:, None]
    cum_pool = jnp.cumsum(pend_usage, axis=0)
    pq_ok = jnp.all(cum_pool + pool_base[None, :] <= pool_quota[None, :],
                    axis=-1)
    gq_ok = jnp.all(cum_pool + group_base[None, :] <= group_quota[None, :],
                    axis=-1)
    queue_ok = rankable_r & pq_ok & gq_ok & enqueue_ok_r

    # 2. per-user quota: running base + cumulative queued usage (all queued
    #    jobs accumulate, pass or fail)
    cum_user = per_user_prefix(user_r, usage_r, queue_ok)
    quota_ok = queue_ok & jnp.all(cum_user + run_base_r <= quota_r, axis=-1)

    # 3. launch-rate tokens: inclusive index among the user's quota-passing
    #    jobs must fit the token budget
    cnt = per_user_prefix(
        user_r, jnp.ones((user_r.shape[0],), dtype=jnp.float32), quota_ok)
    rl_ok = quota_ok & (cnt <= jnp.floor(tokens_r))

    # 4. + 5. plugin verdicts, then the backoff cap on admitted jobs
    accepted = rl_ok & launch_ok_r
    admitted_prefix = jnp.cumsum(accepted.astype(jnp.int32))
    match_valid = accepted & (admitted_prefix <= num_considerable)
    return ConsiderableResult(match_valid=match_valid, queue_ok=queue_ok,
                              accepted=accepted)
