"""Bucketed padding so per-cycle dynamic sizes hit a small set of compiled shapes.

Pending-job and offer counts vary every cycle; XLA requires static shapes, so
we round sizes up to geometric buckets (x2 steps) to bound recompiles
(SURVEY.md section 7 "dynamic shapes" hard part).
"""

from __future__ import annotations

MIN_BUCKET = 64


def bucket(n: int, minimum: int = MIN_BUCKET) -> int:
    """Smallest power-of-two bucket >= max(n, 1)."""
    size = minimum
    n = max(n, 1)
    while size < n:
        size *= 2
    return size


def pad_to(arr, size: int, fill=0):
    """Pad a numpy array's leading axis up to ``size`` with ``fill``."""
    import numpy as np

    if arr.shape[0] == size:
        return arr
    pad_shape = (size - arr.shape[0],) + arr.shape[1:]
    return np.concatenate([arr, np.full(pad_shape, fill, dtype=arr.dtype)], axis=0)
