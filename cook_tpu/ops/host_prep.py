"""Host-side packing: entity lists -> padded device tensors.

The control plane deals in Job/Instance entities; the kernels in padded
arrays.  This module is the boundary: pure numpy, no JAX, so it can feed
either the TPU kernels or the CPU fallback.
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as np

from .padding import bucket, pad_to
from .reference_impl import UserTasks

F32 = np.float32


def pack_rank_inputs(users: List[UserTasks],
                     shares: Dict[str, Tuple[float, float, float]],
                     quotas: Dict[str, np.ndarray],
                     pad: bool = True):
    """Build the arrays of ops.dru.RankInputs (as numpy) plus the flat
    task-id table mapping kernel positions back to tasks.

    Users are laid out contiguously, sorted by user name (matching the
    reference's deterministic ``(sort-by first)``, dru.clj:123).
    Returns (arrays dict, task_ids list).
    """
    users = sorted(users, key=lambda u: u.user)
    usage_rows, quota_rows, share_rows = [], [], []
    first_idx, user_rank, pending, task_ids = [], [], [], []
    offset = 0
    for rank, ut in enumerate(users):
        n = len(ut.task_ids)
        share = np.asarray(shares[ut.user], dtype=F32)
        quota = np.asarray(quotas[ut.user], dtype=F32)
        for i in range(n):
            usage_rows.append(ut.usage[i])
            quota_rows.append(quota)
            share_rows.append(share)
            first_idx.append(offset)
            user_rank.append(rank)
            pending.append(ut.pending[i])
            task_ids.append(ut.task_ids[i])
        offset += n

    if not task_ids:  # canonical 1-row all-padding layout
        usage_rows = [np.zeros(4, dtype=F32)]
        quota_rows = [np.full(4, np.inf, dtype=F32)]
        share_rows = [np.full(3, np.inf, dtype=F32)]
        first_idx, user_rank, pending = [0], [0], [False]
    arrays = {
        "usage": np.array(usage_rows, dtype=F32),
        "quota": np.array(quota_rows, dtype=F32),
        "shares": np.array(share_rows, dtype=F32),
        "first_idx": np.array(first_idx, dtype=np.int32),
        "user_rank": np.array(user_rank, dtype=np.int32),
        "pending": np.array(pending, dtype=bool),
        "valid": np.full(len(first_idx), bool(task_ids)),
    }
    if pad:
        size = bucket(arrays["usage"].shape[0])
        arrays["usage"] = pad_to(arrays["usage"], size)
        arrays["quota"] = pad_to(arrays["quota"], size, fill=np.inf)
        arrays["shares"] = pad_to(arrays["shares"], size, fill=np.inf)
        arrays["first_idx"] = pad_to(arrays["first_idx"], size)
        arrays["user_rank"] = pad_to(arrays["user_rank"], size,
                                     fill=np.int32(2**31 - 1))
        arrays["pending"] = pad_to(arrays["pending"], size, fill=False)
        arrays["valid"] = pad_to(arrays["valid"], size, fill=False)
    return arrays, task_ids


def pack_match_inputs(job_res: Sequence[Sequence[float]],
                      constraint_mask: np.ndarray,
                      host_avail: Sequence[Sequence[float]],
                      host_capacity: Sequence[Sequence[float]],
                      pad: bool = True):
    """Pad jobs x hosts match inputs to buckets. Padding jobs get valid=False;
    padding hosts get zero capacity (never feasible)."""
    job_res = np.asarray(job_res, dtype=F32).reshape(-1, 4)
    avail = np.asarray(host_avail, dtype=F32).reshape(-1, 4)
    capacity = np.asarray(host_capacity, dtype=F32).reshape(-1, 4)
    J, H = job_res.shape[0], avail.shape[0]
    cmask = np.asarray(constraint_mask, dtype=bool).reshape(J, H)
    valid = np.ones(J, dtype=bool)
    if pad:
        JB, HB = bucket(J), bucket(H)
        job_res = pad_to(job_res, JB)
        valid = pad_to(valid, JB, fill=False)
        avail = pad_to(avail, HB)
        capacity = pad_to(capacity, HB)
        grown = np.zeros((JB, HB), dtype=bool)
        grown[:J, :H] = cmask
        cmask = grown
    return {
        "job_res": job_res,
        "constraint_mask": cmask,
        "avail": avail,
        "capacity": capacity,
        "valid": valid,
        "num_jobs": J,
        "num_hosts": H,
    }
