"""Host-side packing: entity lists -> padded device tensors.

The control plane deals in Job/Instance entities; the kernels in padded
arrays.  This module is the boundary: pure numpy, no JAX, so it can feed
either the TPU kernels or the CPU fallback.
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as np

from .padding import bucket, pad_to
from .reference_impl import UserTasks

F32 = np.float32


def pack_rank_inputs(users: List[UserTasks],
                     shares: Dict[str, Tuple[float, float, float]],
                     quotas: Dict[str, np.ndarray],
                     pad: bool = True):
    """Build the arrays of ops.dru.RankInputs (as numpy) plus the flat
    task-id table mapping kernel positions back to tasks.

    Users are laid out contiguously, sorted by user name (matching the
    reference's deterministic ``(sort-by first)``, dru.clj:123).
    Returns (arrays dict, task_ids list).
    """
    users = sorted(users, key=lambda u: u.user)
    users = [u for u in users if len(u.task_ids)]
    if users:
        # O(users) Python, O(tasks) numpy: per-user blocks are repeated /
        # concatenated wholesale rather than appended one task at a time
        # (the round-1 per-task loop was the host-side hot spot at 1M tasks).
        counts = np.array([len(u.task_ids) for u in users], dtype=np.int64)
        total = int(counts.sum())
        starts = (np.cumsum(counts) - counts).astype(np.int32)
        usage = np.concatenate(
            [np.asarray(u.usage, dtype=F32).reshape(len(u.task_ids), -1)
             for u in users], axis=0)
        quota = np.repeat(
            np.stack([np.asarray(quotas[u.user], dtype=F32) for u in users]),
            counts, axis=0)
        share = np.repeat(
            np.stack([np.asarray(shares[u.user], dtype=F32) for u in users]),
            counts, axis=0)
        first = np.repeat(starts, counts)
        rank = np.repeat(np.arange(len(users), dtype=np.int32), counts)
        pend = np.concatenate(
            [np.asarray(u.pending, dtype=bool) for u in users])
        task_ids = [t for u in users for t in u.task_ids]
        arrays = {
            "usage": usage,
            "quota": quota,
            "shares": share,
            "first_idx": first,
            "user_rank": rank,
            "pending": pend,
            "valid": np.ones(total, dtype=bool),
        }
    else:  # canonical 1-row all-padding layout
        task_ids = []
        arrays = {
            "usage": np.zeros((1, 4), dtype=F32),
            "quota": np.full((1, 4), np.inf, dtype=F32),
            "shares": np.full((1, 3), np.inf, dtype=F32),
            "first_idx": np.zeros(1, dtype=np.int32),
            "user_rank": np.zeros(1, dtype=np.int32),
            "pending": np.zeros(1, dtype=bool),
            "valid": np.zeros(1, dtype=bool),
        }
    if pad:
        arrays = pad_rank_arrays(arrays)
    return arrays, task_ids


def pad_rank_arrays(arrays: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    """Pad unpadded RankInputs columns to the bucketed size (shared by the
    entity packer above and the columnar-index fast path)."""
    arrays = dict(arrays)
    size = bucket(arrays["usage"].shape[0])
    arrays["usage"] = pad_to(arrays["usage"], size)
    arrays["quota"] = pad_to(arrays["quota"], size, fill=np.inf)
    arrays["shares"] = pad_to(arrays["shares"], size, fill=np.inf)
    arrays["first_idx"] = pad_to(arrays["first_idx"], size)
    arrays["user_rank"] = pad_to(arrays["user_rank"], size,
                                 fill=np.int32(2**31 - 1))
    arrays["pending"] = pad_to(arrays["pending"], size, fill=False)
    arrays["valid"] = pad_to(arrays["valid"], size, fill=False)
    return arrays


def pack_match_inputs(job_res: Sequence[Sequence[float]],
                      constraint_mask: np.ndarray,
                      host_avail: Sequence[Sequence[float]],
                      host_capacity: Sequence[Sequence[float]],
                      pad: bool = True):
    """Pad jobs x hosts match inputs to buckets. Padding jobs get valid=False;
    padding hosts get zero capacity (never feasible)."""
    job_res = np.asarray(job_res, dtype=F32).reshape(-1, 4)
    avail = np.asarray(host_avail, dtype=F32).reshape(-1, 4)
    capacity = np.asarray(host_capacity, dtype=F32).reshape(-1, 4)
    J, H = job_res.shape[0], avail.shape[0]
    cmask = np.asarray(constraint_mask, dtype=bool).reshape(J, H)
    valid = np.ones(J, dtype=bool)
    if pad:
        JB, HB = bucket(J), bucket(H)
        job_res = pad_to(job_res, JB)
        valid = pad_to(valid, JB, fill=False)
        avail = pad_to(avail, HB)
        capacity = pad_to(capacity, HB)
        grown = np.zeros((JB, HB), dtype=bool)
        grown[:J, :H] = cmask
        cmask = grown
    return {
        "job_res": job_res,
        "constraint_mask": cmask,
        "avail": avail,
        "capacity": capacity,
        "valid": valid,
        "num_jobs": J,
        "num_hosts": H,
    }
