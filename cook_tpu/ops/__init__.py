from .dru import (  # noqa: F401
    CompactRankInputs,
    RankInputs,
    RankResult,
    pool_quota_mask,
    rank_kernel,
    rank_kernel_compact,
    segment_cumsum,
    user_quota_mask,
)
from .match import (  # noqa: F401
    MatchInputs,
    auction_match_kernel,
    greedy_match_kernel,
    multipass_match_kernel,
    waterfill_match_kernel,
)
from .gang import (  # noqa: F401
    GangPack,
    GangStats,
    apply_gang_cycle,
    build_gang_pack,
    gang_reduce_kernel,
)
from .padding import bucket, pad_to  # noqa: F401
from .rebalance import (  # noqa: F401
    RebalanceDecision,
    RebalanceInputs,
    preemption_kernel,
)
from .scan import segmented_cumsum  # noqa: F401
from . import host_prep, reference_impl, telemetry  # noqa: F401
