"""JAX-level device telemetry: recompile counters, transfer byte counters,
device sync-wait accounting.

Three legs, all feeding the metrics registry AND the active cycle's
flight record (cook_tpu/utils/flight.py) so a recompile storm or transfer
regression is attributed to the exact cycle whose p99 it blew:

* :func:`instrument_jit` wraps a jitted kernel entry point; each call
  compares the jit cache size before/after, so a tracing/compilation
  (shape change, new static arg) increments
  ``cook_jit_compile_total{kernel=...}``, tags the enclosing tracing span,
  and lands on the owning CycleRecord.  Every kernel in cook_tpu/ops and
  the fused pool-cycle executable are wrapped at definition site.

* :func:`count_transfer` / :func:`sync_wait` are called by the dispatch
  paths (sched/fused.py staging + fetch, sched/matcher.py kernel runs)
  around ``device_put``/``copy_to_host_async``-style boundaries:
  ``cook_device_transfer_bytes_total{direction=h2d|d2h}`` plus
  ``cook_sync_wait_seconds`` for time spent blocked on the device.

* :func:`install_jax_monitoring` (opt-in, COOK_JAX_MONITORING=1 or an
  explicit call) forwards ``jax.monitoring`` events into
  ``cook_jax_event_total{event=...}`` — the firehose view when the
  per-kernel counters aren't enough.
"""

from __future__ import annotations

import functools
import os
import sys
import time
from contextlib import contextmanager
from typing import Any, Optional

from ..utils import tracing
from ..utils.flight import recorder
from ..utils.metrics import registry


def _on_compile(kernel: str, n: int) -> None:
    registry.counter_inc("cook_jit_compile", float(n), {"kernel": kernel})
    recorder.note_recompile(kernel, n)
    sp = tracing.tracer.current()
    if sp is not None:
        sp.set_tag("recompiles", int(sp.tags.get("recompiles", 0)) + n)
        sp.set_tag("recompiled_kernel", kernel)


class InstrumentedJit:
    """Transparent wrapper over a jitted callable that detects cache
    growth (= a fresh trace+compile) per call.  Attribute access (lower,
    _cache_size, static argname plumbing) forwards to the wrapped fn."""

    def __init__(self, kernel: str, fn):
        self._kernel = kernel
        self._fn = fn
        try:
            functools.update_wrapper(self, fn, updated=())
        except Exception:  # jit objects without full wrapper attrs
            pass

    def __call__(self, *args, **kwargs):
        fn = self._fn
        before: Optional[int]
        try:
            before = fn._cache_size()
        except Exception:
            before = None
        out = fn(*args, **kwargs)
        # every SUCCESSFUL call through an instrumented entry point is
        # one device kernel dispatch: the per-cycle launch count
        # (ISSUE 14) falls out of the wrapper every kernel already
        # passes through.  Counted after the call — a dispatch that
        # raises (Mosaic lowering gap, injected fault) never launched,
        # and charging it would double-count against its fallback
        registry.counter_inc("cook_kernel_launches", 1.0,
                             {"kernel": self._kernel})
        recorder.note_kernel_launch(self._kernel)
        if before is not None:
            try:
                after = fn._cache_size()
            except Exception:
                after = before
            if after > before:
                _on_compile(self._kernel, after - before)
        return out

    def __getattr__(self, name: str) -> Any:
        return getattr(self.__dict__["_fn"], name)


def instrument_jit(kernel: str, fn) -> InstrumentedJit:
    """Wrap a jitted entry point with per-kernel compile counting."""
    return InstrumentedJit(kernel, fn)


def count_transfer(direction: str, nbytes: int) -> None:
    """Record ``nbytes`` crossing the host<->device boundary
    (direction: "h2d" or "d2h")."""
    if nbytes:
        registry.counter_inc("cook_device_transfer_bytes", float(nbytes),
                             {"direction": direction})
        recorder.note_transfer(direction, nbytes)


@contextmanager
def sync_wait(kind: str = "fetch"):
    """Time a block that waits on the device (device_get / block_until_
    ready): observed on ``cook_sync_wait_seconds{kind=}`` and summed into
    the cycle record's sync_wait_ms."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        registry.observe("cook_sync_wait_seconds", dt, {"kind": kind})
        recorder.note_sync_wait(dt)


def profile_upload(stage_ms: float, inp) -> None:
    """COOK_PROFILE_UPLOAD=1 debug probe for the dispatch path: block
    until the staged inputs land on device and print stage/upload times.
    Lives here so the hot loop in sched/fused.py carries one call, not a
    conditional-import block."""
    if not os.environ.get("COOK_PROFILE_UPLOAD"):
        return
    import jax
    t0 = time.perf_counter()
    jax.block_until_ready(list(inp))
    nbytes = sum(getattr(a, "nbytes", 0) for a in inp)
    print(f"[profile] stage={stage_ms}ms upload="
          f"{(time.perf_counter() - t0) * 1e3:.0f}ms "
          f"({nbytes / 1e6:.1f}MB)", file=sys.stderr)


def enable_compilation_cache(path: str) -> bool:
    """Point JAX's persistent compilation cache at ``path`` (created if
    missing) with no minimum-compile-time floor, so fused-cycle
    executables survive process restarts: a failover or rolling restart
    re-traces but never re-compiles.  Returns True when the cache is
    active; False (never raises) when this jax build lacks the knobs —
    the scheduler must still boot on such builds, just without the
    cache."""
    if not path:
        return False
    try:
        import jax
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        try:
            # compile-once-per-fleet beats the write-amplification guard
            # for a scheduler whose kernel set is small and stable
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              0.0)
        except Exception:
            pass  # older knob name / absent: dir alone still caches
        try:
            jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                              -1)
        except Exception:
            pass
    except Exception:
        return False
    return True


_monitoring_installed = False


def install_jax_monitoring() -> bool:
    """Forward jax.monitoring events into the metrics registry.  Opt-in
    (global listener, so tests and embedders choose); returns True when
    the listeners are installed."""
    global _monitoring_installed
    if _monitoring_installed:
        return True
    try:
        from jax import monitoring
    except Exception:  # pragma: no cover - jax without monitoring
        return False
    monitoring.register_event_listener(
        lambda event, **kw: registry.counter_inc(
            "cook_jax_event", 1.0, {"event": event}))
    monitoring.register_event_duration_secs_listener(
        lambda event, duration, **kw: registry.observe(
            "cook_jax_event_duration_seconds", duration, {"event": event}))
    _monitoring_installed = True
    return True


if os.environ.get("COOK_JAX_MONITORING"):  # pragma: no cover - env opt-in
    install_jax_monitoring()
