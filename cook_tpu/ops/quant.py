"""Quantized compact wire form: narrow-dtype codecs for the fused
cycle's per-cycle h2d arrays, negotiated per pool group (ISSUE 14).

The compact wire (parallel/sharded.CompactPoolCycleInputs) already moved
everything derivable onto the device; what still ships every cycle is
the sorted row permutation (i32), the flags byte, and the per-host
avail/capacity stacks (f32).  At the 100k x 5k design point that is
~940 KB per full upload.  This module halves it again by narrowing each
field to the smallest dtype its DOMAIN admits this cycle — and only
when the round trip is EXACT:

* ``rows`` — delta-from-position coding.  The columnar index emits the
  base rows in user-sorted order and compaction rebuilds them sorted, so
  the sorted permutation is near-identity and ``rows[t] - t`` fits int8
  or int16 for the steady-state majority.  Negotiation picks the
  narrowest width that holds EVERY delta (int8 -> int16 -> wide i32);
  the device reconstructs ``rows = delta + iota`` losslessly.
* ``avail``/``capacity`` — fixed-point uint16 with a per-wire
  power-of-two scale, accepted only when ``decode(encode(x)) == x``
  bit-for-bit for every element (host resources are overwhelmingly
  small integers / power-of-two fractions); any non-representable value
  falls back to the wide f32 form for the whole field.
* ``host_gpu``/``host_blocked`` — bitpacked, 8 hosts per byte.
* ``flags`` stays the u8 it already is.

Every codec is negotiated independently and the negotiated wire carries
its own codec tags, so "quantized" NEVER means "approximate": the
property ``expand(quantize(x)) == expand(x)`` holds wherever a narrow
form was chosen, and an overflowing domain is shipped wide with an
explicit fallback count (``cook_quant_wide_fallback_total{field}``).

The delta feed's scatter path (ops/delta.PackDeltaApplier) reuses the
rows codec for its value payload: scatter values are coded as deltas
against their own target position, so a steady-state scatter row costs
idx + 1-2 value bytes + 1 flag byte instead of 9.
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np

from ..utils.metrics import registry

# rows codec tags (static in the decode executable's jit key)
ROWS_WIDE = 0    # i32 absolute rows, no transform
ROWS_I16 = 1     # int16 delta vs position
ROWS_I8 = 2      # int8 delta vs position

_ROWS_DTYPE = {ROWS_WIDE: np.int32, ROWS_I16: np.int16, ROWS_I8: np.int8}

# fixed-point scales tried for the resource stacks, finest first: the
# finest exact scale wins; non-power-of-two values (or magnitudes past
# 65535 * scale) force the wide form
_FIXED_SCALES = (0.125, 0.25, 0.5, 1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0)


class QuantizedRows(NamedTuple):
    """One negotiated rows wire: ``codec`` is a ROWS_* tag, ``data`` the
    narrow (or wide) array.  Decode: ``data.astype(i32) + iota`` for the
    delta codecs, identity for wide."""

    codec: int
    data: np.ndarray

    @property
    def nbytes(self) -> int:
        return self.data.nbytes


class QuantizedFixed(NamedTuple):
    """A fixed-point u16 wire (``scale`` is a per-trailing-column tuple
    of power-of-two scales) or the wide f32 fallback (``scale`` == 0.0,
    ``data`` is the original array).  Per-COLUMN scales matter: one
    resource axis mixes cpus (sub-integer granularity) with disk MB
    (magnitudes past 65535), and a single shared scale would force the
    whole field wide."""

    scale: object   # tuple of per-column floats, or 0.0 = wide
    data: np.ndarray

    @property
    def nbytes(self) -> int:
        return self.data.nbytes


def note_wide(field: str) -> None:
    """Count one lossless-narrow negotiation that fell back to the wide
    form (the contract: quantization is lossless-or-wide, and wide is
    always COUNTED so an operator can see it never engaging)."""
    registry.counter_inc("cook_quant_wide_fallback", labels={"field": field})


_note_wide = note_wide


def quantize_rows(rows: np.ndarray) -> QuantizedRows:
    """Negotiate the narrowest exact delta coding for a rows permutation
    (any leading batch shape; position runs along the LAST axis)."""
    rows = np.asarray(rows, dtype=np.int64)
    iota = np.arange(rows.shape[-1], dtype=np.int64)
    delta = rows - iota
    lo, hi = (int(delta.min()), int(delta.max())) if delta.size else (0, 0)
    if -128 <= lo and hi <= 127:
        return QuantizedRows(ROWS_I8, delta.astype(np.int8))
    if -32768 <= lo and hi <= 32767:
        return QuantizedRows(ROWS_I16, delta.astype(np.int16))
    _note_wide("rows")
    return QuantizedRows(ROWS_WIDE, rows.astype(np.int32))


def expand_rows(q: QuantizedRows) -> np.ndarray:
    """Host-side decode (the device twin is :func:`expand_rows_device`)."""
    if q.codec == ROWS_WIDE:
        return np.asarray(q.data, dtype=np.int32)
    iota = np.arange(q.data.shape[-1], dtype=np.int32)
    return q.data.astype(np.int32) + iota


def expand_rows_device(codec: int, data, T: int):
    """Device-side rows decode (jnp; runs inside the megakernel's expand
    stage or a pre-cycle decode).  ``codec`` must be static."""
    import jax.numpy as jnp
    if codec == ROWS_WIDE:
        return data.astype(jnp.int32)
    iota = jnp.arange(T, dtype=jnp.int32)
    return data.astype(jnp.int32) + iota


def quantize_fixed(x: np.ndarray, field: str,
                   prefer=None) -> QuantizedFixed:
    """Negotiate an exact u16 fixed-point coding for a non-negative f32
    array (scale chosen PER trailing column), or fall back wide.
    Exactness is CHECKED, not assumed: the coding is accepted only when
    every element survives the round trip bit-for-bit.

    ``prefer`` is a previously negotiated scale tuple: when it still
    round-trips this cycle's values it is reused verbatim, keeping the
    scale tuple — a STATIC jit key of the consuming kernel — sticky
    across cycles instead of flapping to the finest exact scale as
    values shift (each flap would be a full kernel retrace)."""
    x = np.asarray(x, dtype=np.float32)
    finite = np.isfinite(x)
    if not finite.all() or (x < 0).any() or x.ndim == 0:
        _note_wide(field)
        return QuantizedFixed(0.0, x)
    if isinstance(prefer, tuple) and len(prefer) == x.shape[-1]:
        sv = np.asarray(prefer, dtype=np.float32)
        q = np.round(x / sv)
        if (q <= 65535).all() and \
                (q.astype(np.float32) * sv == x).all():
            return QuantizedFixed(tuple(prefer), q.astype(np.uint16))
    scales = []
    for c in range(x.shape[-1]):
        col = x[..., c]
        for s in _FIXED_SCALES:
            q = np.round(col / np.float32(s))
            if (q <= 65535).all() and \
                    (q.astype(np.float32) * np.float32(s) == col).all():
                scales.append(float(s))
                break
        else:
            _note_wide(field)
            return QuantizedFixed(0.0, x)
    sv = np.asarray(scales, dtype=np.float32)
    return QuantizedFixed(tuple(scales),
                          np.round(x / sv).astype(np.uint16))


def expand_fixed(q: QuantizedFixed) -> np.ndarray:
    if q.scale == 0.0:
        return np.asarray(q.data, dtype=np.float32)
    return q.data.astype(np.float32) \
        * np.asarray(q.scale, dtype=np.float32)


def expand_fixed_device(scale, data):
    """Device-side fixed-point decode (``scale`` static: a per-column
    tuple, or 0.0 = wide passthrough).  Column-wise scalar multiplies,
    not one scale vector: a jnp constant array would be CAPTURED by the
    pallas kernel that calls this (the pitfall the
    pallas-module-constant lint pass polices at module level)."""
    import jax.numpy as jnp
    if scale == 0.0:
        return data
    f = data.astype(jnp.float32)
    return jnp.stack([f[..., c] * float(s) for c, s in enumerate(scale)],
                     axis=-1)


def pack_bits(x: np.ndarray) -> np.ndarray:
    """Bitpack a bool array along its last axis (8 entries/byte)."""
    return np.packbits(np.asarray(x, dtype=bool), axis=-1)


def unpack_bits(packed: np.ndarray, n: int) -> np.ndarray:
    return np.unpackbits(packed, axis=-1, count=n).astype(bool)


def unpack_bits_device(packed, n: int):
    """Device-side bit unpack along the last axis (``n`` static).  Shift
    math stays in int32 — Mosaic prefers wide integer vectors and the
    result is a bool mask either way."""
    import jax.numpy as jnp
    p32 = packed.astype(jnp.int32)
    shifts = jnp.arange(8, dtype=jnp.int32)
    bits = (p32[..., :, None] >> (7 - shifts)) & 1
    flat = bits.reshape(packed.shape[:-1] + (packed.shape[-1] * 8,))
    return flat[..., :n] != 0


def compact_wire_nbytes(rows: np.ndarray, flags: np.ndarray,
                        avail: np.ndarray, capacity: np.ndarray,
                        host_gpu: np.ndarray,
                        host_blocked: np.ndarray) -> int:
    """The unquantized compact wire cost of the same fields — the bench's
    apples-to-apples denominator for the quantization ratio."""
    return (np.asarray(rows).astype(np.int32).nbytes
            + np.asarray(flags).astype(np.uint8).nbytes
            + np.asarray(avail).astype(np.float32).nbytes
            + np.asarray(capacity).astype(np.float32).nbytes
            + np.asarray(host_gpu).astype(bool).nbytes
            + np.asarray(host_blocked).astype(bool).nbytes)
