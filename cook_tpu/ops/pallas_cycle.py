"""Pallas fused-cycle MEGAKERNEL: rank -> admission -> match ->
gang-reduce in ONE kernel launch, with every [T]-sized intermediate
resident in VMEM (ISSUE 14; ROADMAP item 5).

The fused XLA driver (parallel/sharded.make_pool_cycle) already runs the
whole cycle as one jit, but XLA still materializes the stage boundaries
— ranked order, admission bits, the compacted candidate block, match
assignments, gang gates — as [T]-sized HBM buffers between fusion
islands, and the split driver pays a full launch + HBM round trip per
stage.  This kernel applies the FlashAttention-era recipe to scheduling:
one ``pl.pallas_call`` whose per-pool program keeps the entire
intermediate chain in VMEM scratch/registers, so HBM traffic is
O(wire inputs + compact outputs) and the launch count per cycle is 1.

Stage structure (grid = (2, P); the phase axis is OUTERMOST, so every
pool's phase-0 program runs before any phase-1 program — VMEM scratch
persists across the sequential TPU grid exactly as pallas_match's
running top-K does):

  phase 0  per-pool RUNNING usage -> ``pool_base`` scratch (the
           cross-pool quota-group reconciliation the fused cycle does
           with an all_gather; one scratch row per pool replaces it on
           the single-mesh path this kernel serves);
  phase 1  wire decode (quantized codecs, ops/quant.py) -> DRU
           cumulative-share rank (ops/dru.rank_body) -> considerable
           admission (ops/considerable.considerable_body) -> compacted
           structured-mask match (the pallas_match mask-composition
           recipe: per-row masks are composed IN VMEM for only the
           admitted C rows, absorbed here as the middle stage) -> greedy
           assignment -> compact outputs -> gang ``gang_min``-gated
           segment reduction (ops/gang reduce math) — all without
           leaving the kernel.

BIT-PARITY is the contract, not a goal: phase 1 calls the SAME module
functions the fused XLA driver vmaps (``_pool_cycle_structured`` and
friends), so the decision math has one home and the parity matrix in
tests/test_megakernel.py asserts byte-identical launch decisions across
megakernel / fused / split / depth-2 pipelined drivers, rigid and
elastic gangs, compact and quantized wire.

On CPU the kernel runs in interpret mode (tier-1 honest, like
ops/pallas_match.py); on TPU a Mosaic lowering failure degrades to the
fused XLA driver with ``cook_kernel_fallback_total{kernel=
pallas.megacycle}`` — the cycle never dies (docs/ROBUSTNESS.md).

VMEM budget per pool program (docs/PERFORMANCE.md kernel registry):
rows/flags/order/assign-chain ~ 6 x 4B x T, the structured mask
composition C x H x 1B, host stacks 2 x H x 16B, base gathers T x 20B —
~13 MB at T=128Ki, C=1Ki, H=8Ki, inside a v5e core's ~16 MB less the
double-buffered wire blocks.  Oversize shapes must fall back to the
fused XLA driver (the dispatch wrapper in sched/fused.py does).
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import quant, telemetry

_BIG = 2 ** 30  # python literal: module-level jnp consts would be captured


class MegaCycleWire(NamedTuple):
    """Device-ready megakernel inputs: the compact wire with each
    quantizable field carried in its NEGOTIATED form (ops/quant.py; the
    codec tags ride separately as static args so one executable serves
    each negotiated shape).  ``rows``/``flags`` may be the
    device-resident buffers (sched/fused._ResidentPack) — then they cost
    zero h2d this cycle and ``rows_codec`` is wide."""

    rows: jax.Array        # [P, T] i32 | i16 | i8 (codec-tagged)
    flags: jax.Array       # u8[P, T]
    res_base: jax.Array    # f32[N, 4] device-resident mirror
    disk_base: jax.Array   # f32[N]
    tokens_u: jax.Array    # f32[P, U]
    shares_u: jax.Array    # f32[P, U, 3]
    quota_u: jax.Array     # f32[P, U, 4]
    num_considerable: jax.Array  # i32[P]
    pool_quota: jax.Array  # f32[P, 4]
    group_quota: jax.Array  # f32[P, 4]
    group_id: jax.Array    # i32[P]
    host_bits: jax.Array   # u8[P, 2, ceil(H/8)] bitpacked (gpu, blocked)
    exc_rows: jax.Array    # i32[P, E]
    exc_mask: jax.Array    # bool[P, E, H]
    avail: jax.Array       # [P, H, 4] f32 | u16 (scale-tagged)
    capacity: jax.Array    # [P, H, 4] f32 | u16
    gang_id: jax.Array     # i32[P, T] sorted-position gang segment, -1
    gang_size: jax.Array   # i32[P, G] reduction threshold (gang_min)
    gang_attr: jax.Array   # i32[P, G]
    host_topo: jax.Array   # i32[P, A, H]


class MegaCycleResult(NamedTuple):
    """Everything the driver consumes per cycle, O(C + queue) on the
    fetch path like PoolCycleResult's compact outputs — plus the fused
    gang stage's verdicts so the host apply can skip its own reduction
    when the candidate set is intact."""

    queue_rows: jax.Array   # i32[P, T] (stays device-resident)
    n_queue: jax.Array      # i32[P]
    cand_row: jax.Array     # i32[P, C]
    cand_assign: jax.Array  # i32[P, C] PRE-gang assignment
    cand_qpos: jax.Array    # i32[P, C]
    cand_gang: jax.Array    # i32[P, C] POST-gang-reduction assignment
    cand_dropped: jax.Array  # i32[P, C] 1 = reduction reset this slot


def _decode_hosts(host_bits, H: int):
    gpu_blk = quant.unpack_bits_device(host_bits[0], H)
    blocked_blk = quant.unpack_bits_device(host_bits[1], H)
    return gpu_blk, blocked_blk


def _gang_reduce_candidates(cand_row, cand_assign, gang_id, gang_size,
                            gang_attr, host_topo):
    """The gang_min-gated segment reduction over the admitted candidate
    slots: map each slot to its task row's gang segment, then run the
    SHARED reduction body (ops/gang.gang_reduce_body — one home for the
    decision math, parity-asserted against reference_impl.gang_reduce).
    Padding slots (cand_row < 0) and padding gangs (unreachable size)
    touch nothing."""
    from .gang import gang_reduce_body
    valid_c = cand_row >= 0
    gid_c = jnp.where(valid_c, gang_id[jnp.maximum(cand_row, 0)], -1)
    return gang_reduce_body(cand_assign, gid_c, gang_size, gang_attr,
                            host_topo)


def _kernel(rows_ref, flags_ref, res_ref, disk_ref, tokens_ref,
            shares_ref, quota_ref, ncons_ref, pq_ref, gq_ref, gid_all_ref,
            hbits_ref, excr_ref, excm_ref, avail_ref, cap_ref,
            gangid_ref, gsize_ref, gattr_ref, gtopo_ref,
            qrows_ref, nq_ref, crow_ref, cassign_ref, cqpos_ref,
            cgang_ref, cdrop_ref, base_s, *, gpu_mode: bool,
            max_over_quota_jobs: int, considerable_cap: int,
            rows_codec: int, avail_scale: float, cap_scale: float,
            n_hosts: int):
    """One (phase, pool) grid step.  Phase 0 banks the pool's running
    usage in the persistent ``base_s`` scratch; phase 1 runs the whole
    fused cycle for the pool against every pool's banked base."""
    s = pl.program_id(0)
    p = pl.program_id(1)
    T = rows_ref.shape[1]
    C = crow_ref.shape[1]

    # --- wire decode (shared with phase 0's usage computation) --------
    rows = quant.expand_rows_device(rows_codec, rows_ref[...][0], T)
    flags = flags_ref[...][0]
    from .delta import FLAG_PENDING, FLAG_VALID
    pending = (flags & FLAG_PENDING) != 0
    valid = (flags & FLAG_VALID) != 0
    res_base = res_ref[...]
    usage = res_base[rows]                                  # [T, 4]

    @pl.when(s == 0)
    def _bank_base():
        pool_base = jnp.sum(usage * (valid & ~pending)[:, None],
                            axis=0)[:4]
        pl.store(base_s, (pl.dslice(p, 1), pl.dslice(0, 4)),
                 pool_base.reshape(1, 4))
        # neutral output writes: phase-1 programs revisit and overwrite
        qrows_ref[0, :] = jnp.zeros((T,), dtype=jnp.int32)
        nq_ref[0, :] = jnp.zeros((1,), dtype=jnp.int32)
        for ref in (crow_ref, cassign_ref, cqpos_ref, cgang_ref):
            ref[0, :] = jnp.full((C,), -1, dtype=jnp.int32)
        cdrop_ref[0, :] = jnp.zeros((C,), dtype=jnp.int32)

    @pl.when(s == 1)
    def _cycle():
        from .delta import FLAG_ENQUEUE_OK, FLAG_LAUNCH_OK, FLAG_USER_FIRST
        from .scan import user_segments_from_flags
        from ..parallel.sharded import _pool_cycle_structured
        disk = disk_ref[...][:, 0][rows]                    # [T]
        enqueue_ok = (flags & FLAG_ENQUEUE_OK) != 0
        launch_ok = (flags & FLAG_LAUNCH_OK) != 0
        is_first = (flags & FLAG_USER_FIRST) != 0
        job_res = jnp.concatenate(
            [usage[:, :3], disk[:, None]], axis=-1) * pending[:, None]
        user_rank, first_idx = user_segments_from_flags(is_first)
        U = tokens_ref.shape[1]
        ur = jnp.clip(user_rank, 0, U - 1)
        tokens = tokens_ref[...][0][ur]
        shares = shares_ref[...][0][ur]
        quota = quota_ref[...][0][ur]
        # exception-position list -> [T] exc_id map (slot T = dump row),
        # the expand_compact recipe per pool
        E = excr_ref.shape[1]
        exc_rows = excr_ref[...][0]
        eids = jnp.arange(E, dtype=jnp.int32)
        slot = jnp.where(exc_rows >= 0, exc_rows, T)
        exc_id = jnp.full((T + 1,), -1, dtype=jnp.int32) \
            .at[slot].set(eids, mode="drop")[:T]
        host_gpu, host_blocked = _decode_hosts(hbits_ref[...][0], n_hosts)
        avail = quant.expand_fixed_device(avail_scale, avail_ref[...][0])
        capacity = quant.expand_fixed_device(cap_scale, cap_ref[...][0])
        # cross-pool quota-group base off the banked phase-0 scratch —
        # the all_gather's single-mesh twin (same sum order: pool-major)
        bases = base_s[...]                                 # [P, 4]
        gid_all = gid_all_ref[...][:, 0]
        gid = gid_all[p]
        pool_base = pl.load(base_s, (pl.dslice(p, 1),
                                     pl.dslice(0, 4)))[0]
        group_base = jnp.sum(
            bases * ((gid_all == gid) & (gid >= 0))[:, None], axis=0)

        (_order, _num_ranked, _dru, _assign, _match_valid, _queue_ok,
         _accepted, _matched_usage, queue_rows, n_queue, cand_row,
         cand_assign, cand_qpos) = _pool_cycle_structured(
            usage, quota, shares, first_idx, user_rank, pending, valid,
            enqueue_ok, launch_ok, tokens, ncons_ref[...][0, 0],
            pq_ref[...][0], gq_ref[...][0], pool_base, group_base,
            job_res, host_gpu, host_blocked, exc_id, excm_ref[...][0],
            avail, capacity, gpu_mode, max_over_quota_jobs,
            considerable_cap)

        cand_gang, dropped = _gang_reduce_candidates(
            cand_row, cand_assign, gangid_ref[...][0], gsize_ref[...][0],
            gattr_ref[...][0], gtopo_ref[...][0])

        qrows_ref[0, :] = queue_rows
        nq_ref[0, :] = n_queue.astype(jnp.int32).reshape(1)
        crow_ref[0, :] = cand_row
        cassign_ref[0, :] = cand_assign
        cqpos_ref[0, :] = cand_qpos
        cgang_ref[0, :] = cand_gang
        cdrop_ref[0, :] = dropped.astype(jnp.int32)


_FNS = {}


def _megacycle_fn(*, shapes, gpu_mode: bool, max_over_quota_jobs: int,
                  considerable_cap: int, rows_codec: int,
                  avail_scale: float, cap_scale: float, n_hosts: int,
                  interpret: bool):
    """Build (and cache) the jitted single-launch cycle for one
    negotiated wire shape.  ``shapes`` is the MegaCycleWire shape/dtype
    tuple — part of the cache key like every other bucketed kernel."""
    key = (shapes, gpu_mode, max_over_quota_jobs, considerable_cap,
           rows_codec, avail_scale, cap_scale, n_hosts, interpret)
    fn = _FNS.get(key)
    if fn is not None:
        return fn
    (P, T) = shapes[0][0]
    N = shapes[2][0][0]
    U = shapes[4][0][1]
    E = shapes[12][0][1]
    H = shapes[14][0][1]
    G = shapes[17][0][1]
    A = shapes[19][0][1]
    B = shapes[11][0][2]              # bitpacked host bytes
    C = considerable_cap
    grid = (2, P)
    mem = {"memory_space": pltpu.VMEM}

    def pool_block(shape):
        """One pool's slice, same block for both phases."""
        return pl.BlockSpec((1,) + shape, lambda s, p: (p,) + (0,) * len(shape),
                            **mem)

    def full_block(shape):
        return pl.BlockSpec(shape, lambda s, p: (0,) * len(shape), **mem)

    kernel = functools.partial(
        _kernel, gpu_mode=gpu_mode,
        max_over_quota_jobs=max_over_quota_jobs,
        considerable_cap=considerable_cap, rows_codec=rows_codec,
        avail_scale=avail_scale, cap_scale=cap_scale, n_hosts=n_hosts)
    in_specs = [
        pool_block((T,)),          # rows
        pool_block((T,)),          # flags
        full_block((N, 4)),        # res_base
        full_block((N, 1)),        # disk_base (reshaped)
        pool_block((U,)),          # tokens_u
        pool_block((U, 3)),        # shares_u
        pool_block((U, 4)),        # quota_u
        pool_block((1,)),          # num_considerable (reshaped [P, 1])
        pool_block((4,)),          # pool_quota
        pool_block((4,)),          # group_quota
        full_block((P, 1)),        # group_id (reshaped; cross-pool)
        pool_block((2, B)),        # host_bits
        pool_block((E,)),          # exc_rows
        pool_block((E, H)),        # exc_mask
        pool_block((H, 4)),        # avail
        pool_block((H, 4)),        # capacity
        pool_block((T,)),          # gang_id
        pool_block((G,)),          # gang_size
        pool_block((G,)),          # gang_attr
        pool_block((A, H)),        # host_topo
    ]
    out_shape = (
        jax.ShapeDtypeStruct((P, T), jnp.int32),   # queue_rows
        jax.ShapeDtypeStruct((P, 1), jnp.int32),   # n_queue
        jax.ShapeDtypeStruct((P, C), jnp.int32),   # cand_row
        jax.ShapeDtypeStruct((P, C), jnp.int32),   # cand_assign
        jax.ShapeDtypeStruct((P, C), jnp.int32),   # cand_qpos
        jax.ShapeDtypeStruct((P, C), jnp.int32),   # cand_gang
        jax.ShapeDtypeStruct((P, C), jnp.int32),   # cand_dropped
    )
    out_specs = (
        pool_block((T,)), pool_block((1,)), pool_block((C,)),
        pool_block((C,)), pool_block((C,)), pool_block((C,)),
        pool_block((C,)),
    )
    call = pl.pallas_call(
        kernel, grid=grid, in_specs=in_specs, out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[pltpu.VMEM((P, 4), jnp.float32)],
        interpret=interpret)

    def run(wire_arrays):
        outs = call(*wire_arrays)
        return MegaCycleResult(
            queue_rows=outs[0], n_queue=outs[1][:, 0], cand_row=outs[2],
            cand_assign=outs[3], cand_qpos=outs[4], cand_gang=outs[5],
            cand_dropped=outs[6])

    fn = telemetry.instrument_jit("pallas.megacycle", jax.jit(run))
    _FNS[key] = fn
    return fn


def megacycle(wire: MegaCycleWire, *, gpu_mode: bool = False,
              max_over_quota_jobs: int = 100,
              considerable_cap: int = 1024,
              rows_codec: int = quant.ROWS_WIDE,
              avail_scale: float = 0.0, cap_scale: float = 0.0,
              interpret: Optional[bool] = None) -> MegaCycleResult:
    """Dispatch one fused-cycle megakernel launch.

    ``wire`` fields may be numpy or device arrays; the wrapper reshapes
    the 1-D scalars ([P] -> [P, 1], disk [N] -> [N, 1]) for Pallas
    block-shape friendliness.  Codec tags are static — the negotiation
    in sched/fused staging picks them and the executable is cached per
    (shape, codec) exactly like every other bucketed kernel."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    cap = int(min(considerable_cap, wire.rows.shape[1]))
    arrays = (
        wire.rows, wire.flags, wire.res_base,
        jnp.asarray(wire.disk_base).reshape(-1, 1),
        wire.tokens_u, wire.shares_u, wire.quota_u,
        jnp.asarray(wire.num_considerable).reshape(-1, 1),
        wire.pool_quota, wire.group_quota,
        jnp.asarray(wire.group_id).reshape(-1, 1),
        wire.host_bits, wire.exc_rows, wire.exc_mask, wire.avail,
        wire.capacity, wire.gang_id, wire.gang_size, wire.gang_attr,
        wire.host_topo)
    arrays = tuple(jnp.asarray(a) for a in arrays)
    # dtypes ride the cache key alongside shapes: two negotiated wires
    # can share every shape and differ only in a narrow dtype
    shapes = tuple((tuple(a.shape), str(a.dtype)) for a in arrays)
    n_hosts = int(wire.exc_mask.shape[2])
    def _scale_key(s):  # 0.0 = wide, else a per-column tuple
        return s if isinstance(s, tuple) else float(s)

    fn = _megacycle_fn(
        shapes=shapes, gpu_mode=bool(gpu_mode),
        max_over_quota_jobs=int(max_over_quota_jobs),
        considerable_cap=cap, rows_codec=int(rows_codec),
        avail_scale=_scale_key(avail_scale),
        cap_scale=_scale_key(cap_scale),
        n_hosts=n_hosts, interpret=bool(interpret))
    return fn(arrays)


def empty_gang_wire(P: int, T: int, H: int) -> Tuple[np.ndarray, ...]:
    """The structural no-op gang wire (no members, one unreachable-size
    padding gang): lets one kernel signature serve gang-free cycles."""
    return (np.full((P, T), -1, dtype=np.int32),
            np.full((P, 8), _BIG, dtype=np.int32),
            np.zeros((P, 8), dtype=np.int32),
            np.full((P, 1, H), -1, dtype=np.int32))
