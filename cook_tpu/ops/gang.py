"""Gang all-or-nothing reduction: segment-min over each gang's member
match bits, zeroing partial gangs and re-offering their capacity within
the same cycle (docs/GANG.md).

The coscheduling pass the paper's one-job-one-host matcher lacks
(Ousterhout, ICDCS'82; Gandiva, OSDI'18 treats multi-worker ML jobs as
atomic gangs): a multi-host TPU slice job submitted as a gang group must
come up whole or not at all — a half-placed gang holds capacity while
its own peers starve behind it.

Shared by both match paths (``sched/matcher.py`` and the fused driver's
``sched/fused._apply_pool``) as a post-kernel pass over the assignment
vector:

1. **reduce** — per gang, count matched members (segment-sum of match
   bits) and, for gangs with a topology request, check every matched
   member landed in ONE topology domain (segment-min == segment-max over
   the members' host topology codes).  Incomplete gangs are reset to
   unmatched — the segment-min of a gang's match bits gates the whole
   gang;
2. **refill** — the capacity the dropped members were holding is folded
   back into host availability and the still-unmatched *group-less* jobs
   get one more greedy pass over it, so a dropped partial gang's offers
   are reusable in the SAME cycle instead of idling a full cadence tick.

The device form (:func:`gang_reduce_kernel`) is a jitted jnp segment
reduction with bucketed shapes (compile reuse like every other kernel in
``cook_tpu.ops``); :func:`cook_tpu.ops.reference_impl.gang_reduce` is
the host golden and the fallback when dispatch fails.

Topology preference (slice-local packing) happens BEFORE the match
kernel, in ``sched/constraints.build_constraint_mask``: gang members'
feasibility rows are restricted to the topology domain with the most
member-feasible hosts, so the kernel packs slice-local by construction
and this pass only enforces the invariant.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, NamedTuple, Optional, Tuple

import numpy as np

from ..utils import tracing
from ..utils.flight import recorder as _flight
from ..utils.metrics import registry
from . import reference_impl, telemetry
from .padding import bucket, pad_to

F32 = np.float32


@dataclass
class GangPack:
    """Host-side gang arrays for one match batch (built only when the
    batch actually contains gang members — the no-gang path never
    allocates any of this).

    ``gang_size`` is the REDUCTION THRESHOLD — the member count below
    which the gang drops whole.  For rigid gangs that is the declared
    ``gang_size``; for elastic gangs it is ``gang_min`` (docs/GANG.md
    elasticity: the segment reduction compares against min; members
    matched beyond min simply keep their placements as surplus).
    ``declared`` carries the full declared size for stats/explainers."""

    gang_id: np.ndarray          # i32[J], -1 = not a gang member
    gang_size: np.ndarray        # i32[G] reduction threshold (min)
    gang_attr: np.ndarray        # i32[G] row into host_topo, 0 = none
    host_topo: np.ndarray        # i32[A, H] topology code, -1 = absent
    uuids: List[str]             # gang segment -> group uuid
    topology: List[Optional[str]]  # gang segment -> requested attribute
    declared: List[int] = None   # gang segment -> declared gang_size


@dataclass
class GangStats:
    """What the reduction did, for the cycle record / explainer."""

    dropped_jobs: int = 0
    dropped_gangs: int = 0
    refilled: int = 0
    # group uuid -> {"size", "matched", "missing", "topology_blocked"}
    partial: Dict[str, Dict] = field(default_factory=dict)


def _topology_table(topo_names: List[Optional[str]], offers
                    ) -> Tuple[Dict[str, int], np.ndarray]:
    """Topology code table shared by the host pack and the megakernel
    wire: one row per distinct requested attribute, row 0 reserved for
    "no topology request" (all zeros, never read through a required
    gang).  Code assignment is offer-order deterministic, so the two
    builders can never disagree on a domain's code."""
    attrs = sorted({a for a in topo_names if a})
    attr_row = {a: i + 1 for i, a in enumerate(attrs)}
    H = max(len(offers), 1)
    host_topo = np.full((len(attrs) + 1, H), -1, dtype=np.int32)
    host_topo[0] = 0
    for a, row in attr_row.items():
        codes: Dict[str, int] = {}
        for h, o in enumerate(offers):
            v = o.attributes.get(a)
            if v is not None:
                host_topo[row, h] = codes.setdefault(v, len(codes))
    return attr_row, host_topo


class GangWire(NamedTuple):
    """Per-pool gang arrays staged PRE-dispatch for the megakernel's
    fused gang_min-gated segment reduction (ops/pallas_cycle.py): the
    same reduction inputs :class:`GangPack` carries, but keyed by TASK
    ROW (sorted pack position) instead of candidate index, because the
    kernel maps candidates to rows itself.  Satisfied elastic gangs'
    grow members are excluded exactly as in :func:`build_gang_pack`
    (gang_id -1 — the grow path places like singles)."""

    gang_id: np.ndarray   # i32[T] by sorted pack position, -1 = none
    gang_size: np.ndarray  # i32[G] reduction threshold (gang_min)
    gang_attr: np.ndarray  # i32[G] row into host_topo, 0 = none
    host_topo: np.ndarray  # i32[A, H]
    uuids: List[str]       # gang segment -> group uuid


def build_gang_wire(T: int, members_by_gang: Dict[str, List],
                    groups_ctx: Dict[str, object], offers,
                    satisfied=None) -> Optional[GangWire]:
    """Gang wire for one packed pool (sched/fused._pack_pool_columnar's
    ``members_by_gang``: group uuid -> [(task_row, job)]), or None when
    the pool stages no reducible gang members this cycle."""
    rows_by_gang = {
        guuid: members for guuid, members in members_by_gang.items()
        if getattr(groups_ctx.get(guuid), "gang", False)
        and not (satisfied and guuid in satisfied)}
    if not rows_by_gang:
        return None
    from ..state.schema import gang_bounds
    gang_id = np.full(T, -1, dtype=np.int32)
    uuids: List[str] = []
    sizes: List[int] = []
    topo_names: List[Optional[str]] = []
    for guuid, members in rows_by_gang.items():
        g = groups_ctx[guuid]
        k = len(uuids)
        uuids.append(guuid)
        sizes.append(gang_bounds(g)[0])
        topo_names.append(getattr(g, "gang_topology", None) or None)
        for row, _job in members:
            gang_id[row] = k
    attr_row, host_topo = _topology_table(topo_names, offers)
    gang_attr = np.array([attr_row.get(a, 0) if a else 0
                          for a in topo_names], dtype=np.int32)
    return GangWire(gang_id=gang_id,
                    gang_size=np.array(sizes, dtype=np.int32),
                    gang_attr=gang_attr, host_topo=host_topo,
                    uuids=uuids)


def build_gang_pack(jobs, groups: Dict[str, object], offers,
                    satisfied=None) -> Optional[GangPack]:
    """Gang arrays for a match batch, or None when no job in the batch
    belongs to a gang group (the structural no-op guard that keeps
    non-gang workloads decision-identical).

    ``satisfied`` (docs/GANG.md elasticity): group uuids of ELASTIC
    gangs already running at >= gang_min live members.  Their waiting
    members in this batch are the GROW path — they place individually
    like group-less jobs, so they are excluded from the pack entirely
    (no cohort gate to fail, no reduction to reset them)."""
    # membership scan FIRST: the gang-free majority must bail before
    # the [J] array below is allocated (a 100k-job gang-free pool would
    # otherwise pay it every match cycle just to hear "None")
    member_rows = [j for j, job in enumerate(jobs)
                   if getattr(job, "group", None)
                   and getattr(groups.get(job.group), "gang", False)
                   and not (satisfied and job.group in satisfied)]
    if not member_rows:
        return None
    from ..state.schema import gang_bounds
    J = len(jobs)
    gang_id = np.full(J, -1, dtype=np.int32)
    uuids: List[str] = []
    sizes: List[int] = []
    declared: List[int] = []
    topo_names: List[Optional[str]] = []
    seg: Dict[str, int] = {}
    for j in member_rows:
        g = groups[jobs[j].group]
        guuid = jobs[j].group
        k = seg.get(guuid)
        if k is None:
            k = seg[guuid] = len(uuids)
            uuids.append(guuid)
            # the reduction gates on the effective MINIMUM: rigid gangs
            # read min == declared size, bit-identically
            sizes.append(gang_bounds(g)[0])
            declared.append(int(getattr(g, "gang_size", 0) or 0))
            topo_names.append(getattr(g, "gang_topology", None) or None)
        gang_id[j] = k
    attr_row, host_topo = _topology_table(topo_names, offers)
    gang_attr = np.array([attr_row.get(a, 0) if a else 0
                          for a in topo_names], dtype=np.int32)
    return GangPack(gang_id=gang_id,
                    gang_size=np.array(sizes, dtype=np.int32),
                    gang_attr=gang_attr, host_topo=host_topo,
                    uuids=uuids, topology=topo_names,
                    declared=declared)


# ------------------------------------------------------------------ device
def gang_reduce_body(assign, gang_id, gang_size, gang_attr, host_topo):
    """The pure (jit/pallas-composable) gang_min-gated segment
    reduction: ONE home for the decision math, shared by the standalone
    jitted kernel below AND the megakernel's fused gang stage
    (ops/pallas_cycle.py) — the two paths must never drift (their
    parity is test-asserted against reference_impl.gang_reduce)."""
    import jax
    import jax.numpy as jnp
    G = gang_size.shape[0]
    member = gang_id >= 0
    gid = jnp.where(member, gang_id, 0)
    matched = member & (assign >= 0)
    cnt = jax.ops.segment_sum(matched.astype(jnp.int32), gid,
                              num_segments=G)
    h = jnp.clip(assign, 0, host_topo.shape[1] - 1)
    topo = host_topo[gang_attr[gid], h]
    big = jnp.int32(2 ** 30)
    tmin = jax.ops.segment_min(jnp.where(matched, topo, big),
                               gid, num_segments=G)
    tmax = jax.ops.segment_max(jnp.where(matched, topo, -big),
                               gid, num_segments=G)
    topo_ok = (gang_attr <= 0) | ((tmin == tmax) & (tmin >= 0))
    complete = (cnt >= gang_size) & topo_ok
    dropped = matched & ~complete[gid]
    return jnp.where(dropped, jnp.int32(-1), assign), dropped


_KERNEL = None


def _kernel():
    """The jitted segment reduction, built once (bucketed shapes reuse
    the compiled cycle like every other kernel here)."""
    global _KERNEL
    if _KERNEL is None:
        import jax
        _KERNEL = telemetry.instrument_jit("gang.reduce",
                                           jax.jit(gang_reduce_body))
    return _KERNEL


def gang_reduce_kernel(assign: np.ndarray, pack: GangPack
                       ) -> Tuple[np.ndarray, np.ndarray]:
    """Device segment reduction over bucketed shapes.  Padding jobs get
    gang_id -1 (never members); padding gangs get an unreachable size so
    they are incomplete with zero members and touch nothing."""
    import jax.numpy as jnp
    J = len(assign)
    Jb = bucket(J)
    Gb = bucket(len(pack.gang_size), minimum=8)
    Ab = bucket(pack.host_topo.shape[0], minimum=1)
    Hb = bucket(pack.host_topo.shape[1])
    assign_p = pad_to(np.asarray(assign, dtype=np.int32), Jb, fill=-1)
    gid_p = pad_to(pack.gang_id, Jb, fill=-1)
    size_p = pad_to(pack.gang_size, Gb, fill=2 ** 30)
    attr_p = pad_to(pack.gang_attr, Gb, fill=0)
    topo_p = np.full((Ab, Hb), -1, dtype=np.int32)
    topo_p[:pack.host_topo.shape[0], :pack.host_topo.shape[1]] = \
        pack.host_topo
    out, dropped = _kernel()(
        jnp.asarray(assign_p), jnp.asarray(gid_p), jnp.asarray(size_p),
        jnp.asarray(attr_p), jnp.asarray(topo_p))
    with telemetry.sync_wait("gang.reduce"):
        out_np = np.asarray(out)[:J]
        dropped_np = np.asarray(dropped)[:J]
    return out_np, dropped_np


# ------------------------------------------------------------------- cycle
def apply_gang_cycle(jobs, assign: np.ndarray, offers,
                     groups: Dict[str, object], *,
                     job_res: Optional[np.ndarray] = None,
                     cmask_fn: Optional[Callable[[], np.ndarray]] = None,
                     avail: Optional[np.ndarray] = None,
                     capacity: Optional[np.ndarray] = None,
                     device: bool = False,
                     refill_ok: Optional[np.ndarray] = None,
                     audit_trail=None,
                     audit_pool: Optional[str] = None,
                     satisfied=None,
                     precomputed: Optional[Tuple[np.ndarray,
                                                 np.ndarray]] = None,
                     ) -> Tuple[np.ndarray, Optional[GangStats]]:
    """The full per-cycle gang pass: reduce partial gangs to nothing and
    refill the freed capacity with still-unmatched group-less jobs.

    Structural no-op (returns ``assign`` unchanged, stats None) when the
    batch has no gang members — non-gang workloads stay
    decision-identical.  ``cmask_fn``/``avail``/``capacity`` feed the
    refill pass and may be omitted to skip it (the caller then re-offers
    freed capacity next cycle instead).

    ``satisfied``: group uuids of elastic gangs already running at >=
    gang_min — their waiting members bypass the reduction (grow path)
    and join the refill pool like group-less jobs (docs/GANG.md
    elasticity).

    ``precomputed``: an ``(out, dropped)`` pair the megakernel's fused
    gang stage already reduced on device (ops/pallas_cycle.py), aligned
    with ``jobs``.  The reduction is skipped — it would recompute the
    identical result (same math, parity-asserted) — while the rescue /
    refill passes and stats run unchanged.  Callers pass it ONLY when
    the candidate set the kernel saw is intact (no vanished jobs, no
    reconcile drops, no group-placement resets since dispatch).
    """
    pack = build_gang_pack(jobs, groups, offers, satisfied=satisfied)
    if pack is None:
        return assign, None
    assign = np.asarray(assign, dtype=np.int32)
    with tracing.span("gang.reduce", gangs=len(pack.uuids),
                      jobs=len(jobs), fused=precomputed is not None):
        if precomputed is not None:
            out = np.asarray(precomputed[0], dtype=np.int32).copy()
            dropped = np.asarray(precomputed[1], dtype=bool).copy()
        elif device:
            try:
                out, dropped = gang_reduce_kernel(assign, pack)
            except Exception:
                import logging
                logging.getLogger(__name__).exception(
                    "gang reduce dispatch failed; host fallback")
                registry.counter_inc("cook_kernel_fallback",
                                     labels={"kernel": "gang.reduce"})
                _flight.note_fault("kernel.dispatch-fallback")
                out, dropped = reference_impl.gang_reduce(
                    assign, pack.gang_id, pack.gang_size,
                    pack.gang_attr, pack.host_topo)
        else:
            out, dropped = reference_impl.gang_reduce(
                assign, pack.gang_id, pack.gang_size,
                pack.gang_attr, pack.host_topo)
    # ---- rescue pass: a dropped cohort whose members are ALL in the
    # batch may still be packable whole — the kernel assigns in rank
    # order, so an unconstrained sibling ranked ahead of a constrained
    # member (novel-host after a requeue, say) can greedily take the
    # only hosts the constrained member could use, dropping the gang
    # identically every cycle.  Re-match just the cohort, most-
    # constrained member FIRST, against the capacity left by the
    # surviving assignments; accept only a complete packing.
    # the constraint mask is a full O(jobs x hosts) rebuild on the fused
    # path — compute it at most once per cycle, shared by rescue + refill
    cmask: Optional[np.ndarray] = None
    if (dropped.any() and cmask_fn is not None and avail is not None
            and capacity is not None and job_res is not None):
        cmask = np.asarray(cmask_fn(), dtype=bool)
        res_f = np.asarray(job_res, dtype=F32)
        cap_f = np.asarray(capacity, dtype=F32)
        H = cap_f.shape[0]
        avail_left = np.asarray(avail, dtype=F32).copy()
        taken = (out >= 0) & (out < H)
        if taken.any():
            np.subtract.at(avail_left, out[taken], res_f[taken])
        avail_left = np.maximum(avail_left, 0.0)
        from ..state.schema import GroupPlacementType
        for g in sorted({int(x) for x in pack.gang_id[dropped]}):
            rows = np.flatnonzero(pack.gang_id == g)
            if len(rows) < int(pack.gang_size[g]):
                continue  # members missing from the batch: no rescue
            ptype = getattr(groups.get(pack.uuids[g]),
                            "placement_type", None)
            if ptype is not None and ptype is not GroupPlacementType.ALL:
                # the re-pack honors resources + per-job cmask only;
                # within-batch host-placement rules (UNIQUE /
                # ATTRIBUTE_EQUALS / BALANCED) live in
                # validate_group_placement, which already ran — a rescue
                # could silently violate them, so such gangs wait for
                # the normal pass next cycle
                continue
            sub_mask = cmask[rows, :H]
            fits = np.stack([np.all(avail_left >= res_f[r][None, :],
                                    axis=1) for r in rows])
            order = np.argsort((sub_mask & fits).sum(axis=1),
                               kind="stable")
            trial = reference_impl.greedy_match(
                res_f[rows][order], sub_mask[order], avail_left, cap_f)
            # acceptance threshold = the reduction threshold: rigid
            # gangs have exactly `need` rows here so this is the old
            # all-assigned test bit-for-bit; an elastic gang accepts a
            # partial packing of >= gang_min members (the unassigned
            # surplus simply stays unmatched, docs/GANG.md elasticity)
            hit = trial >= 0
            if int(hit.sum()) >= int(pack.gang_size[g]):
                out[rows[order]] = trial
                dropped[rows] = False
                np.subtract.at(avail_left, trial[hit],
                               res_f[rows][order][hit])
                avail_left = np.maximum(avail_left, 0.0)
    stats = GangStats()
    member = pack.gang_id >= 0
    matched_before = member & (assign >= 0)
    matched_final = member & (out >= 0)
    for g, guuid in enumerate(pack.uuids):
        rows = pack.gang_id == g
        matched = int(matched_before[rows].sum())
        # need = reduction threshold (gang_min); size = declared size.
        # Rigid gangs read need == size, so the entry is unchanged.
        need = int(pack.gang_size[g])
        size = int(pack.declared[g]) if pack.declared else need
        if int(matched_final[rows].sum()) >= need \
                and not dropped[rows].any():
            continue  # placed whole (directly or via the rescue pass)
        # topology_blocked: every member matched but the reduction still
        # dropped them — the placements straddled topology domains (or
        # landed outside any), i.e. no single slice took them all
        entry = {
            "size": size, "matched": matched,
            "missing": max(need - matched, 0),
            "topology_blocked": bool(matched >= need
                                     and dropped[rows].any())}
        if need != size:
            entry["min"] = need
        stats.partial[guuid] = entry
    stats.dropped_jobs = int(dropped.sum())
    stats.dropped_gangs = len(
        {int(g) for g in pack.gang_id[dropped]})
    if stats.dropped_jobs:
        registry.counter_inc("cook_gang_partial_drops",
                             float(stats.dropped_gangs))
        # aggregate histogram + per-job attribution from one drop mask
        # (utils/audit.note_skips; the member resets explain themselves
        # on each job's timeline)
        from ..utils import audit as _audit
        _audit.note_skips(audit_trail, {
            "gang-partial": [jobs[i].uuid
                             for i in np.flatnonzero(dropped)]},
            pool=audit_pool)
        # ---- same-cycle refill: the freed capacity goes back to the
        # pool for group-less unmatched jobs (group members need their
        # own group semantics re-validated, so they wait a cycle)
        if (cmask_fn is not None and avail is not None
                and capacity is not None and job_res is not None):
            avail_after = np.asarray(avail, dtype=F32).copy()
            # defensive clip: a padding-host assignment (possible only
            # for zero-resource jobs) must not index past the real hosts
            taken = (out >= 0) & (out < avail_after.shape[0])
            if taken.any():
                np.subtract.at(avail_after, out[taken],
                               np.asarray(job_res, dtype=F32)[taken])
            avail_after = np.maximum(avail_after, 0.0)
            # group-less jobs — plus the grow members of SATISFIED
            # elastic gangs, which the elasticity contract says refill
            # exactly like group-less jobs (docs/GANG.md)
            eligible = ((out < 0) & ~dropped
                        & np.array([not getattr(j, "group", None)
                                    or bool(satisfied
                                            and j.group in satisfied)
                                    for j in jobs], dtype=bool))
            if refill_ok is not None:
                # the caller vetoes rows whose unmatched state is not a
                # plain capacity miss (e.g. pipeline resource conflicts
                # whose staged availability is known-stale)
                eligible &= np.asarray(refill_ok, dtype=bool)
            idx = np.flatnonzero(eligible)
            if idx.size:
                if cmask is None:
                    cmask = np.asarray(cmask_fn(), dtype=bool)
                refill = reference_impl.greedy_match(
                    np.asarray(job_res, dtype=F32)[idx], cmask[idx],
                    avail_after, np.asarray(capacity, dtype=F32))
                hit = refill >= 0
                if hit.any():
                    out[idx[hit]] = refill[hit]
                    stats.refilled = int(hit.sum())
    return out, stats
