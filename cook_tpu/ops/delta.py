"""Device-resident incremental cycle state: the delta scatter-apply
kernel and the device base mirror (ISSUE 7; ROADMAP item 2).

The production fused cycle used to rebuild its stacked [P, T] wire
arrays on the host and re-upload them every cycle — the "host staging
wall" that dominated step_cycle once the kernels were fast.  This module
is the mechanism that replaces the rebuild with incremental view
maintenance (Omega's shared-state insight one level down; McSherry-style
deltas):

* the pack's per-cycle wire arrays (``rows`` row permutation + ``flags``
  admission bits, CompactPoolCycleInputs) live in DEVICE-RESIDENT
  buffers across cycles;
* each cycle the driver diffs the freshly staged host arrays against its
  host shadow (delta extraction — native/pack.cpp when built) and
  dispatches :func:`apply_pack_delta`, a jitted scatter of just the
  changed positions, instead of uploading the world;
* a full repack happens only on an index compaction fence, a bucket
  regrow / group reshape, a kernel-dispatch fault (degrading like every
  other kernel, ``cook_kernel_fallback_total``), or when the delta is so
  large the full upload is cheaper.

Flag-bit constants live here (not in parallel/sharded.py) so the state
and sched layers can reason about wire flags without importing the mesh
layer; parallel/sharded re-exports them under the same names.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np

from . import telemetry
from .padding import bucket

F32 = np.float32

# flag bits of CompactPoolCycleInputs.flags (one wire byte per task)
FLAG_PENDING = 1
FLAG_VALID = 2
FLAG_ENQUEUE_OK = 4
FLAG_LAUNCH_OK = 8
FLAG_USER_FIRST = 16   # first row of a user segment

# delta batches are padded to power-of-two buckets so the scatter
# executable is reused across cycles (min floor keeps tiny deltas from
# compiling log2(min) variants)
_DELTA_MIN_BUCKET = 256


def pack_flags(pending: np.ndarray, valid: np.ndarray,
               is_first: np.ndarray, enqueue_ok=None,
               launch_ok=None) -> np.ndarray:
    """The wire flags byte, packed ONE way for every producer (the fused
    pack and the compact rank path must never drift on bit layout).
    ``enqueue_ok``/``launch_ok`` default to all-accept when omitted —
    note the rank kernel simply ignores those bits."""
    flags = (pending.astype(np.uint8) * FLAG_PENDING
             + valid.astype(np.uint8) * FLAG_VALID
             + is_first.astype(np.uint8) * FLAG_USER_FIRST)
    if enqueue_ok is not None:
        flags += enqueue_ok.astype(np.uint8) * FLAG_ENQUEUE_OK
    if launch_ok is not None:
        flags += launch_ok.astype(np.uint8) * FLAG_LAUNCH_OK
    return flags


def _donate_default() -> bool:
    """Donate the resident buffers into the scatter only where XLA
    honors input-output aliasing (TPU/GPU).  On CPU donation is ignored
    with a warning per call — the copy is cheap there anyway."""
    import jax
    return jax.default_backend() not in ("cpu",)


class _StagedDelta:
    """One staged (h2d-in-flight) scatter batch: the padded device
    arrays whose host->device copies started at :meth:`stage` time.
    Holding it across the current cycle's kernel dispatch is the
    DOUBLE-BUFFERED form (ISSUE 14): the next cycle's delta bytes move
    while the current kernel computes, because every stage allocates
    FRESH host buffers — nothing rewrites memory an in-flight copy still
    reads."""

    __slots__ = ("shape", "kb", "codec", "idx", "vals", "flags", "nbytes")

    def __init__(self, shape, kb, codec, idx, vals, flags, nbytes):
        self.shape = shape
        self.kb = kb
        self.codec = codec
        self.idx = idx
        self.vals = vals
        self.flags = flags
        self.nbytes = nbytes


class PackDeltaApplier:
    """Caches one jitted scatter executable per (buffer shape, delta
    bucket, value codec); donation re-uses the old buffer's device
    memory so the resident pack never doubles its footprint during the
    update.

    The scatter's value payload rides the quantized wire's rows codec
    (ops/quant.py): with ``quantize=True`` row values are coded as
    deltas against their own target position, so a steady-state scatter
    row costs 4 (idx) + 1-2 (value) + 1 (flag) bytes instead of 9 —
    losslessly, with automatic wide fallback when a batch's deltas
    overflow the narrow width."""

    def __init__(self, donate: Optional[bool] = None):
        self._fns: Dict[Tuple, object] = {}
        self._donate = donate

    def _fn(self, shape: Tuple[int, ...], kb: int, codec: int = 0):
        key = (shape, kb, codec)
        fn = self._fns.get(key)
        if fn is None:
            import jax
            import jax.numpy as jnp
            from .quant import ROWS_WIDE
            if self._donate is None:
                self._donate = _donate_default()
            T = shape[-1]

            def _apply(rows_buf, flags_buf, idx, rows_v, flags_v):
                flat_r = rows_buf.reshape(-1)
                flat_f = flags_buf.reshape(-1)
                if codec != ROWS_WIDE:
                    # position-relative decode; the padding sentinel's
                    # garbage value is dropped by its OOB index anyway
                    rows_v32 = rows_v.astype(jnp.int32) + (idx % T)
                else:
                    rows_v32 = rows_v
                # padding idx entries are == buffer size: OOB, dropped
                flat_r = flat_r.at[idx].set(rows_v32, mode="drop")
                flat_f = flat_f.at[idx].set(flags_v, mode="drop")
                return (flat_r.reshape(rows_buf.shape),
                        flat_f.reshape(flags_buf.shape))

            fn = telemetry.instrument_jit("delta.apply", jax.jit(
                _apply,
                donate_argnums=(0, 1) if self._donate else ()))
            self._fns[key] = fn
        return fn

    def stage(self, shape: Tuple[int, ...], idx: np.ndarray,
              rows_vals: np.ndarray, flags_vals: np.ndarray,
              quantize: bool = False) -> _StagedDelta:
        """Pad, negotiate the value codec, and START the host->device
        copies for one delta batch.  Split from :meth:`commit` so a
        pipelined driver's stage-(k+1) h2d overlaps cycle k's in-flight
        kernel (the double-buffering half of ISSUE 14's wire work)."""
        import jax.numpy as jnp
        from . import quant as _q
        n_flat = int(np.prod(shape))
        T = int(shape[-1])
        k = int(idx.size)
        kb = min(bucket(max(k, 1), minimum=_DELTA_MIN_BUCKET), n_flat)
        if kb < k:  # bucket clamped under the delta: caller should repack
            raise ValueError(f"delta larger than buffer ({k} > {n_flat})")
        idx_p = np.full(kb, n_flat, dtype=np.int32)  # OOB sentinel pad
        idx_p[:k] = idx
        codec = _q.ROWS_WIDE
        if quantize and k:
            delta = rows_vals.astype(np.int64) - (idx.astype(np.int64) % T)
            lo, hi = int(delta.min()), int(delta.max())
            if -128 <= lo and hi <= 127:
                codec, dt = _q.ROWS_I8, np.int8
            elif -32768 <= lo and hi <= 32767:
                codec, dt = _q.ROWS_I16, np.int16
            else:
                # the lossless-or-wide contract counts EVERY wide
                # fallback (an operator must be able to see the narrow
                # path never engaging)
                _q.note_wide("delta")
        if codec != _q.ROWS_WIDE:
            rows_p = np.zeros(kb, dtype=dt)
            rows_p[:k] = delta.astype(dt)
        else:
            rows_p = np.zeros(kb, dtype=np.int32)
            rows_p[:k] = rows_vals
        flags_p = np.zeros(kb, dtype=np.uint8)
        flags_p[:k] = flags_vals
        nbytes = idx_p.nbytes + rows_p.nbytes + flags_p.nbytes
        telemetry.count_transfer("h2d", nbytes)
        return _StagedDelta(tuple(shape), kb, codec, jnp.asarray(idx_p),
                            jnp.asarray(rows_p), jnp.asarray(flags_p),
                            nbytes)

    def commit(self, rows_dev, flags_dev, st: _StagedDelta):
        """Dispatch the scatter against a previously staged batch."""
        fn = self._fn(st.shape, st.kb, st.codec)
        return fn(rows_dev, flags_dev, st.idx, st.vals, st.flags)

    def apply(self, rows_dev, flags_dev, idx: np.ndarray,
              rows_vals: np.ndarray, flags_vals: np.ndarray,
              quantize: bool = False):
        """Scatter the delta batch into the resident buffers; returns the
        (new_rows_dev, new_flags_dev) device arrays.  ``idx`` holds flat
        positions into the raveled buffer."""
        st = self.stage(tuple(rows_dev.shape), idx, rows_vals,
                        flags_vals, quantize=quantize)
        return self.commit(rows_dev, flags_dev, st)


class DeviceBaseMirror:
    """Device-resident mirror of the columnar index's immutable res/disk
    base columns: rows are append-only while the compaction epoch is
    unchanged, so steady-state cycles upload only the NEW rows (one
    bucketed chunk append); a compaction epoch change or capacity
    overflow triggers a full (re)upload.  Shared by the fused driver and
    the columnar rank path."""

    def __init__(self):
        self._key: Optional[int] = None   # compaction epoch mirrored
        self._n = 0                       # rows synced
        self._cap = 0                     # device buffer capacity
        self._res = None                  # f32[cap, 4] on device
        self._disk = None                 # f32[cap] on device
        self._append_fn = None            # shared jitted chunk append

    def _append(self, base, chunk, off):
        """Donating chunk append (jit caches one executable per shape)."""
        if self._append_fn is None:
            import jax
            from jax import lax
            self._append_fn = telemetry.instrument_jit(
                "delta.append", jax.jit(
                    lambda b, c, o: lax.dynamic_update_slice(
                        b, c, (o,) + (0,) * (c.ndim - 1)),
                    donate_argnums=0))
        return self._append_fn(base, chunk, off)

    @property
    def capacity(self) -> int:
        return self._cap

    def sync(self, res_base: np.ndarray, disk_base: np.ndarray,
             compactions: int):
        """Bring the device mirror up to the snapshot: full (re)upload on
        a compaction epoch change or capacity overflow, else one bucketed
        chunk append of the rows added since the last cycle.  Returns the
        (res, disk) device arrays (capacity-padded)."""
        import jax.numpy as jnp
        n = res_base.shape[0]
        full = (self._key != compactions or n > self._cap)
        if not full and n > self._n:
            k = n - self._n
            kb = bucket(k, minimum=1024)
            if self._n + kb > self._cap:
                full = True  # dynamic_update_slice would clamp, not grow
            else:
                chunk = np.zeros((kb, 4), dtype=F32)
                chunk[:k] = res_base[self._n:n]
                dchunk = np.zeros(kb, dtype=F32)
                dchunk[:k] = disk_base[self._n:n]
                off = jnp.asarray(self._n, dtype=jnp.int32)
                telemetry.count_transfer("h2d",
                                         chunk.nbytes + dchunk.nbytes)
                self._res = self._append(self._res, jnp.asarray(chunk), off)
                self._disk = self._append(self._disk, jnp.asarray(dchunk),
                                          off)
                self._n = n
        if full:
            cap = bucket(n, minimum=1024)
            res_p = np.zeros((cap, 4), dtype=F32)
            res_p[:n] = res_base
            disk_p = np.zeros(cap, dtype=F32)
            disk_p[:n] = disk_base
            telemetry.count_transfer("h2d", res_p.nbytes + disk_p.nbytes)
            self._res = jnp.asarray(res_p)
            self._disk = jnp.asarray(disk_p)
            self._key, self._n, self._cap = compactions, n, cap
        return self._res, self._disk
