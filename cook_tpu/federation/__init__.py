"""Multi-cell federation (ROADMAP item 2): a thin front-door tier over
N autonomous cook_tpu cells.

The design is Hydra's (NSDI'19): cells stay sovereign — each keeps its
own store, journal, election, scheduler — and what crosses the cell
boundary is *bounded summaries*, never job state.  The pieces:

``tokens``
    cell-qualified commit tokens: PR 12's ``(partition, epoch, offset)``
    vector entries prefixed with a cell id so read-your-writes survives
    a multi-cell front door (``cellA/p0:3:128``).
``cells``
    CellSpec/CellHandle — one cell's address, capacity tier, locality
    attributes, a breaker-guarded raw HTTP transport, and the cached
    health/saturation snapshot the router scores with.
``summary``
    FederatedUserSummaries — the staleness-bounded UserSummaryExchange
    pattern lifted one level: per-user pending/running/resource tables
    fetched from every serving cell, merged with the oldest table's age
    backdating the whole view, ``SummaryStalenessError`` at the bound.
``router``
    FederationRouter — routes whole submission batches (gangs never
    split; PR 5's owning-cluster rule generalized) by locality, load,
    saturation and capacity tier; enforces the GLOBAL per-user pending
    cap and dominant-share ceiling off the federated summaries; keeps
    the bounded commit ledger that makes full-cell-outage re-route
    lossless for committed work.
``rest``
    FederationServer — the stateless front door (the ``federation``
    daemon role): single-cell deployments proxy wire-identically to a
    direct cell connection; multi-cell deployments qualify commit
    tokens, gate reads against the right cell, and degrade cross-cell
    reads honestly (bounded-stale headers, never faked).
"""

from .tokens import (  # noqa: F401
    CELL_SEP,
    cells_in_token,
    qualify_token,
    split_entry,
    strip_for_cell,
)
from .cells import CellHandle, CellSpec, CellUnreachable  # noqa: F401
from .summary import FederatedUserSummaries  # noqa: F401
from .router import FederationRouter, RouteRejected  # noqa: F401
from .rest import FederationServer, build_federation_node  # noqa: F401
