"""The staleness-bounded UserSummaryExchange, federated across cells.

Global fair-share needs one answer per user — pending count, running
count, resource sums — that covers EVERY cell, without ever shipping
job state between cells (that would rebuild the single blast domain
federation exists to remove).  The intra-cell machinery already solved
this shape for partitions and then for shard processes
(:class:`cook_tpu.state.partition.UserSummaryExchange`); this module
lifts it one level by plugging a per-cell HTTP fetch into the SAME
exchange as its ``peer_fetch`` carrier:

- each serving cell's bounded table rides
  ``GET /debug/federation/summary`` (a few floats per distinct user);
- a cell that answers contributes a fresh table (its reported age
  backdates the merge, exactly like a shard peer's table would);
- a cell that does NOT answer keeps contributing its LAST table with
  its true age — the merge's staleness then grows loudly toward the
  bound and enforcement raises
  :class:`~cook_tpu.state.partition.SummaryStalenessError` instead of
  silently serving a view that no longer covers that cell's users;
- a DRAINED cell leaves the merge entirely (operator intent: its
  demand was finished or re-routed; a tombstone table would
  double-count every re-routed user forever) and re-converges on
  rejoin with one fresh fetch.
"""

from __future__ import annotations

import time
from typing import TYPE_CHECKING, Dict, List, Tuple

from ..state.partition import SummaryStalenessError, UserSummaryExchange
from ..utils.metrics import registry
from .cells import CellUnreachable

if TYPE_CHECKING:  # pragma: no cover
    from .cells import CellHandle

__all__ = ["FederatedUserSummaries", "SummaryStalenessError"]


class FederatedUserSummaries:
    """Per-user tables from every serving cell, merged under one
    asserted staleness bound."""

    def __init__(self, cells: Dict[str, "CellHandle"],
                 max_age_s: float = 5.0):
        self._cells = cells
        #: last successfully fetched table per cell:
        #: cell id -> (users_table, fetched_monotonic, reported_age_s)
        self._cache: Dict[str, Tuple[Dict[str, Dict[str, float]],
                                     float, float]] = {}
        self.fetch_errors = 0
        self._exchange = UserSummaryExchange(
            partitions=[], max_age_s=max_age_s,
            peer_fetch=self._fetch, assert_bound=True)

    @property
    def max_age_s(self) -> float:
        return self._exchange.max_age_s

    def _fetch(self) -> List[Tuple[Dict[str, Dict[str, float]], float]]:
        """The exchange's peer carrier: one (table, age) entry per
        serving cell — fresh when the cell answers, the aged cache when
        it does not, and an infinitely old placeholder for a serving
        cell never successfully read (its users are invisible, and the
        merge must say so rather than enforce around them)."""
        out: List[Tuple[Dict[str, Dict[str, float]], float]] = []
        for cell_id, handle in self._cells.items():
            if not handle.serving():
                continue
            try:
                doc = handle.get_json("/debug/federation/summary")
                table = dict(doc.get("users") or {})
                age = max(float(doc.get("age_s") or 0.0), 0.0)
                self._cache[cell_id] = (table, time.monotonic(), age)
                out.append((table, age))
            except (CellUnreachable, ValueError, TypeError):
                self.fetch_errors += 1
                cached = self._cache.get(cell_id)
                if cached is None:
                    out.append(({}, float("inf")))
                else:
                    table, at, age = cached
                    out.append((table, age + (time.monotonic() - at)))
        registry.gauge_set("cook_federation_summary_staleness_seconds",
                           min(self.staleness_s(), 1e12))
        return out

    def forget(self, cell_id: str) -> None:
        """Drop a drained cell's cached table so a later rejoin starts
        from a fresh fetch, not a resurrected corpse."""
        self._cache.pop(cell_id, None)

    # -------------------------------------------------- exchange surface
    def refresh(self) -> None:
        self._exchange.refresh()

    def staleness_s(self) -> float:
        return self._exchange.staleness_s()

    def merged(self) -> Dict[str, Dict[str, float]]:
        return self._exchange.merged()

    def user_totals(self, user: str) -> Dict[str, float]:
        return self._exchange.user_totals(user)

    def stats(self) -> Dict[str, object]:
        stats = self._exchange.stats()
        stats["fetch_errors"] = self.fetch_errors
        stats["cells_cached"] = len(self._cache)
        return stats
