"""The federation routing brain.

Everything the front door decides happens here, behind a plain method
surface the REST shim (federation/rest.py) and the chaos simulator
call directly:

- **whole-batch routing** — one submission batch (and therefore one
  gang: a gang's jobs always ride one atomic batch) lands on exactly
  one cell, chosen by locality attributes, capacity tier, per-cell
  load and the cell's own saturation/brownout signals.  PR 5's
  owning-cluster rule, generalized: demand that must stay together
  routes together or not at all.
- **breaker-per-cell reroute** — a cell that stops answering trips its
  breaker after ``breaker_failures`` consecutive transport failures;
  from then on its traffic reroutes WHOLE to surviving cells (no
  per-request dribble into a dead socket, no cascade: the surviving
  cells' breakers never see the dead cell's failures).
- **global fair-share** — the per-user pending cap and dominant-share
  ceiling are enforced HERE, against the federated summary merge, so
  a user cannot escape their cap by spraying cells; refusals quote the
  staleness window and the merge raises rather than silently serving a
  view that no longer covers an unreachable cell.
- **the commit ledger** — a bounded record of every batch a cell
  ACCEPTED (positively acknowledged; never in-flight guesses), which
  is exactly the set "zero lost committed submissions" quantifies
  over: on full-cell outage or spot reclaim, every ledgered batch of
  the dead cell re-submits whole to a surviving cell, mea-culpa
  (Reasons.CELL_RECLAIMED — free retries, the platform's fault).
"""

from __future__ import annotations

import json
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional, Set, Tuple

from ..config import FederationConfig
from ..state.partition import SummaryStalenessError
from ..state.schema import Reasons
from ..utils import tracing
from ..utils.metrics import registry
from .cells import CellHandle, CellSpec, CellUnreachable
from .summary import FederatedUserSummaries

#: label key (under the configured prefix) that pins a batch to a cell
#: id instead of matching attributes
PIN_KEY = "cell"


class RouteRejected(Exception):
    """An admission refusal minted by the ROUTER itself (global caps,
    no eligible cell): carries the HTTP shape the front door answers
    with, mirroring rest.api.ApiError."""

    def __init__(self, status: int, message: str,
                 headers: Optional[Dict[str, str]] = None,
                 extra: Optional[Dict[str, Any]] = None):
        super().__init__(message)
        self.status = status
        self.message = message
        self.headers = headers or {}
        self.extra = extra or {}


class FederationRouter:
    """Routing + global enforcement + the commit ledger for one front
    door.  Stateless in the durability sense: every decision input is
    re-fetchable from the cells, and the ledger only accelerates
    re-route/read-routing — losing the router loses no committed work
    (the cells hold it)."""

    def __init__(self, config: FederationConfig):
        self.config = config
        self.cells: "OrderedDict[str, CellHandle]" = OrderedDict()
        for entry in config.cells:
            spec = CellSpec(
                id=str(entry["id"]), url=str(entry["url"]),
                tier=str(entry.get("tier", "standard")),
                attributes=dict(entry.get("attributes") or {}),
                weight=float(entry.get("weight", 1.0)))
            self.cells[spec.id] = CellHandle(
                spec, failure_threshold=config.breaker_failures,
                reset_timeout_s=config.breaker_reset_seconds,
                request_timeout_s=config.request_timeout_seconds)
        self.summaries = FederatedUserSummaries(
            self.cells, max_age_s=config.summary_max_age_seconds)
        self._mu = threading.Lock()
        #: batch key (first job uuid) -> ledger entry; insertion-ordered
        #: so eviction drops the oldest accepted batch first
        self._ledger: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
        self._uuid_to_batch: Dict[str, str] = {}
        self.ledger_evicted = 0
        #: recent routed batches' job shapes for goodput-mode replay
        self._recent: "deque[Dict[str, Any]]" = deque(
            maxlen=max(int(config.goodput_window), 1))
        self.rejections = 0
        self.rerouted_jobs = 0
        self.rerouted_batches = 0
        registry.gauge_set("cook_federation_cells", float(len(self.cells)))

    # ------------------------------------------------------------ surface
    @property
    def single_cell(self) -> bool:
        """One configured cell ⇒ the front door is a pure reverse
        proxy: no token qualification, no global enforcement beyond
        what the cell itself does — decision- and wire-identical to
        talking to the cell directly."""
        return len(self.cells) == 1

    def cell(self, cell_id: str) -> Optional[CellHandle]:
        return self.cells.get(cell_id)

    def eligible_cells(self) -> List[CellHandle]:
        return [h for h in self.cells.values() if h.eligible()]

    # ------------------------------------------------------- batch parsing
    @staticmethod
    def _batch_uuids(body: Dict[str, Any]) -> List[str]:
        return [str(s["uuid"]) for s in body.get("jobs", [])
                if isinstance(s, dict) and s.get("uuid")]

    @staticmethod
    def _is_gang(body: Dict[str, Any]) -> bool:
        if body.get("groups"):
            return True
        return any(isinstance(s, dict) and s.get("group")
                   for s in body.get("jobs", []))

    def _locality_demands(self, body: Dict[str, Any]) -> Dict[str, str]:
        """The union of every job's locality labels — a batch is one
        placement unit, so its demands combine (conflicting demands
        simply match no cell, which is the honest answer)."""
        prefix = self.config.locality_label_prefix
        demands: Dict[str, str] = {}
        for spec in body.get("jobs", []):
            labels = spec.get("labels") if isinstance(spec, dict) else None
            if not isinstance(labels, dict):
                continue
            for k, v in labels.items():
                if isinstance(k, str) and k.startswith(prefix):
                    demands[k[len(prefix):]] = str(v)
        return demands

    # --------------------------------------------------- global fair-share
    def _check_global_caps(self, body: Dict[str, Any], user: str) -> None:
        """The front door's global per-user enforcement.  Single-cell
        routers skip it entirely (parity: the cell's own admission is
        the only admission), as do deployments with both caps off."""
        cfg = self.config
        if self.single_cell or \
                (cfg.max_user_pending <= 0
                 and cfg.max_user_dominant_share <= 0.0):
            return
        n_jobs = len(body.get("jobs", []))
        try:
            totals = self.summaries.user_totals(user)
        except SummaryStalenessError as exc:
            # the global view cannot be brought under its bound (a
            # serving cell is unreachable): enforcement must not guess.
            # 503 + Retry-After, never a silently-unenforced admit and
            # never a refusal quoting a window we don't actually have.
            self.rejections += 1
            registry.counter_inc("cook_federation_rejections", 1.0,
                                 {"scope": "user", "reason": "stale"})
            raise RouteRejected(
                503, f"global fair-share view unavailable: {exc}",
                headers={"Retry-After": "1"},
                extra={"reason": "summary-stale"})
        if cfg.max_user_pending > 0 and \
                totals["pending"] + n_jobs > cfg.max_user_pending:
            self.rejections += 1
            registry.counter_inc("cook_federation_rejections", 1.0,
                                 {"scope": "user", "reason": "pending-cap"})
            raise RouteRejected(
                429, f"user {user} would exceed the global pending cap "
                     f"({int(totals['pending'])} pending across "
                     f"{len(self.cells)} cells + {n_jobs} submitted > "
                     f"{cfg.max_user_pending}; view "
                     f"{self.summaries.staleness_s():.3f}s stale, bound "
                     f"{self.summaries.max_age_s}s)",
                headers={"Retry-After": "5"},
                extra={"reason": "global-pending-cap"})
        if cfg.max_user_dominant_share > 0.0:
            share = self._dominant_share(user, totals)
            if share > cfg.max_user_dominant_share:
                self.rejections += 1
                registry.counter_inc(
                    "cook_federation_rejections", 1.0,
                    {"scope": "user", "reason": "dominant-share"})
                raise RouteRejected(
                    429, f"user {user} holds {share:.3f} dominant share "
                         f"of the federation's running usage (cap "
                         f"{cfg.max_user_dominant_share}); view "
                         f"{self.summaries.staleness_s():.3f}s stale, "
                         f"bound {self.summaries.max_age_s}s",
                    headers={"Retry-After": "15"},
                    extra={"reason": "global-dominant-share"})

    def _dominant_share(self, user: str,
                        totals: Dict[str, float]) -> float:
        """The user's dominant resource share of the FEDERATION's
        running usage — DRU's defining ratio, computed on the merged
        summaries (usage over usage: capacity totals never cross the
        cell boundary, so the denominator is what is actually in use,
        which is the conservative choice under contention — exactly
        when the cap matters)."""
        merged = self.summaries.merged()
        fleet = {"cpus": 0.0, "mem": 0.0, "gpus": 0.0}
        for u in merged.values():
            for k in fleet:
                fleet[k] += u.get(k, 0.0)
        share = 0.0
        for k, total in fleet.items():
            if total > 0:
                share = max(share, totals.get(k, 0.0) / total)
        return share

    # ------------------------------------------------------------- scoring
    def _candidates(self, demands: Dict[str, str],
                    exclude: Set[str]) -> List[CellHandle]:
        pinned = demands.get(PIN_KEY)
        out = []
        for h in self.cells.values():
            if h.spec.id in exclude or not h.eligible():
                continue
            if pinned is not None and h.spec.id != pinned:
                continue
            if any(h.spec.attributes.get(k) != v
                   for k, v in demands.items() if k != PIN_KEY):
                continue
            out.append(h)
        return out

    def _score(self, h: CellHandle) -> float:
        s = h.spec.weight * (1.0 - min(h.saturation(), 1.0)) \
            / (1.0 + h.inflight + 0.01 * h.routed_total)
        if h.spec.tier == "spot":
            s *= self.config.spot_penalty
        return s

    def _goodput_scores(self,
                        cands: List[CellHandle]) -> Dict[str, float]:
        """Goodput route mode: replay this router's recent routed job
        shapes through ``sim/`` against each candidate cell's last
        advertised host inventory (PR 13's optimizer replay, one level
        up).  Cells that never advertised hosts score 0 additions —
        the load score alone decides."""
        recent = list(self._recent)
        if not recent:
            return {}
        from ..sim.simulator import Simulator, load_hosts
        from ..state.schema import Job, Resources
        scores: Dict[str, float] = {}
        for h in cands:
            hosts = (h._health.get("federation_hosts")
                     if isinstance(h._health, dict) else None)
            if not hosts:
                try:
                    doc = h.get_json("/debug/federation/summary")
                    hosts = doc.get("hosts") or []
                    if isinstance(h._health, dict):
                        h._health["federation_hosts"] = hosts
                except (CellUnreachable, ValueError):
                    continue
            if not hosts:
                continue
            jobs = [Job(uuid=f"replay-{i}", user=e["user"],
                        command="replay",
                        resources=Resources(cpus=e["cpus"], mem=e["mem"],
                                            gpus=e["gpus"]),
                        submit_time_ms=0,
                        labels={"sim/duration_ms": "1000"})
                    for i, e in enumerate(recent)]
            try:
                sim = Simulator(jobs, load_hosts(hosts), backend="cpu")
                with registry.suppressed():
                    res = sim.run(max_virtual_ms=30_000)
                scores[h.spec.id] = float(
                    res.goodput.get("goodput", res.completed))
            except Exception:
                continue
        return scores

    def pick_cell(self, body: Dict[str, Any],
                  exclude: Optional[Set[str]] = None) -> CellHandle:
        demands = self._locality_demands(body)
        cands = self._candidates(demands, exclude or set())
        if not cands:
            self.rejections += 1
            registry.counter_inc("cook_federation_rejections", 1.0,
                                 {"scope": "batch", "reason": "no-cell"})
            raise RouteRejected(
                503, "no eligible cell for this batch "
                     f"(locality demands {demands or '{}'}; "
                     f"{len(self.cells)} cells configured)",
                headers={"Retry-After": "2"},
                extra={"reason": "no-eligible-cell"})
        if len(cands) == 1:
            return cands[0]
        goodput = (self._goodput_scores(cands)
                   if self.config.route_mode == "goodput" else {})
        return max(cands,
                   key=lambda h: (self._score(h)
                                  * (1.0 + goodput.get(h.spec.id, 0.0)),
                                  h.spec.id))

    # ------------------------------------------------------------- routing
    def submit(self, raw: bytes, user: str,
               headers: Dict[str, str]
               ) -> Tuple[int, Dict[str, str], bytes, str]:
        """Route one submission batch: admission → cell choice → proxy
        → ledger.  Returns ``(status, headers, body, cell_id)`` of the
        cell's answer.  An unreachable first choice re-routes the WHOLE
        batch to the next eligible cell (the breaker records every
        miss, so a dead cell stops being chosen after
        ``breaker_failures`` batches fleet-wide)."""
        try:
            body = json.loads(raw.decode() or "{}")
        except ValueError:
            raise RouteRejected(400, "malformed submission body")
        if not isinstance(body, dict):
            raise RouteRejected(400, "malformed submission body")
        self._check_global_caps(body, user)
        uuids = self._batch_uuids(body)
        gang = self._is_gang(body)
        tried: Set[str] = set()
        with tracing.span("federation.route", user=user,
                          jobs=len(uuids), gang=gang):
            while True:
                handle = self.pick_cell(body, exclude=tried)
                cell_id = handle.spec.id
                tried.add(cell_id)
                handle.inflight += 1
                t0 = time.perf_counter()
                try:
                    status, resp_headers, resp_raw = handle.request(
                        "POST", "/jobs", body=raw, headers=headers)
                except CellUnreachable:
                    # whole-batch re-route: the breaker recorded the
                    # miss; the next iteration excludes this cell
                    registry.counter_inc(
                        "cook_federation_reroutes_total", 1.0,
                        {"reason": "unreachable"})
                    continue
                finally:
                    handle.inflight -= 1
                registry.observe("cook_federation_route_seconds",
                                 time.perf_counter() - t0)
                if 200 <= status < 300:
                    self._record_accepted(cell_id, raw, user, uuids, gang)
                registry.counter_inc("cook_federation_routed_total", 1.0,
                                     {"cell": cell_id})
                handle.routed_total += 1
                return status, resp_headers, resp_raw, cell_id

    def _record_accepted(self, cell_id: str, raw: bytes, user: str,
                         uuids: List[str], gang: bool) -> None:
        if not uuids:
            return
        entry = {"cell": cell_id, "raw": raw, "user": user,
                 "uuids": uuids, "gang": gang, "reroutes": 0}
        for e in ({"user": user, "cpus": s.get("cpus", 1.0),
                   "mem": s.get("mem", 256.0),
                   "gpus": s.get("gpus", 0.0)}
                  for s in json.loads(raw.decode()).get("jobs", [])
                  if isinstance(s, dict)):
            self._recent.append(e)
        with self._mu:
            key = uuids[0]
            self._ledger[key] = entry
            for u in uuids:
                self._uuid_to_batch[u] = key
            while len(self._ledger) > self.config.ledger_max_batches:
                old_key, old = self._ledger.popitem(last=False)
                for u in old["uuids"]:
                    self._uuid_to_batch.pop(u, None)
                self.ledger_evicted += 1
                registry.counter_inc("cook_federation_ledger_evicted_total")

    def cell_of_uuid(self, uuid: str) -> Optional[str]:
        with self._mu:
            key = self._uuid_to_batch.get(uuid)
            return self._ledger[key]["cell"] if key else None

    # ------------------------------------------------- drain/reclaim/outage
    def drain_cell(self, cell_id: str) -> Dict[str, Any]:
        """Operator drain: no NEW demand routes here; the cell's
        summary table leaves the global merge.  Existing demand keeps
        running on the cell (it is healthy — this is the dynamic-
        cluster drain contract, one level up)."""
        handle = self._require(cell_id)
        handle.drained = True
        self.summaries.forget(cell_id)
        registry.counter_inc("cook_federation_drains_total",
                             labels={"cell": cell_id})
        return {"cell": cell_id, "drained": True}

    def rejoin_cell(self, cell_id: str) -> Dict[str, Any]:
        """Undo a drain: the cell takes new demand again and its table
        re-enters the merge on the next sweep (re-convergence is one
        fresh fetch — the exchange's staleness bound guarantees the
        window)."""
        handle = self._require(cell_id)
        handle.drained = False
        handle.breaker.record_success()
        self.summaries.refresh()
        return {"cell": cell_id, "drained": False}

    def reclaim_cell(self, cell_id: str,
                     reason=Reasons.CELL_RECLAIMED) -> Dict[str, Any]:
        """Spot-tier reclaim or confirmed full-cell outage: drain the
        cell AND re-route every ledgered batch it had accepted to
        surviving cells, whole batches only (a gang re-lands as one
        gang or stays pending — never split).  ``reason`` is mea-culpa:
        the re-routed demand keeps its retry budget; the platform took
        the capacity, the jobs did nothing wrong."""
        handle = self._require(cell_id)
        handle.drained = True
        self.summaries.forget(cell_id)
        with self._mu:
            batches = [dict(e) for e in self._ledger.values()
                       if e["cell"] == cell_id]
        rerouted, failed = [], []
        for entry in batches:
            ok, new_cell = self._reroute_batch(entry, cell_id,
                                               reason.name)
            (rerouted if ok else failed).append(
                {"batch": entry["uuids"][0], "jobs": len(entry["uuids"]),
                 "gang": entry["gang"], "cell": new_cell})
        registry.counter_inc("cook_federation_reclaims_total",
                             labels={"cell": cell_id,
                                     "reason": reason.name})
        return {"cell": cell_id, "reason": reason.name,
                "mea_culpa": reason.mea_culpa,
                "rerouted_batches": rerouted, "failed_batches": failed}

    def _reroute_batch(self, entry: Dict[str, Any], dead_cell: str,
                       reason_name: str) -> Tuple[bool, Optional[str]]:
        """Re-submit one accepted batch whole to a surviving cell.
        The resubmission is marked idempotent so a batch that ALSO
        survived on a half-dead cell (or a double reroute) lands as a
        no-op rather than a duplicate-uuid refusal."""
        try:
            body = json.loads(entry["raw"].decode())
        except ValueError:
            return False, None
        body["idempotent"] = True
        raw = json.dumps(body).encode()
        headers = {"Content-Type": "application/json",
                   "X-Cook-User": entry["user"]}
        tried = {dead_cell}
        while True:
            try:
                handle = self.pick_cell(body, exclude=tried)
            except RouteRejected:
                return False, None
            tried.add(handle.spec.id)
            try:
                status, _, _ = handle.request("POST", "/jobs", body=raw,
                                              headers=headers)
            except CellUnreachable:
                continue
            if 200 <= status < 300:
                with self._mu:
                    key = entry["uuids"][0]
                    if key in self._ledger:
                        self._ledger[key]["cell"] = handle.spec.id
                        self._ledger[key]["reroutes"] += 1
                        for u in entry["uuids"]:
                            self._uuid_to_batch[u] = key
                self.rerouted_batches += 1
                self.rerouted_jobs += len(entry["uuids"])
                registry.counter_inc(
                    "cook_federation_rerouted_jobs_total",
                    float(len(entry["uuids"])),
                    {"reason": reason_name})
                return True, handle.spec.id
            return False, handle.spec.id

    def _require(self, cell_id: str) -> CellHandle:
        handle = self.cells.get(cell_id)
        if handle is None:
            raise RouteRejected(404, f"no such cell {cell_id!r}")
        return handle

    # ------------------------------------------------------------ debugging
    def probe_all(self) -> None:
        for handle in self.cells.values():
            if handle.serving():
                handle.probe_health()

    def to_doc(self) -> Dict[str, Any]:
        """The ``/debug/federation`` panel."""
        try:
            summary_stats = self.summaries.stats()
        except Exception as exc:  # stats() itself never asserts, but
            summary_stats = {"error": str(exc)}  # stay panel-safe
        with self._mu:
            ledger = {"batches": len(self._ledger),
                      "jobs": len(self._uuid_to_batch),
                      "evicted": self.ledger_evicted,
                      "max_batches": self.config.ledger_max_batches}
        return {
            "cells": [h.to_doc() for h in self.cells.values()],
            "single_cell": self.single_cell,
            "route_mode": self.config.route_mode,
            "summaries": summary_stats,
            "ledger": ledger,
            "rejections": self.rejections,
            "rerouted_batches": self.rerouted_batches,
            "rerouted_jobs": self.rerouted_jobs,
            "caps": {
                "max_user_pending": self.config.max_user_pending,
                "max_user_dominant_share":
                    self.config.max_user_dominant_share,
                "summary_max_age_seconds":
                    self.config.summary_max_age_seconds,
            },
        }
