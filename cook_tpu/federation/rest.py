"""The federation front door: a stateless HTTP tier over N cells.

This is the ``federation`` daemon role (a ``"federation"`` section in
the daemon conf makes the process a router node: no store, no journal,
no election).  Two operating regimes, chosen purely by cell count:

**Single cell** — the router is a pure reverse proxy.  Request and
response bytes pass through verbatim, commit tokens stay unqualified,
no global enforcement runs (the cell's own admission is the only
admission).  A client cannot distinguish the front door from a direct
cell connection — the wire-parity contract tier-1 asserts.

**Multiple cells** — submissions route whole-batch by locality, load,
tier and saturation (federation/router.py); accepted writes come back
with their ``X-Cook-Commit-Offset`` CELL-QUALIFIED (``cellA/p0:3:128``)
so one session token spans journals; reads carry the vector back, the
router strips it to the target cell's entries (cells never see cell
ids) and names every OTHER cell the vector mentioned in
``X-Cook-Federation-Stale-Cells`` — the read is honestly bounded-stale
with respect to those cells, never faked fresh.  Reads the router
cannot answer faithfully across cells are refused with 501 and the
reason, not half-answered.

Routes served by the router itself (API_ROUTES-style table below,
harvested into the OBSERVABILITY.md endpoint registry):
``/debug/federation`` (the routing panel), ``/debug/health``,
``/metrics``, ``/info``, plus the drain/rejoin/reclaim admin POSTs.
"""

from __future__ import annotations

import json
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple

from ..config import FederationConfig
from ..utils.metrics import registry
from .cells import CellUnreachable
from .router import FederationRouter, RouteRejected
from .tokens import qualify_token, strip_for_cell

#: (method, path, summary, admin_only) — the front door's own surface.
#: Everything else is proxied/routed to cells (or honestly refused).
FEDERATION_ROUTES = [
    ("GET", "/debug/federation",
     "federation routing panel: per-cell breaker/drain/saturation, "
     "ledger depth, global summary staleness, reroute counters", False),
    ("GET", "/debug/health",
     "router health roll-up: eligible cell count, per-cell breaker "
     "states, summary staleness", False),
    ("GET", "/metrics", "router Prometheus metrics", False),
    ("GET", "/info", "router identity + cell roster", False),
    ("POST", "/federation/drain/{cell}",
     "drain a cell: no new demand, summary leaves the merge", True),
    ("POST", "/federation/rejoin/{cell}",
     "rejoin a drained cell: takes demand, summary re-converges", True),
    ("POST", "/federation/reclaim/{cell}",
     "reclaim a cell (spot tier / outage): drain + whole-batch "
     "mea-culpa re-route of its accepted demand", True),
]

#: hop-by-hop / recomputed headers never forwarded in either direction
_HOP_HEADERS = {"host", "connection", "content-length", "server",
                "date", "transfer-encoding", "keep-alive"}

#: read paths fanned out to EVERY serving cell and merged (list-shaped
#: answers concatenate; /usage sums; /pools unions by name)
_FANOUT_CONCAT = {"/list", "/running"}
_FANOUT_UNION = {"/pools"}


def _forwardable(headers) -> Dict[str, str]:
    return {k: v for k, v in headers.items()
            if k.lower() not in _HOP_HEADERS}


class _FederationHandler(BaseHTTPRequestHandler):
    router: FederationRouter  # bound per-server subclass
    protocol_version = "HTTP/1.1"
    # Nagle off, same as the cell server (rest/api.py): the proxied
    # response is written headers-then-body, and on localhost the
    # second segment would otherwise sit out a ~40ms delayed-ACK round
    # per request — 10x the whole routed hop.
    disable_nagle_algorithm = True
    timeout = 120
    # fully-buffered response stream: status line, relayed headers and
    # the proxied body coalesce into ONE sendall per response
    # (handle_one_request flushes after every method call, so
    # keep-alive responses still go out immediately)
    wbufsize = -1

    def log_message(self, fmt, *args):  # quiet, like the cell server
        pass

    # ------------------------------------------------------------ plumbing
    def _body(self) -> bytes:
        try:
            n = int(self.headers.get("Content-Length", 0) or 0)
        except ValueError:
            n = 0
        return self.rfile.read(n) if n > 0 else b""

    def _respond_json(self, status: int, payload: Any,
                      extra_headers: Optional[Dict[str, str]] = None
                      ) -> None:
        raw = json.dumps(payload).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(raw)))
        for k, v in (extra_headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(raw)

    def _respond_raw(self, status: int, headers: Dict[str, str],
                     raw: bytes,
                     extra_headers: Optional[Dict[str, str]] = None
                     ) -> None:
        """Pass a cell's answer through byte-identically (plus any
        router-added headers) — the wire-parity path."""
        self.send_response(status)
        for k, v in headers.items():
            if k.lower() not in _HOP_HEADERS:
                self.send_header(k, v)
        for k, v in (extra_headers or {}).items():
            self.send_header(k, v)
        self.send_header("Content-Length", str(len(raw)))
        self.end_headers()
        self.wfile.write(raw)

    def _user(self) -> str:
        return str(self.headers.get("X-Cook-User") or "")

    # ------------------------------------------------------------- proxying
    def _proxy(self, handle, method: str, target: str,
               body: Optional[bytes],
               extra_resp_headers: Optional[Dict[str, str]] = None,
               req_headers: Optional[Dict[str, str]] = None) -> None:
        try:
            status, resp_headers, raw = handle.request(
                method, target, body=body,
                headers=req_headers if req_headers is not None
                else _forwardable(self.headers))
        except CellUnreachable as exc:
            self._respond_json(503, {"error": str(exc),
                                     "cell": handle.spec.id},
                              extra_headers={"Retry-After": "2"})
            return
        self._respond_raw(status, resp_headers, raw,
                          extra_headers=extra_resp_headers)

    def _target(self) -> Tuple[str, str, Dict[str, List[str]]]:
        parsed = urllib.parse.urlparse(self.path)
        target = (parsed.path or "/") + \
            ("?" + parsed.query if parsed.query else "")
        return parsed.path or "/", target, \
            urllib.parse.parse_qs(parsed.query)

    def _read_headers_for_cell(self, cell_id: str
                               ) -> Tuple[Dict[str, str],
                                          Optional[Dict[str, str]]]:
        """Forwarded headers for a read against ``cell_id``: the
        client's commit-token vector reduced to that cell's entries
        (prefix stripped — the cell's wait gate speaks the intra-cell
        grammar), plus the honest stale-cells response header when the
        vector named anyone else."""
        fwd = _forwardable(self.headers)
        want = self.headers.get("X-Cook-Min-Offset")
        if want is None or self.router.single_cell:
            return fwd, None
        cell_token, others = strip_for_cell(want, cell_id)
        if cell_token is None:
            fwd.pop("X-Cook-Min-Offset", None)
        else:
            fwd["X-Cook-Min-Offset"] = cell_token
        if others:
            registry.counter_inc("cook_federation_stale_reads_total")
            return fwd, {"X-Cook-Federation-Stale-Cells":
                         ",".join(sorted(others))}
        return fwd, None

    # ------------------------------------------------------------- routing
    def _route(self, method: str) -> None:
        path, target, params = self._target()
        router = self.router
        try:
            # ---- the router's own surface.  Only /debug/federation and
            # the /federation/* admin verbs are claimed unconditionally
            # (no cell serves them); /info, /metrics and /debug/health
            # are router-local ONLY with multiple cells — a single-cell
            # front door proxies them for byte-level wire parity.
            if method == "GET" and path == "/debug/federation":
                router.probe_all()
                self._respond_json(200, router.to_doc())
                return
            parts = [p for p in path.split("/") if p]
            if method == "POST" and len(parts) == 3 \
                    and parts[0] == "federation" \
                    and parts[1] in ("drain", "rejoin", "reclaim"):
                self._body()  # drain any body, keep keep-alive sound
                op = {"drain": router.drain_cell,
                      "rejoin": router.rejoin_cell,
                      "reclaim": router.reclaim_cell}[parts[1]]
                self._respond_json(200, op(parts[2]))
                return

            # ---- single cell: pure reverse proxy, wire-identical
            if router.single_cell:
                handle = next(iter(router.cells.values()))
                body = self._body() if method in ("POST", "PUT",
                                                  "DELETE") else None
                self._proxy(handle, method, target, body)
                return

            if method == "GET" and path == "/debug/health":
                self._respond_json(200, self._health_doc())
                return
            if method == "GET" and path == "/metrics":
                raw = registry.expose().encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(raw)))
                self.end_headers()
                self.wfile.write(raw)
                return
            if method == "GET" and path == "/info":
                self._respond_json(200, {
                    "role": "federation-router",
                    "cells": [h.spec.id for h in router.cells.values()],
                    "single_cell": router.single_cell})
                return

            # ---- multi-cell routing
            if method == "POST" and path in ("/jobs", "/rawscheduler"):
                self._submit()
                return
            if method == "GET":
                self._routed_read(path, target, params, parts)
                return
            if method == "DELETE" and path in ("/jobs", "/rawscheduler"):
                self._routed_kill(target, params)
                return
            if method in ("POST", "PUT") and path == "/retry":
                self._routed_retry(method, target)
                return
            self._respond_json(
                501, {"error": f"{method} {path} is not federated: the "
                               "front door cannot answer it faithfully "
                               "across cells — address the owning cell "
                               "directly (docs/DEPLOY.md multi-cell "
                               "federation)"})
        except RouteRejected as e:
            self._respond_json(e.status,
                              {"error": e.message, **e.extra},
                              extra_headers=e.headers)
        except Exception as e:  # pragma: no cover
            self._respond_json(500, {"error": f"router error: {e}"})

    def _health_doc(self) -> Dict[str, Any]:
        router = self.router
        eligible = router.eligible_cells()
        try:
            staleness = round(min(router.summaries.staleness_s(), 1e12), 3)
        except Exception:
            staleness = None
        return {"healthy": bool(eligible),
                "role": "federation-router",
                "cells_total": len(router.cells),
                "cells_eligible": len(eligible),
                "cells": {h.spec.id: {"breaker": h.breaker.state,
                                      "drained": h.drained}
                          for h in router.cells.values()},
                "summary_staleness_s": staleness}

    # ---------------------------------------------------------- write paths
    def _submit(self) -> None:
        raw = self._body()
        status, headers, resp_raw, cell_id = self.router.submit(
            raw, self._user(), _forwardable(self.headers))
        extra = None
        token = headers.get("X-Cook-Commit-Offset") \
            or headers.get("x-cook-commit-offset")
        if token:
            # the ONE header rewrite the front door performs: qualify
            # the cell's commit token so the client's session vector
            # can span journals
            headers = {k: v for k, v in headers.items()
                       if k.lower() != "x-cook-commit-offset"}
            extra = {"X-Cook-Commit-Offset":
                     qualify_token(cell_id, token)}
        self._respond_raw(status, headers, resp_raw, extra_headers=extra)

    def _routed_kill(self, target: str,
                     params: Dict[str, List[str]]) -> None:
        uuids = params.get("uuid") or params.get("job") or []
        by_cell = self._group_by_cell(uuids)
        if by_cell is None:
            return
        # kill fans out per owning cell; the combined answer is the
        # union (each cell only sees its own uuids)
        merged: Dict[str, Any] = {}
        worst = 200
        for cell_id, cell_uuids in by_cell.items():
            handle = self.router.cell(cell_id)
            q = urllib.parse.urlencode([("uuid", u) for u in cell_uuids])
            base = target.split("?", 1)[0]
            try:
                status, _, raw = handle.request(
                    "DELETE", f"{base}?{q}",
                    headers=_forwardable(self.headers))
            except CellUnreachable as exc:
                self._respond_json(503, {"error": str(exc),
                                         "cell": cell_id},
                                  extra_headers={"Retry-After": "2"})
                return
            if status >= worst:
                worst = status
            try:
                doc = json.loads(raw.decode() or "{}")
                if isinstance(doc, dict):
                    merged.update(doc)
            except ValueError:
                pass
        self._respond_json(worst, merged)

    def _routed_retry(self, method: str, target: str) -> None:
        raw = self._body()
        try:
            body = json.loads(raw.decode() or "{}")
        except ValueError:
            self._respond_json(400, {"error": "malformed retry body"})
            return
        uuids = [str(u) for u in (body.get("jobs") or [])]
        if body.get("job"):
            uuids.append(str(body["job"]))
        cells = {self.router.cell_of_uuid(u) for u in uuids}
        cells.discard(None)
        if len(cells) != 1:
            self._respond_json(
                400 if len(cells) > 1 else 404,
                {"error": "retry batch must target ONE cell's jobs "
                          f"(found {len(cells)} owning cells for "
                          f"{len(uuids)} uuids; split the batch per "
                          "cell)"})
            return
        handle = self.router.cell(cells.pop())
        self._proxy(handle, method, target, raw)

    # ----------------------------------------------------------- read paths
    def _group_by_cell(self,
                       uuids: List[str]) -> Optional[Dict[str, List[str]]]:
        """Owning cell per uuid from the commit ledger; answers the
        request itself (404, honest) when any uuid has no known owner
        and returns None."""
        by_cell: Dict[str, List[str]] = {}
        unknown = []
        for u in uuids:
            cell = self.router.cell_of_uuid(u)
            if cell is None:
                unknown.append(u)
            else:
                by_cell.setdefault(cell, []).append(u)
        if unknown:
            # probe each serving cell for the first unknown uuid rather
            # than failing blind: uuids submitted around a router
            # restart are findable, just not ledgered
            for u in unknown:
                found = self._find_cell(u)
                if found is None:
                    self._respond_json(
                        404, {"error": f"job {u} is unknown to this "
                                       "federation router (not in the "
                                       "commit ledger and no serving "
                                       "cell knows it)"})
                    return None
                by_cell.setdefault(found, []).append(u)
        if not by_cell:
            self._respond_json(400, {"error": "no uuids supplied"})
            return None
        return by_cell

    def _find_cell(self, uuid: str) -> Optional[str]:
        for handle in self.router.cells.values():
            if not handle.serving() or not handle.breaker.allow():
                continue
            try:
                status, _, _ = handle.request("GET", f"/jobs/{uuid}",
                                              headers={})
            except CellUnreachable:
                continue
            if status == 200:
                return handle.spec.id
        return None

    def _routed_read(self, path: str, target: str,
                     params: Dict[str, List[str]],
                     parts: List[str]) -> None:
        router = self.router
        # one-uuid paths: /jobs/{u}, /instances/{t},
        # /debug/job/{u}/timeline route to the owning cell
        uuid_path = None
        if len(parts) == 2 and parts[0] in ("jobs", "instances"):
            uuid_path = parts[1]
        elif len(parts) == 4 and parts[0] == "debug" \
                and parts[1] == "job" and parts[3] == "timeline":
            uuid_path = parts[2]
        if uuid_path is not None:
            cell = router.cell_of_uuid(uuid_path) \
                if parts[0] != "instances" else None
            if cell is None:
                cell = self._find_cell(uuid_path.split("-inst")[0]
                                       if parts[0] == "instances"
                                       else uuid_path)
            if cell is None and parts[0] == "instances":
                # instance ids don't map to job uuids generically:
                # ask each cell
                for handle in router.cells.values():
                    if handle.serving() and handle.breaker.allow():
                        fwd, extra = self._read_headers_for_cell(
                            handle.spec.id)
                        try:
                            status, hs, raw = handle.request(
                                "GET", target, headers=fwd)
                        except CellUnreachable:
                            continue
                        if status == 200:
                            self._respond_raw(status, hs, raw,
                                              extra_headers=extra)
                            return
                self._respond_json(404, {"error":
                                         f"no cell knows {uuid_path}"})
                return
            if cell is None:
                self._respond_json(
                    404, {"error": f"job {uuid_path} is unknown to "
                                   "this federation router"})
                return
            handle = router.cell(cell)
            fwd, extra = self._read_headers_for_cell(cell)
            self._proxy(handle, "GET", target, None,
                        extra_resp_headers=extra, req_headers=fwd)
            return
        if path in ("/jobs", "/rawscheduler", "/group"):
            uuids = params.get("uuid") or []
            if path == "/group" and uuids:
                # group uuids are not ledgered: probe cells for the
                # group and serve the first 200
                for handle in router.cells.values():
                    if not handle.serving() or not handle.breaker.allow():
                        continue
                    fwd, extra = self._read_headers_for_cell(
                        handle.spec.id)
                    try:
                        status, hs, raw = handle.request("GET", target,
                                                         headers=fwd)
                    except CellUnreachable:
                        continue
                    if status == 200:
                        self._respond_raw(status, hs, raw,
                                          extra_headers=extra)
                        return
                self._respond_json(404,
                                   {"error": "no cell knows this group"})
                return
            by_cell = self._group_by_cell(uuids)
            if by_cell is None:
                return
            if len(by_cell) == 1:
                cell_id, cell_uuids = next(iter(by_cell.items()))
                fwd, extra = self._read_headers_for_cell(cell_id)
                self._proxy(router.cell(cell_id), "GET", target, None,
                            extra_resp_headers=extra, req_headers=fwd)
                return
            # uuids span cells: fan out per owning cell, concatenate
            merged_list: List[Any] = []
            stale: List[str] = []
            base = target.split("?", 1)[0]
            for cell_id, cell_uuids in by_cell.items():
                handle = router.cell(cell_id)
                fwd, extra = self._read_headers_for_cell(cell_id)
                if extra:
                    stale.append(extra["X-Cook-Federation-Stale-Cells"])
                q = urllib.parse.urlencode([("uuid", u)
                                            for u in cell_uuids])
                try:
                    status, _, raw = handle.request(
                        "GET", f"{base}?{q}", headers=fwd)
                except CellUnreachable as exc:
                    self._respond_json(503, {"error": str(exc),
                                             "cell": cell_id},
                                      extra_headers={"Retry-After": "2"})
                    return
                if status != 200:
                    self._respond_raw(status, {}, raw)
                    return
                doc = json.loads(raw.decode() or "[]")
                merged_list.extend(doc if isinstance(doc, list)
                                   else [doc])
            self._respond_json(
                200, merged_list,
                extra_headers={"X-Cook-Federation-Stale-Cells":
                               ",".join(sorted(set(",".join(stale)
                                                   .split(","))))}
                if stale else None)
            return
        if path in _FANOUT_CONCAT or path in _FANOUT_UNION \
                or path in ("/usage", "/failure_reasons",
                            "/stats/instances"):
            self._fanout_read(path, target)
            return
        self._respond_json(
            501, {"error": f"GET {path} is not federated — address the "
                           "owning cell directly (docs/DEPLOY.md "
                           "multi-cell federation)"})

    def _fanout_read(self, path: str, target: str) -> None:
        """Fan a read out to every serving cell and merge: lists
        concatenate, /usage sums numbers, /pools unions by name,
        /failure_reasons serves the first answer (identical tables)."""
        router = self.router
        answers: List[Any] = []
        for handle in router.cells.values():
            if not handle.serving() or not handle.breaker.allow():
                continue
            fwd, _ = self._read_headers_for_cell(handle.spec.id)
            try:
                status, _, raw = handle.request("GET", target,
                                                headers=fwd)
            except CellUnreachable:
                continue
            if status != 200:
                self._respond_raw(status, {}, raw)
                return
            try:
                answers.append(json.loads(raw.decode() or "null"))
            except ValueError:
                self._respond_json(502, {"error": "unparseable cell "
                                                  "answer",
                                         "cell": handle.spec.id})
                return
        if not answers:
            self._respond_json(503, {"error": "no serving cell answered"},
                              extra_headers={"Retry-After": "2"})
            return
        if path == "/failure_reasons":
            self._respond_json(200, answers[0])
        elif path in _FANOUT_UNION:
            by_name: Dict[str, Any] = {}
            for doc in answers:
                for item in (doc or []):
                    by_name.setdefault(item.get("name"), item)
            self._respond_json(200, list(by_name.values()))
        elif path == "/usage" or path == "/stats/instances":
            self._respond_json(200, _sum_merge(answers))
        else:
            merged: List[Any] = []
            for doc in answers:
                merged.extend(doc if isinstance(doc, list) else [doc])
            self._respond_json(200, merged)

    # --------------------------------------------------------- verb mapping
    def do_GET(self):
        self._route("GET")

    def do_POST(self):
        self._route("POST")

    def do_PUT(self):
        self._route("PUT")

    def do_DELETE(self):
        self._route("DELETE")


def _sum_merge(docs: List[Any]) -> Any:
    """Recursively merge JSON documents: numbers add, objects merge
    key-wise, lists concatenate, scalars keep the first answer."""
    first = docs[0]
    if isinstance(first, dict):
        out: Dict[str, Any] = {}
        for doc in docs:
            if not isinstance(doc, dict):
                continue
            for k, v in doc.items():
                if k in out:
                    out[k] = _sum_merge([out[k], v])
                else:
                    out[k] = v
        return out
    if isinstance(first, bool):
        return first
    if isinstance(first, (int, float)):
        return sum(d for d in docs if isinstance(d, (int, float))
                   and not isinstance(d, bool))
    if isinstance(first, list):
        out_list: List[Any] = []
        for doc in docs:
            if isinstance(doc, list):
                out_list.extend(doc)
        return out_list
    return first


class _FederationHTTPServer(ThreadingHTTPServer):
    request_queue_size = 128
    daemon_threads = True


class FederationServer:
    """Threaded HTTP wrapper for the front door (mirrors
    rest.api.ApiServer so the daemon lifecycle treats both alike)."""

    def __init__(self, router: FederationRouter,
                 host: str = "127.0.0.1", port: int = 0):
        handler = type("BoundFederationHandler", (_FederationHandler,),
                       {"router": router})
        self.router = router
        self.server = _FederationHTTPServer((host, port), handler)
        self.host, self.port = self.server.server_address
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self.server.serve_forever,
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self.server.shutdown()
        self.server.server_close()
        if self._thread:
            self._thread.join(timeout=5)

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"


def build_federation_node(conf_section: Dict,
                          host: str = "127.0.0.1",
                          port: int = 0) -> FederationServer:
    """Boot-validate a ``"federation"`` conf section and assemble the
    router + front-door server (not yet started — the daemon owns the
    lifecycle)."""
    cfg = FederationConfig.from_conf(dict(conf_section))
    return FederationServer(FederationRouter(cfg), host=host, port=port)
