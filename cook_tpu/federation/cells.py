"""One federated cell as the router sees it.

A cell is a whole cook_tpu deployment — leader, standbys, partitions,
its own journal and election — reachable at one front URL.  The router
never reaches around that URL: everything it knows about a cell comes
from the wire (``/debug/health`` saturation snapshots, the per-user
summary endpoint, response headers), so a cell can be a single
in-process test server or a real multi-host deployment and the routing
tier cannot tell the difference.

The transport is deliberately raw: the router must see each response's
exact status, headers and body bytes to proxy them through unmodified
(wire parity) and to qualify ``X-Cook-Commit-Offset`` headers — a
convenience client that followed redirects or merged tokens itself
would destroy exactly the information the front door exists to
preserve.
"""

from __future__ import annotations

import http.client
import json
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple
from urllib.parse import urlsplit

from ..utils.retry import CircuitBreaker

#: capacity tiers a cell may declare.  ``spot`` capacity is cheap but
#: reclaimable: the router penalizes its score so standard cells absorb
#: steady demand, and a reclaim triggers the mea-culpa re-route path
#: (jobs lose nothing for the platform's decision).
CELL_TIERS = ("standard", "spot")


class CellUnreachable(ConnectionError):
    """The cell did not answer (connect/send/read failure) — recorded
    on the breaker by the caller; distinct from an HTTP error status,
    which IS an answer."""


@dataclass
class CellSpec:
    """Boot-validated declaration of one cell (the ``federation.cells``
    conf entries)."""

    id: str
    url: str
    tier: str = "standard"
    #: data-locality attributes (e.g. ``{"region": "us-east"}``): a job
    #: whose labels pin an attribute routes only to matching cells
    attributes: Dict[str, str] = field(default_factory=dict)
    #: relative capacity weight for load scoring
    weight: float = 1.0

    def __post_init__(self):
        if not self.id or "/" in self.id or "," in self.id:
            # "/" is the token-qualifier separator and "," the vector
            # separator: a cell id containing either would make every
            # session token ambiguous
            raise ValueError(
                f"cell id must be non-empty without '/' or ',', got "
                f"{self.id!r}")
        if not str(self.url).startswith(("http://", "https://")):
            raise ValueError(f"cell {self.id!r} url must be http(s), "
                             f"got {self.url!r}")
        if self.tier not in CELL_TIERS:
            raise ValueError(f"cell {self.id!r} tier must be one of "
                             f"{CELL_TIERS}, got {self.tier!r}")
        if not isinstance(self.attributes, dict):
            raise ValueError(f"cell {self.id!r} attributes must be an "
                             "object of string pairs")
        self.attributes = {str(k): str(v)
                           for k, v in self.attributes.items()}
        if float(self.weight) <= 0:
            raise ValueError(f"cell {self.id!r} weight must be > 0")
        self.weight = float(self.weight)


class CellHandle:
    """Live routing state for one cell: breaker, drain flag, cached
    health snapshot, in-flight counter, and the raw HTTP transport."""

    def __init__(self, spec: CellSpec, failure_threshold: int = 3,
                 reset_timeout_s: float = 5.0,
                 request_timeout_s: float = 5.0):
        self.spec = spec
        self.breaker = CircuitBreaker(
            f"cell:{spec.id}", failure_threshold=failure_threshold,
            reset_timeout_s=reset_timeout_s)
        self.request_timeout_s = float(request_timeout_s)
        #: operator intent: a drained cell takes no NEW demand and its
        #: summary table leaves the global merge (its load was either
        #: finished or re-routed; keeping a tombstone table would
        #: double-count users forever) — the dynamic-cluster drain
        #: contract, one level up
        self.drained = False
        # per-thread keep-alive connections: the front door serves many
        # client threads at once and one shared socket would serialize
        # every proxied exchange behind a lock
        self._local = threading.local()
        self.inflight = 0
        self.routed_total = 0
        self.last_error: Optional[str] = None
        #: last /debug/health snapshot: worst saturation gauge + the
        #: brownout stage, aged so a stale probe decays to "unknown"
        self._health: Dict[str, Any] = {}
        self._health_at = float("-inf")

    # ---------------------------------------------------------- transport
    def _connection(self, scheme: str,
                    netloc: str) -> http.client.HTTPConnection:
        conns = getattr(self._local, "conns", None)
        if conns is None:
            conns = self._local.conns = {}
        conn = conns.get((scheme, netloc))
        if conn is None:
            cls = http.client.HTTPSConnection if scheme == "https" \
                else http.client.HTTPConnection
            conn = cls(netloc, timeout=self.request_timeout_s)
            conns[(scheme, netloc)] = conn
        return conn

    def _drop_connection(self, scheme: str, netloc: str) -> None:
        conns = getattr(self._local, "conns", None) or {}
        conn = conns.pop((scheme, netloc), None)
        if conn is not None:
            try:
                conn.close()
            except Exception:
                pass

    def request(self, method: str, target: str,
                body: Optional[bytes] = None,
                headers: Optional[Dict[str, str]] = None,
                record: bool = True
                ) -> Tuple[int, Dict[str, str], bytes]:
        """One proxied exchange → ``(status, headers, raw_body)``.

        Raises :class:`CellUnreachable` when the cell never answered;
        records breaker outcomes (an HTTP error status is a SERVED
        answer and counts as transport success — a cell refusing one
        bad request must not trip the whole cell's breaker)."""
        parsed = urlsplit(self.spec.url)
        scheme = parsed.scheme or "http"
        netloc = parsed.netloc
        hdrs = dict(headers or {})
        if body is not None:
            hdrs.setdefault("Content-Length", str(len(body)))
        for attempt in (0, 1):
            conn = self._connection(scheme, netloc)
            try:
                conn.request(method, target, body=body, headers=hdrs)
                resp = conn.getresponse()
                raw = resp.read()
            except (http.client.HTTPException, ConnectionError,
                    BrokenPipeError, OSError) as exc:
                self._drop_connection(scheme, netloc)
                if attempt == 0:
                    # a keep-alive socket the cell closed while idle
                    # is not an outage: one fresh-socket retry
                    continue
                self.last_error = f"{type(exc).__name__}: {exc}"
                if record:
                    self.breaker.record_failure()
                raise CellUnreachable(
                    f"cell {self.spec.id} unreachable: "
                    f"{self.last_error}") from exc
            if record:
                self.breaker.record_success()
                self.last_error = None
            return resp.status, dict(resp.getheaders()), raw
        raise AssertionError("unreachable")  # pragma: no cover

    def get_json(self, path: str,
                 headers: Optional[Dict[str, str]] = None) -> Any:
        status, _, raw = self.request("GET", path, headers=headers)
        if status != 200:
            raise CellUnreachable(
                f"cell {self.spec.id} GET {path} -> {status}")
        return json.loads(raw.decode() or "null")

    # ------------------------------------------------------------- health
    def probe_health(self) -> Optional[Dict[str, Any]]:
        """Refresh the cached ``/debug/health`` snapshot; ``None`` when
        the cell did not answer (the breaker already recorded it)."""
        try:
            doc = self.get_json("/debug/health")
        except (CellUnreachable, ValueError):
            return None
        self._health = doc if isinstance(doc, dict) else {}
        self._health_at = time.monotonic()
        return self._health

    def health_age_s(self) -> float:
        return time.monotonic() - self._health_at

    def saturation(self) -> float:
        """Worst normalized saturation gauge from the last health
        probe; 0.0 when never probed (optimism is safe — the breaker
        catches a cell that cannot even answer)."""
        sat = self._health.get("saturation")
        if isinstance(sat, dict) and sat:
            try:
                return max(float(v) for v in sat.values())
            except (TypeError, ValueError):
                return 0.0
        return 0.0

    def browning_out(self) -> bool:
        """PR 17's brownout ladder, read from the cell's own health
        panel: stage >= 3 means the cell is shedding writes — routing
        MORE submissions there would be feeding the fire."""
        stage = self._health.get("admission", {})
        if isinstance(stage, dict):
            try:
                return int(stage.get("brownout_stage", 0)) >= 3
            except (TypeError, ValueError):
                return False
        return False

    # ------------------------------------------------------------ routing
    def eligible(self) -> bool:
        """May NEW demand route here right now?"""
        return (not self.drained) and self.breaker.allow() \
            and not self.browning_out()

    def serving(self) -> bool:
        """Does this cell participate in the global summary merge?
        Drain is the only exclusion: an UNREACHABLE cell stays in the
        merge so its table ages loudly toward the staleness bound
        instead of its users silently vanishing from enforcement."""
        return not self.drained

    def to_doc(self) -> Dict[str, Any]:
        return {
            "id": self.spec.id, "url": self.spec.url,
            "tier": self.spec.tier, "weight": self.spec.weight,
            "attributes": dict(self.spec.attributes),
            "drained": self.drained,
            "breaker": self.breaker.state,
            "inflight": self.inflight,
            "routed_total": self.routed_total,
            "saturation": round(self.saturation(), 4),
            "browning_out": self.browning_out(),
            "health_age_s": (round(self.health_age_s(), 3)
                             if self._health_at > float("-inf") else None),
            "last_error": self.last_error,
        }
