"""Cell-qualified commit tokens.

PR 12 made the commit token a VECTOR of per-partition entries
(``p<P>:<epoch>:<offset>``, comma-joined) that clients merge
latest-per-partition.  Federation prefixes each entry with the id of
the cell whose journal minted it — ``cellA/p0:3:128`` — so one session
token can carry read-your-writes positions against MANY sovereign
journals at once:

- the router qualifies ``X-Cook-Commit-Offset`` response headers with
  the cell that answered the write (multi-cell deployments only; a
  single-cell front door passes tokens through verbatim, which is what
  keeps it wire-identical to a direct cell connection);
- the client merges entries latest-per-``(cell, partition)``
  (:meth:`cook_tpu.client.JobClient._merge_commit_token`);
- on a read, the router strips the vector down to the entries minted
  by the TARGET cell (prefix removed — cells never see cell ids; their
  wait gates speak the intra-cell grammar unchanged) and reports the
  entries it could NOT enforce via ``X-Cook-Federation-Stale-Cells``
  (honest bounded-stale degrade, never a faked read-your-writes).

Entries stay string-opaque end to end, exactly like the partition
vector before them: nothing here parses epochs or offsets.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

#: separator between the cell id and the intra-cell token entry.  "/"
#: cannot appear in an intra-cell entry (digits, ":", leading "p") nor
#: in a validated cell id, so the split is unambiguous.
CELL_SEP = "/"


def split_entry(entry: str) -> Tuple[Optional[str], str]:
    """``cellA/p0:3:128`` → ``("cellA", "p0:3:128")``; an unqualified
    entry returns ``(None, entry)`` unchanged."""
    cell, sep, rest = entry.partition(CELL_SEP)
    if sep and cell and rest:
        return cell, rest
    return None, entry


def qualify_token(cell: str, token: str) -> str:
    """Prefix every entry of a cell-minted token vector with the cell
    id.  Entries that already carry a cell prefix are left alone (a
    router in front of another router must not double-qualify)."""
    out: List[str] = []
    for e in (p.strip() for p in token.split(",")):
        if not e:
            continue
        got, _ = split_entry(e)
        out.append(e if got is not None else f"{cell}{CELL_SEP}{e}")
    return ",".join(out)


def cells_in_token(token: str) -> Set[str]:
    """The set of cell ids a token vector names (unqualified entries
    contribute nothing)."""
    cells: Set[str] = set()
    for e in (p.strip() for p in token.split(",")):
        if e:
            cell, _ = split_entry(e)
            if cell is not None:
                cells.add(cell)
    return cells


def strip_for_cell(token: str, cell: str) -> Tuple[Optional[str],
                                                   Set[str]]:
    """Reduce a (possibly mixed) token vector to what the TARGET cell
    can enforce.

    Returns ``(cell_token, other_cells)``: ``cell_token`` is the
    comma-joined vector of this cell's entries with their prefixes
    stripped plus any unqualified entries passed through verbatim
    (``None`` when nothing remains — the read proceeds ungated);
    ``other_cells`` names every OTHER cell the vector mentions, which
    the caller reports as the unenforced remainder."""
    keep: List[str] = []
    others: Set[str] = set()
    for e in (p.strip() for p in token.split(",")):
        if not e:
            continue
        got, rest = split_entry(e)
        if got is None:
            keep.append(e)
        elif got == cell:
            keep.append(rest)
        else:
            others.add(got)
    return (",".join(keep) if keep else None), others


def merge_token(tokens: Dict[str, str], cell: str, token: str) -> None:
    """Fold one cell-minted token into a per-``(cell, partition)``
    latest-wins map (the router's view of its own recent writes; the
    client keeps its own copy via ``_merge_commit_token``)."""
    for e in (p.strip() for p in token.split(",")):
        if not e:
            continue
        got, rest = split_entry(e)
        key_cell = got if got is not None else cell
        part = rest.partition(":")[0] if rest.startswith("p") \
            and ":" in rest else ""
        tokens[f"{key_cell}{CELL_SEP}{part}"] = rest


def joined(tokens: Dict[str, str]) -> str:
    """The session-token form of a per-(cell, partition) map: each
    entry re-qualified with its cell and sorted for determinism."""
    out = []
    for key in sorted(tokens):
        cell = key.split(CELL_SEP, 1)[0]
        out.append(f"{cell}{CELL_SEP}{tokens[key]}")
    return ",".join(out)
