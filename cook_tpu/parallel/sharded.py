"""Pool-sharded fused scheduling cycle: rank + considerable + match on a
device mesh.

One jitted step runs EVERY pool's rank (DRU segmented prefix sums + sort),
considerable-job admission (pool/group quota, per-user quota, launch-rate
tokens, plugin verdicts, head-of-queue backoff cap — see
ops/considerable.py) and match (greedy bin-pack scan) with pools sharded
over the mesh's "pool" axis via ``shard_map``; cross-pool facts are
reconciled with XLA collectives:

 - per-pool RUNNING usage and quota-group ids are ``all_gather``'d so
   quota-group caps spanning pools (reference: scheduler.clj:2125-2157
   quota-group aggregation) are ENFORCED inside the cycle against a
   globally consistent view — each pool caps its ranked prefix by the
   group's running total, matching the host path's
   Ranker._apply_pool_quota;
 - per-pool matched-resource totals are ``all_gather``'d for the global
   cycle telemetry the reference logs per match cycle
   (scheduler.clj:1210-1280), along with a ``psum`` placement count.

The match job axis is aligned with the rank task axis (running-task rows
are never admitted), so the ranked order permutes match inputs entirely on
device — no host round-trip between rank and match.

This module is the scale axis of the framework (SURVEY.md section 5
"long-context" slot): pools across devices, and within a pool the
job/offer tensors are bucketed so XLA tiles them onto the VPU/MXU.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import dru as dru_ops
from ..ops import match as match_ops
from ..ops.considerable import considerable_body
from ..ops.scan import segmented_cumsum_by_first_idx
from .mesh import POOL_AXIS

INF = jnp.inf


class PoolCycleInputs(NamedTuple):
    """Per-pool padded arrays, stacked on a leading pool axis [P, ...].

    Task/job axes are shared: row t is one task; pending rows double as
    match candidates (job_res/cmask); running rows have pending=False.
    Admission-side arrays come from the host control plane (see
    sched/fused.py): plugin verdicts, rate-limit token budgets, the
    offensive-job filter, backoff caps, and pool/quota-group caps.
    """

    # rank side [P, T, ...]
    usage: jax.Array       # f32[P, T, 4]
    quota: jax.Array       # f32[P, T, 4]
    shares: jax.Array      # f32[P, T, 3]
    first_idx: jax.Array   # i32[P, T]
    user_rank: jax.Array   # i32[P, T]
    pending: jax.Array     # bool[P, T]
    valid: jax.Array       # bool[P, T]
    # admission side
    enqueue_ok: jax.Array        # bool[P, T] False = host-stifled job
    launch_ok: jax.Array         # bool[P, T] launch-plugin verdicts
    tokens: jax.Array            # f32[P, T] user launch-rate budget (inf=off)
    num_considerable: jax.Array  # i32[P] backoff cap on admitted jobs
    pool_quota: jax.Array        # f32[P, 4] pool cap (inf = uncapped)
    group_quota: jax.Array       # f32[P, 4] quota-group cap (inf = uncapped)
    group_id: jax.Array          # i32[P] quota-group id, -1 = none
    # match side
    job_res: jax.Array     # f32[P, T, R]
    cmask: jax.Array       # bool[P, T, H]
    avail: jax.Array       # f32[P, H, R]
    capacity: jax.Array    # f32[P, H, R]

    @classmethod
    def build(cls, *, usage, quota, shares, first_idx, user_rank, pending,
              valid, job_res, cmask, avail, capacity, enqueue_ok=None,
              launch_ok=None, tokens=None, num_considerable=None,
              pool_quota=None, group_quota=None, group_id=None
              ) -> "PoolCycleInputs":
        """Fill permissive defaults for the admission-side arrays (all jobs
        admitted, no caps) so kernel-level callers and tests can exercise
        rank+match alone."""
        P, T = np.shape(pending)[:2]
        ones = jnp.ones((P, T), dtype=bool)
        return cls(
            usage=usage, quota=quota, shares=shares, first_idx=first_idx,
            user_rank=user_rank, pending=pending, valid=valid,
            enqueue_ok=ones if enqueue_ok is None else enqueue_ok,
            launch_ok=ones if launch_ok is None else launch_ok,
            tokens=(jnp.full((P, T), INF, dtype=jnp.float32)
                    if tokens is None else tokens),
            num_considerable=(jnp.full((P,), T, dtype=jnp.int32)
                              if num_considerable is None
                              else num_considerable),
            pool_quota=(jnp.full((P, 4), INF, dtype=jnp.float32)
                        if pool_quota is None else pool_quota),
            group_quota=(jnp.full((P, 4), INF, dtype=jnp.float32)
                         if group_quota is None else group_quota),
            group_id=(jnp.full((P,), -1, dtype=jnp.int32)
                      if group_id is None else group_id),
            job_res=job_res, cmask=cmask, avail=avail, capacity=capacity)


class PoolCycleResult(NamedTuple):
    order: jax.Array          # i32[P, T] rank order (pending first)
    num_ranked: jax.Array     # i32[P] rankable pending count
    dru: jax.Array            # f32[P, T] per-task DRU score (task order)
    assign: jax.Array         # i32[P, T] host or -1, in RANK order
    match_valid: jax.Array    # bool[P, T] admitted for matching (RANK order)
    queue_ok: jax.Array       # bool[P, T] queue membership (RANK order)
    accepted: jax.Array       # bool[P, T] admitted pre-cap (RANK order)
    matched_usage: jax.Array  # f32[P, 4] resources matched per pool (global)
    total_matched: jax.Array  # i32[] global placement count


def _segment_totals(cum: jax.Array, first_idx: jax.Array) -> jax.Array:
    """Broadcast each contiguous segment's total (the value of the inclusive
    prefix sum at the segment's last row) back to every row of the segment."""
    T = first_idx.shape[0]
    pos = jnp.arange(T, dtype=jnp.int32)
    is_last = jnp.concatenate(
        [first_idx[1:] != first_idx[:-1], jnp.ones((1,), dtype=bool)])
    seg_last = jax.lax.cummin(jnp.where(is_last, pos, T - 1), axis=0,
                              reverse=True)
    return cum[seg_last]


def _user_running_base(usage, pending, valid, first_idx) -> jax.Array:
    """f32[T, 4]: each task's user's total RUNNING usage in this pool
    (the accumulator seed of pending-jobs->considerable-jobs,
    scheduler.clj:729 / tools.clj:899-915)."""
    run_usage = usage * (valid & ~pending)[:, None]
    cum_run = segmented_cumsum_by_first_idx(run_usage, first_idx)
    return _segment_totals(cum_run, first_idx)


def _pool_cycle_one(usage, quota, shares, first_idx, user_rank, pending,
                    valid, enqueue_ok, launch_ok, tokens, num_considerable,
                    pool_quota, group_quota, pool_base, group_base,
                    job_res, cmask, avail, capacity,
                    gpu_mode: bool, max_over_quota_jobs: int):
    """One pool's full rank -> considerable -> match, all on device."""
    order, num_ranked, dru, _keep, rankable = dru_ops.rank_body(
        usage, quota, shares, first_idx, user_rank, pending, valid,
        gpu_mode, max_over_quota_jobs)
    run_base = _user_running_base(usage, pending, valid, first_idx)

    # permute every admission input into rank order
    cr = considerable_body(
        usage_r=usage[order], quota_r=quota[order],
        user_r=user_rank[order], run_base_r=run_base[order],
        tokens_r=tokens[order], launch_ok_r=launch_ok[order],
        enqueue_ok_r=enqueue_ok[order], rankable_r=rankable[order],
        pool_base=pool_base, pool_quota=pool_quota,
        group_base=group_base, group_quota=group_quota,
        num_considerable=num_considerable)

    sorted_res = jnp.take(job_res, order, axis=0)
    sorted_mask = jnp.take(cmask, order, axis=0)
    assign, _avail = match_ops.greedy_assign(
        sorted_res, sorted_mask, cr.match_valid, avail, capacity)
    matched = (assign >= 0)
    matched_usage = jnp.sum(sorted_res * matched[:, None], axis=0)[:4]
    return (order, num_ranked, dru, assign, cr.match_valid, cr.queue_ok,
            cr.accepted, matched_usage)


def single_pool_cycle(usage, quota, shares, first_idx, user_rank, pending,
                      valid, job_res, cmask, avail, capacity,
                      gpu_mode: bool = False, max_over_quota_jobs: int = 100,
                      enqueue_ok=None, launch_ok=None, tokens=None,
                      num_considerable=None, pool_quota=None,
                      group_quota=None, group_base=None):
    """Single-chip fused rank+considerable+match step (the framework's
    'forward pass').  Jittable as-is; admission inputs default to
    permissive."""
    T = pending.shape[0]
    ones = jnp.ones((T,), dtype=bool)
    enqueue_ok = ones if enqueue_ok is None else enqueue_ok
    launch_ok = ones if launch_ok is None else launch_ok
    tokens = (jnp.full((T,), INF, dtype=jnp.float32)
              if tokens is None else tokens)
    num_considerable = (jnp.asarray(T, dtype=jnp.int32)
                        if num_considerable is None else num_considerable)
    pool_quota = (jnp.full((4,), INF, dtype=jnp.float32)
                  if pool_quota is None else pool_quota)
    group_quota = (jnp.full((4,), INF, dtype=jnp.float32)
                   if group_quota is None else group_quota)
    pool_base = jnp.sum(usage * (valid & ~pending)[:, None], axis=0)[:4]
    group_base = pool_base if group_base is None else group_base
    (order, num_ranked, dru, assign, _mv, _qok, _acc, _mu) = _pool_cycle_one(
        usage, quota, shares, first_idx, user_rank, pending, valid,
        enqueue_ok, launch_ok, tokens, num_considerable, pool_quota,
        group_quota, pool_base, group_base, job_res, cmask, avail, capacity,
        gpu_mode, max_over_quota_jobs)
    return order, num_ranked, dru, assign


def make_pool_cycle(mesh, *, gpu_mode: bool = False,
                    max_over_quota_jobs: int = 100):
    """Build the jitted pool-sharded cycle for a mesh."""
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    # pools shard over every mesh axis: ("pool",) single-slice, or
    # ("dcn", "pool") with slice-independent pool blocks
    axes = tuple(mesh.axis_names)
    spec = P(axes)

    def cycle_body(inp: PoolCycleInputs) -> PoolCycleResult:
        # Pass 1 (cheap, vmapped): per-pool RUNNING usage for pool quota and
        # for the quota-group all_gather.
        pool_base = jax.vmap(
            lambda u, p, v: jnp.sum(u * (v & ~p)[:, None], axis=0)[:4]
        )(inp.usage, inp.pending, inp.valid)

        # Reconciliation collective #1: running usage + group ids of every
        # pool, so each pool can enforce its quota-group's cap against the
        # global running total (reference: scheduler.clj:2125-2157). On a
        # 1-D mesh this rides ICI; on ("dcn", "pool") it is the only
        # cross-slice traffic, sized [pools, 4] + [pools].
        base_all, gid_all = pool_base, inp.group_id
        for axis in reversed(axes):
            base_all = jax.lax.all_gather(base_all, axis, axis=0, tiled=True)
            gid_all = jax.lax.all_gather(gid_all, axis, axis=0, tiled=True)
        group_base = jax.vmap(
            lambda gid: jnp.sum(
                base_all * ((gid_all == gid) & (gid >= 0))[:, None], axis=0)
        )(inp.group_id)

        # Pass 2: the full fused cycle per local pool.
        per_pool = functools.partial(_pool_cycle_one, gpu_mode=gpu_mode,
                                     max_over_quota_jobs=max_over_quota_jobs)
        (order, num_ranked, dru, assign, match_valid, queue_ok, accepted,
         matched_usage) = jax.vmap(per_pool)(
            inp.usage, inp.quota, inp.shares, inp.first_idx, inp.user_rank,
            inp.pending, inp.valid, inp.enqueue_ok, inp.launch_ok,
            inp.tokens, inp.num_considerable, inp.pool_quota,
            inp.group_quota, pool_base, group_base, inp.job_res, inp.cmask,
            inp.avail, inp.capacity)

        # Reconciliation collective #2: global matched usage + placement
        # count (cycle telemetry, scheduler.clj:1210-1280).
        matched_usage_global = matched_usage
        for axis in reversed(axes):
            matched_usage_global = jax.lax.all_gather(
                matched_usage_global, axis, axis=0, tiled=True)
        total = jax.lax.psum(jnp.sum((assign >= 0).astype(jnp.int32)), axes)
        return PoolCycleResult(order=order, num_ranked=num_ranked, dru=dru,
                               assign=assign, match_valid=match_valid,
                               queue_ok=queue_ok, accepted=accepted,
                               matched_usage=matched_usage_global,
                               total_matched=total)

    sharded = shard_map(
        cycle_body, mesh=mesh,
        in_specs=(PoolCycleInputs(*(spec,) * len(PoolCycleInputs._fields)),),
        out_specs=PoolCycleResult(
            order=spec, num_ranked=spec, dru=spec, assign=spec,
            match_valid=spec, queue_ok=spec, accepted=spec,
            matched_usage=P(), total_matched=P()),
        check_vma=False)
    return jax.jit(sharded)
