"""Pool-sharded fused scheduling cycle: rank + match on a device mesh.

One jitted step runs EVERY pool's rank (DRU segmented prefix sums + sort) and
match (greedy bin-pack scan) with pools sharded over the mesh's "pool" axis
via ``shard_map``; cross-pool facts are reconciled with XLA collectives:

 - per-pool matched-resource totals are ``all_gather``'d so quota-group caps
   spanning pools (reference: scheduler.clj:2125-2157 quota-group
   aggregation) can be enforced against a globally consistent view;
 - a ``psum`` of per-pool placement counts gives the global cycle telemetry
   the reference logs per match cycle (scheduler.clj:1210-1280).

The match job axis is aligned with the rank task axis (running-task rows are
never valid match rows), so the ranked order permutes match inputs entirely
on device — no host round-trip between rank and match.

This module is the scale axis of the framework (SURVEY.md section 5
"long-context" slot): pools across devices, and within a pool the job/offer
tensors are bucketed so XLA tiles them onto the VPU/MXU.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from ..ops import dru as dru_ops
from ..ops import match as match_ops
from .mesh import POOL_AXIS


class PoolCycleInputs(NamedTuple):
    """Per-pool padded arrays, stacked on a leading pool axis [P, ...].

    Task/job axes are shared: row t is one task; pending rows double as
    match candidates (job_res/cmask); running rows have pending=False.
    """

    # rank side [P, T, ...]
    usage: jax.Array       # f32[P, T, 4]
    quota: jax.Array       # f32[P, T, 4]
    shares: jax.Array      # f32[P, T, 3]
    first_idx: jax.Array   # i32[P, T]
    user_rank: jax.Array   # i32[P, T]
    pending: jax.Array     # bool[P, T]
    valid: jax.Array       # bool[P, T]
    # match side
    job_res: jax.Array     # f32[P, T, R]
    cmask: jax.Array       # bool[P, T, H]
    avail: jax.Array       # f32[P, H, R]
    capacity: jax.Array    # f32[P, H, R]


class PoolCycleResult(NamedTuple):
    order: jax.Array          # i32[P, T] rank order (pending first)
    num_ranked: jax.Array     # i32[P]
    dru: jax.Array            # f32[P, T]
    assign: jax.Array         # i32[P, T] host or -1, in RANK order
    matched_usage: jax.Array  # f32[P, 4] resources matched per pool (global view)
    total_matched: jax.Array  # i32[] global placement count


def _rank_one_pool(usage, quota, shares, first_idx, user_rank, pending, valid,
                   gpu_mode: bool, max_over_quota_jobs: int):
    order, num_ranked, dru, _keep, rankable = dru_ops.rank_body(
        usage, quota, shares, first_idx, user_rank, pending, valid,
        gpu_mode, max_over_quota_jobs)
    return order, num_ranked, dru, rankable


def _match_one_pool(job_res, cmask, avail, capacity, valid):
    assign, _avail = match_ops.greedy_assign(job_res, cmask, valid, avail,
                                             capacity)
    return assign


def single_pool_cycle(usage, quota, shares, first_idx, user_rank, pending,
                      valid, job_res, cmask, avail, capacity,
                      gpu_mode: bool = False, max_over_quota_jobs: int = 100):
    """Single-chip fused rank+match step (the framework's 'forward pass'):
    DRU-rank all tasks, permute pending jobs into rank order, greedy
    bin-pack them against the offers. Jittable as-is."""
    order, num_ranked, dru, rankable = _rank_one_pool(
        usage, quota, shares, first_idx, user_rank, pending, valid,
        gpu_mode, max_over_quota_jobs)
    sorted_res = jnp.take(job_res, order, axis=0)
    sorted_mask = jnp.take(cmask, order, axis=0)
    sorted_ok = jnp.take(rankable, order, axis=0)
    assign = _match_one_pool(sorted_res, sorted_mask, avail, capacity,
                             sorted_ok)
    return order, num_ranked, dru, assign


def make_pool_cycle(mesh: Mesh, *, gpu_mode: bool = False,
                    max_over_quota_jobs: int = 100):
    """Build the jitted pool-sharded cycle for a mesh."""

    def cycle_body(inp: PoolCycleInputs) -> PoolCycleResult:
        # local block: leading dim = pools on this device
        def per_pool(usage, quota, shares, first_idx, user_rank, pending,
                     valid, job_res, cmask, avail, capacity):
            order, num_ranked, dru, rankable = _rank_one_pool(
                usage, quota, shares, first_idx, user_rank, pending, valid,
                gpu_mode, max_over_quota_jobs)
            sorted_res = jnp.take(job_res, order, axis=0)
            sorted_mask = jnp.take(cmask, order, axis=0)
            sorted_ok = jnp.take(rankable, order, axis=0)
            assign = _match_one_pool(sorted_res, sorted_mask, avail,
                                     capacity, sorted_ok)
            matched = (assign >= 0)
            matched_usage = jnp.sum(
                sorted_res * matched[:, None], axis=0)[:4]
            return order, num_ranked, dru, assign, matched_usage

        order, num_ranked, dru, assign, matched_usage = jax.vmap(per_pool)(
            inp.usage, inp.quota, inp.shares, inp.first_idx, inp.user_rank,
            inp.pending, inp.valid, inp.job_res, inp.cmask, inp.avail,
            inp.capacity)
        # Reconciliation: every device sees every pool's matched usage
        # (quota groups span pools) and the global placement count. On a
        # 1-D mesh this rides ICI; on a ("dcn", "pool") multi-slice mesh
        # the gather spans both axes — the ONLY cross-slice traffic, sized
        # [pools, 4] + a scalar, which is what belongs on DCN.
        matched_usage_global = matched_usage
        for axis in reversed(axes):
            matched_usage_global = jax.lax.all_gather(
                matched_usage_global, axis, axis=0, tiled=True)
        total = jax.lax.psum(jnp.sum((assign >= 0).astype(jnp.int32)),
                             axes)
        return PoolCycleResult(order=order, num_ranked=num_ranked, dru=dru,
                               assign=assign,
                               matched_usage=matched_usage_global,
                               total_matched=total)

    # pools shard over every mesh axis: ("pool",) single-slice, or
    # ("dcn", "pool") with slice-independent pool blocks
    axes = tuple(mesh.axis_names)
    spec = P(axes)
    sharded = shard_map(
        cycle_body, mesh=mesh,
        in_specs=(PoolCycleInputs(*(spec,) * len(PoolCycleInputs._fields)),),
        out_specs=PoolCycleResult(
            order=spec, num_ranked=spec, dru=spec, assign=spec,
            matched_usage=P(), total_matched=P()),
        check_vma=False)
    return jax.jit(sharded)
