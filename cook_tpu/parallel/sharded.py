"""Pool-sharded fused scheduling cycle: rank + considerable + match on a
device mesh.

One jitted step runs EVERY pool's rank (DRU segmented prefix sums + sort),
considerable-job admission (pool/group quota, per-user quota, launch-rate
tokens, plugin verdicts, head-of-queue backoff cap — see
ops/considerable.py) and match (greedy bin-pack scan) with pools sharded
over the mesh's "pool" axis via ``shard_map``; cross-pool facts are
reconciled with XLA collectives:

 - per-pool RUNNING usage and quota-group ids are ``all_gather``'d so
   quota-group caps spanning pools (reference: scheduler.clj:2125-2157
   quota-group aggregation) are ENFORCED inside the cycle against a
   globally consistent view — each pool caps its ranked prefix by the
   group's running total, matching the host path's
   Ranker._apply_pool_quota;
 - per-pool matched-resource totals are ``all_gather``'d for the global
   cycle telemetry the reference logs per match cycle
   (scheduler.clj:1210-1280), along with a ``psum`` placement count.

The match job axis is aligned with the rank task axis (running-task rows
are never admitted), so the ranked order permutes match inputs entirely on
device — no host round-trip between rank and match.

This module is the scale axis of the framework (SURVEY.md section 5
"long-context" slot): pools across devices, and within a pool the
job/offer tensors are bucketed so XLA tiles them onto the VPU/MXU.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import dru as dru_ops
from ..ops import match as match_ops
from ..ops.considerable import considerable_body
from ..ops.scan import segmented_cumsum_by_first_idx
from .mesh import POOL_AXIS

INF = jnp.inf


class PoolCycleInputs(NamedTuple):
    """Per-pool padded arrays, stacked on a leading pool axis [P, ...].

    Task/job axes are shared: row t is one task; pending rows double as
    match candidates (job_res/cmask); running rows have pending=False.
    Admission-side arrays come from the host control plane (see
    sched/fused.py): plugin verdicts, rate-limit token budgets, the
    offensive-job filter, backoff caps, and pool/quota-group caps.
    """

    # rank side [P, T, ...]
    usage: jax.Array       # f32[P, T, 4]
    quota: jax.Array       # f32[P, T, 4]
    shares: jax.Array      # f32[P, T, 3]
    first_idx: jax.Array   # i32[P, T]
    user_rank: jax.Array   # i32[P, T]
    pending: jax.Array     # bool[P, T]
    valid: jax.Array       # bool[P, T]
    # admission side
    enqueue_ok: jax.Array        # bool[P, T] False = host-stifled job
    launch_ok: jax.Array         # bool[P, T] launch-plugin verdicts
    tokens: jax.Array            # f32[P, T] user launch-rate budget (inf=off)
    num_considerable: jax.Array  # i32[P] backoff cap on admitted jobs
    pool_quota: jax.Array        # f32[P, 4] pool cap (inf = uncapped)
    group_quota: jax.Array       # f32[P, 4] quota-group cap (inf = uncapped)
    group_id: jax.Array          # i32[P] quota-group id, -1 = none
    # match side
    job_res: jax.Array     # f32[P, T, R]
    cmask: jax.Array       # bool[P, T, H]
    avail: jax.Array       # f32[P, H, R]
    capacity: jax.Array    # f32[P, H, R]

    @classmethod
    def build(cls, *, usage, quota, shares, first_idx, user_rank, pending,
              valid, job_res, cmask, avail, capacity, enqueue_ok=None,
              launch_ok=None, tokens=None, num_considerable=None,
              pool_quota=None, group_quota=None, group_id=None
              ) -> "PoolCycleInputs":
        """Fill permissive defaults for the admission-side arrays (all jobs
        admitted, no caps) so kernel-level callers and tests can exercise
        rank+match alone."""
        P, T = np.shape(pending)[:2]
        ones = jnp.ones((P, T), dtype=bool)
        return cls(
            usage=usage, quota=quota, shares=shares, first_idx=first_idx,
            user_rank=user_rank, pending=pending, valid=valid,
            enqueue_ok=ones if enqueue_ok is None else enqueue_ok,
            launch_ok=ones if launch_ok is None else launch_ok,
            tokens=(jnp.full((P, T), INF, dtype=jnp.float32)
                    if tokens is None else tokens),
            num_considerable=(jnp.full((P,), T, dtype=jnp.int32)
                              if num_considerable is None
                              else num_considerable),
            pool_quota=(jnp.full((P, 4), INF, dtype=jnp.float32)
                        if pool_quota is None else pool_quota),
            group_quota=(jnp.full((P, 4), INF, dtype=jnp.float32)
                         if group_quota is None else group_quota),
            group_id=(jnp.full((P,), -1, dtype=jnp.int32)
                      if group_id is None else group_id),
            job_res=job_res, cmask=cmask, avail=avail, capacity=capacity)


class StructuredPoolCycleInputs(NamedTuple):
    """PoolCycleInputs with the dense bool[P, T, H] constraint mask replaced
    by its STRUCTURE — the insight that at the 1M x 50k design point almost
    every row's mask is derivable from per-host vectors (gpu isolation,
    max-tasks, reservations) plus a small exception set of complex jobs.
    The dense mask costs O(T*H) host build + transfer per cycle (500 MB at
    100k x 5k); the structured form transfers O(T + E*H + H):

      host_gpu     bool[P, H]    host has gpu capacity
      host_blocked bool[P, H]    max-tasks-per-host exceeded, or reserved
                                 (owners punch through via exceptions)
      exc_id       i32[P, T]     row -> exception index, -1 = derive base
      exc_mask     bool[P, E, H] full mask rows for exception jobs

    The per-row base is composed ON DEVICE after compaction, so only the
    admitted C rows ever materialize a mask."""

    usage: jax.Array
    quota: jax.Array
    shares: jax.Array
    first_idx: jax.Array
    user_rank: jax.Array
    pending: jax.Array
    valid: jax.Array
    enqueue_ok: jax.Array
    launch_ok: jax.Array
    tokens: jax.Array
    num_considerable: jax.Array
    pool_quota: jax.Array
    group_quota: jax.Array
    group_id: jax.Array
    job_res: jax.Array
    host_gpu: jax.Array
    host_blocked: jax.Array
    exc_id: jax.Array
    exc_mask: jax.Array
    avail: jax.Array
    capacity: jax.Array


# flag bits of CompactPoolCycleInputs.flags: canonically defined beside
# the delta scatter-apply kernel (ops/delta.py) so the state and sched
# layers can reason about wire flags without importing the mesh layer;
# re-exported here under their historical names
from ..ops.delta import (  # noqa: E402,F401
    FLAG_ENQUEUE_OK,
    FLAG_LAUNCH_OK,
    FLAG_PENDING,
    FLAG_USER_FIRST,
    FLAG_VALID,
)


class CompactPoolCycleInputs(NamedTuple):
    """The minimum-transfer form of StructuredPoolCycleInputs: what the
    host must genuinely SEND each cycle, with everything derivable moved
    onto the device — ~5 B/task on the wire vs the naive ~76 (10.8 MB ->
    ~1 MB per cycle at the 100k x 5k design point; decisive over a
    tunneled chip and still the right shape over PCIe):

      - the immutable per-job resource columns live in a DEVICE-RESIDENT
        base mirror (res_base/disk_base, replicated across the mesh; the
        driver appends new rows incrementally and fully resyncs only on
        an index compaction), so the per-cycle per-task upload is just
        the sorted row permutation ``rows`` + one ``flags`` byte,
      - usage (cpus, mem, gpus, 1) and match demand (cpus, mem, gpus,
        disk)*pending are device-side gathers/views of the base,
      - per-USER share/quota/token tables [U, ...] gathered on device via
        user_rank, which is itself re-derived from the FLAG_USER_FIRST
        segment boundaries (as is first_idx),
      - exception rows arrive as a position list ``exc_rows`` (-1 padded)
        and scatter into the [T] exc_id map on device.

    Expanded to StructuredPoolCycleInputs by ``expand_compact`` inside the
    sharded cycle body (so expansion happens post-scatter, per shard)."""

    rows: jax.Array        # i32[P, T] absolute base row per sorted
    #                        position (0 for padding rows; flags=0 there)
    flags: jax.Array       # u8[P, T] FLAG_* bits
    res_base: jax.Array    # f32[N, 4] (cpus, mem, gpus, 1) — REPLICATED
    disk_base: jax.Array   # f32[N] — REPLICATED
    tokens_u: jax.Array    # f32[P, U] per-user launch-rate budget
    shares_u: jax.Array    # f32[P, U, 3]
    quota_u: jax.Array     # f32[P, U, 4]
    num_considerable: jax.Array  # i32[P]
    pool_quota: jax.Array  # f32[P, 4]
    group_quota: jax.Array  # f32[P, 4]
    group_id: jax.Array    # i32[P]
    host_gpu: jax.Array    # bool[P, H]
    host_blocked: jax.Array  # bool[P, H]
    exc_rows: jax.Array    # i32[P, E] task positions of exception jobs, -1 pad
    exc_mask: jax.Array    # bool[P, E, H]
    avail: jax.Array       # f32[P, H, 4]
    capacity: jax.Array    # f32[P, H, 4]


def expand_compact(inp: CompactPoolCycleInputs) -> StructuredPoolCycleInputs:
    """Device-side expansion of the compact wire form (leading pool axis
    preserved; runs inside the shard so every op stays local)."""
    P, T = inp.rows.shape
    usage = jax.vmap(lambda r: inp.res_base[r])(inp.rows)    # [P, T, 4]
    disk = jax.vmap(lambda r: inp.disk_base[r])(inp.rows)    # [P, T]
    flags = inp.flags
    pending = (flags & FLAG_PENDING) != 0
    valid = (flags & FLAG_VALID) != 0
    enqueue_ok = (flags & FLAG_ENQUEUE_OK) != 0
    launch_ok = (flags & FLAG_LAUNCH_OK) != 0
    is_first = (flags & FLAG_USER_FIRST) != 0
    job_res = jnp.concatenate(
        [usage[..., :3], disk[..., None]], axis=-1) * pending[..., None]
    # user_rank / first_idx from the segment boundaries (rows arrive
    # user-sorted; ops/scan.user_segments_from_flags — one derivation
    # shared with the compact rank kernel)
    from ..ops.scan import user_segments_from_flags
    user_rank, first_idx = user_segments_from_flags(is_first, axis=1)
    ur = jnp.clip(user_rank, 0, inp.tokens_u.shape[1] - 1)
    tokens = jnp.take_along_axis(inp.tokens_u, ur, axis=1)
    shares = jax.vmap(lambda s, u: s[u])(inp.shares_u, ur)
    quota = jax.vmap(lambda q, u: q[u])(inp.quota_u, ur)
    # exception-position list -> [T] exc_id map (slot T is the dump row)
    E = inp.exc_rows.shape[1]
    eids = jnp.arange(E, dtype=jnp.int32)[None, :]
    slot = jnp.where(inp.exc_rows >= 0, inp.exc_rows, T)
    exc_id = jax.vmap(
        lambda s, e: jnp.full((T + 1,), -1, dtype=jnp.int32)
        .at[s].set(e, mode="drop")[:T])(slot, jnp.broadcast_to(eids, (P, E)))
    return StructuredPoolCycleInputs(
        usage=usage, quota=quota, shares=shares, first_idx=first_idx,
        user_rank=user_rank, pending=pending, valid=valid,
        enqueue_ok=enqueue_ok, launch_ok=launch_ok, tokens=tokens,
        num_considerable=inp.num_considerable, pool_quota=inp.pool_quota,
        group_quota=inp.group_quota, group_id=inp.group_id,
        job_res=job_res, host_gpu=inp.host_gpu,
        host_blocked=inp.host_blocked, exc_id=exc_id,
        exc_mask=inp.exc_mask, avail=inp.avail, capacity=inp.capacity)


class PoolCycleResult(NamedTuple):
    order: jax.Array          # i32[P, T] rank order (pending first)
    num_ranked: jax.Array     # i32[P] rankable pending count
    dru: jax.Array            # f32[P, T] per-task DRU score (task order)
    assign: jax.Array         # i32[P, T] host or -1, in RANK order
    match_valid: jax.Array    # bool[P, T] admitted for matching (RANK order)
    queue_ok: jax.Array       # bool[P, T] queue membership (RANK order)
    accepted: jax.Array       # bool[P, T] admitted pre-cap (RANK order)
    matched_usage: jax.Array  # f32[P, 4] resources matched per pool (global)
    total_matched: jax.Array  # i32[] global placement count
    # COMPACT outputs: everything the production driver consumes per cycle,
    # O(C + queue) instead of O(T).  The full [T] arrays above stay device-
    # resident (the lazy ranked-queue fetch reads queue_rows on demand);
    # over a tunneled chip the device->host link is the cycle's scarcest
    # resource (~10 MB/s observed vs ~1 GB/s up), so the driver fetches
    # only the [C]-sized candidate arrays + scalars each cycle.
    queue_rows: jax.Array     # i32[P, T] queue members' task rows in rank
    #                           order; first n_queue entries valid
    n_queue: jax.Array        # i32[P] queue membership count
    cand_row: jax.Array       # i32[P, C] task row per admitted slot, -1 empty
    cand_assign: jax.Array    # i32[P, C] assigned host per slot, -1 unmatched
    cand_qpos: jax.Array      # i32[P, C] queue position per slot, -1 empty


def _segment_totals(cum: jax.Array, first_idx: jax.Array) -> jax.Array:
    """Broadcast each contiguous segment's total (the value of the inclusive
    prefix sum at the segment's last row) back to every row of the segment."""
    T = first_idx.shape[0]
    pos = jnp.arange(T, dtype=jnp.int32)
    is_last = jnp.concatenate(
        [first_idx[1:] != first_idx[:-1], jnp.ones((1,), dtype=bool)])
    seg_last = jax.lax.cummin(jnp.where(is_last, pos, T - 1), axis=0,
                              reverse=True)
    return cum[seg_last]


def _user_running_base(usage, pending, valid, first_idx) -> jax.Array:
    """f32[T, 4]: each task's user's total RUNNING usage in this pool
    (the accumulator seed of pending-jobs->considerable-jobs,
    scheduler.clj:729 / tools.clj:899-915)."""
    run_usage = usage * (valid & ~pending)[:, None]
    cum_run = segmented_cumsum_by_first_idx(run_usage, first_idx)
    return _segment_totals(cum_run, first_idx)


def _compact_admitted(order: jax.Array, match_valid: jax.Array,
                      cap: int) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Compact the admitted rows (rank order) into a static-``cap`` prefix.

    The greedy match is a sequential ``lax.scan`` over its job axis, so
    running it over all T rank rows costs O(T) scan steps and a [T, H]
    gather even though at most ``num_considerable`` (<= cap) rows are
    admitted.  Compaction keeps the admitted rows' relative order (greedy
    parity is order-dependent) while shrinking the match to O(cap x H).

    Returns (sel i32[cap] rank positions with sentinel T for empty slots,
    task_idx i32[cap] original task rows, valid bool[cap])."""
    T = match_valid.shape[0]
    k = jnp.cumsum(match_valid.astype(jnp.int32)) - 1
    # each admitted row (within cap) writes its rank position into slot k;
    # everything else lands in the discarded dump slot ``cap``
    slot = jnp.where(match_valid & (k < cap), k, cap)
    sel = jnp.full((cap + 1,), T, dtype=jnp.int32).at[slot].set(
        jnp.arange(T, dtype=jnp.int32))[:cap]
    valid = sel < T
    task_idx = order[jnp.minimum(sel, T - 1)]
    return sel, task_idx, valid


def _rank_admit(usage, quota, shares, first_idx, user_rank, pending, valid,
                enqueue_ok, launch_ok, tokens, num_considerable,
                pool_quota, group_quota, pool_base, group_base,
                gpu_mode: bool, max_over_quota_jobs: int):
    """Shared rank + considerable stage of the fused cycle."""
    order, num_ranked, dru, _keep, rankable = dru_ops.rank_body(
        usage, quota, shares, first_idx, user_rank, pending, valid,
        gpu_mode, max_over_quota_jobs)
    run_base = _user_running_base(usage, pending, valid, first_idx)

    # permute every admission input into rank order
    cr = considerable_body(
        usage_r=usage[order], quota_r=quota[order],
        user_r=user_rank[order], run_base_r=run_base[order],
        tokens_r=tokens[order], launch_ok_r=launch_ok[order],
        enqueue_ok_r=enqueue_ok[order], rankable_r=rankable[order],
        pool_base=pool_base, pool_quota=pool_quota,
        group_base=group_base, group_quota=group_quota,
        num_considerable=num_considerable)
    return order, num_ranked, dru, cr


def _match_tail(order, cr, job_res, mask_of, avail, capacity,
                cap: int, T: int):
    """Compact -> gather/compose masks -> greedy match -> scatter back.
    ``mask_of(task_idx)`` produces bool[C, H] for the compacted rows."""
    sel, task_idx, valid_c = _compact_admitted(order, cr.match_valid, cap)
    res_c = job_res[task_idx] * valid_c[:, None]
    mask_c = mask_of(task_idx) & valid_c[:, None]
    assign_c, _avail = match_ops.greedy_assign(
        res_c, mask_c, valid_c, avail, capacity)
    # scatter back to rank order; sentinel slots (sel == T) drop out
    assign = jnp.full((T,), -1, dtype=jnp.int32).at[sel].set(
        assign_c, mode="drop")
    matched = (assign_c >= 0)
    matched_usage = jnp.sum(res_c * matched[:, None], axis=0)[:4]
    return assign, matched_usage, sel, task_idx, valid_c, assign_c


def _compact_outputs(order, queue_ok, sel, task_idx, valid_c, assign_c,
                     T: int):
    """The driver-facing compact form: queue membership compacted to a
    rank-ordered row list + per-admitted-slot (row, host, queue-position)
    triples, so the host fetches O(C + touched-queue-prefix) bytes per
    cycle instead of four full [T] arrays."""
    qpos = jnp.cumsum(queue_ok.astype(jnp.int32)) - 1
    n_queue = jnp.sum(queue_ok.astype(jnp.int32))
    slot = jnp.where(queue_ok, qpos, T)
    queue_rows = jnp.full((T + 1,), T, dtype=jnp.int32).at[slot].set(
        order, mode="drop")[:T]
    cand_row = jnp.where(valid_c, task_idx, -1)
    cand_assign = jnp.where(valid_c, assign_c, -1)
    cand_qpos = jnp.where(valid_c, qpos[jnp.minimum(sel, T - 1)], -1)
    return queue_rows, n_queue, cand_row, cand_assign, cand_qpos


def _pool_cycle_one(usage, quota, shares, first_idx, user_rank, pending,
                    valid, enqueue_ok, launch_ok, tokens, num_considerable,
                    pool_quota, group_quota, pool_base, group_base,
                    job_res, cmask, avail, capacity,
                    gpu_mode: bool, max_over_quota_jobs: int,
                    considerable_cap: Optional[int] = None):
    """One pool's full rank -> considerable -> match with a DENSE
    bool[T, H] constraint mask.

    ``considerable_cap`` (static) bounds the match problem size; it must be
    >= the dynamic ``num_considerable`` or over-cap admitted rows are left
    unmatched this cycle (the fused driver derives it from the pools'
    max_jobs_considered configs)."""
    T = pending.shape[0]
    order, num_ranked, dru, cr = _rank_admit(
        usage, quota, shares, first_idx, user_rank, pending, valid,
        enqueue_ok, launch_ok, tokens, num_considerable, pool_quota,
        group_quota, pool_base, group_base, gpu_mode, max_over_quota_jobs)
    cap = T if considerable_cap is None else min(considerable_cap, T)
    assign, matched_usage, sel, task_idx, valid_c, assign_c = _match_tail(
        order, cr, job_res, lambda ti: cmask[ti], avail, capacity, cap, T)
    compact = _compact_outputs(order, cr.queue_ok, sel, task_idx, valid_c,
                               assign_c, T)
    return (order, num_ranked, dru, assign, cr.match_valid, cr.queue_ok,
            cr.accepted, matched_usage) + compact


def _pool_cycle_structured(usage, quota, shares, first_idx, user_rank,
                           pending, valid, enqueue_ok, launch_ok, tokens,
                           num_considerable, pool_quota, group_quota,
                           pool_base, group_base, job_res, host_gpu,
                           host_blocked, exc_id, exc_mask, avail, capacity,
                           gpu_mode: bool, max_over_quota_jobs: int,
                           considerable_cap: Optional[int] = None):
    """Fused cycle with the STRUCTURED mask (StructuredPoolCycleInputs):
    per-row masks are composed on device for only the compacted rows —
    gpu bidirectional isolation from job_res, host blocks, and full
    exception rows for the complex-job minority."""
    T = pending.shape[0]
    order, num_ranked, dru, cr = _rank_admit(
        usage, quota, shares, first_idx, user_rank, pending, valid,
        enqueue_ok, launch_ok, tokens, num_considerable, pool_quota,
        group_quota, pool_base, group_base, gpu_mode, max_over_quota_jobs)
    cap = T if considerable_cap is None else min(considerable_cap, T)

    def mask_of(task_idx):
        gpu_rows = job_res[task_idx, 2] > 0
        base = jnp.where(gpu_rows[:, None], host_gpu[None, :],
                         ~host_gpu[None, :]) & ~host_blocked[None, :]
        eid = exc_id[task_idx]
        exc_rows = exc_mask[jnp.maximum(eid, 0)]
        return jnp.where((eid >= 0)[:, None], exc_rows, base)

    assign, matched_usage, sel, task_idx, valid_c, assign_c = _match_tail(
        order, cr, job_res, mask_of, avail, capacity, cap, T)
    compact = _compact_outputs(order, cr.queue_ok, sel, task_idx, valid_c,
                               assign_c, T)
    return (order, num_ranked, dru, assign, cr.match_valid, cr.queue_ok,
            cr.accepted, matched_usage) + compact


def single_pool_cycle(usage, quota, shares, first_idx, user_rank, pending,
                      valid, job_res, cmask, avail, capacity,
                      gpu_mode: bool = False, max_over_quota_jobs: int = 100,
                      enqueue_ok=None, launch_ok=None, tokens=None,
                      num_considerable=None, pool_quota=None,
                      group_quota=None, group_base=None,
                      considerable_cap: Optional[int] = None):
    """Single-chip fused rank+considerable+match step (the framework's
    'forward pass').  Jittable as-is; admission inputs default to
    permissive."""
    T = pending.shape[0]
    ones = jnp.ones((T,), dtype=bool)
    enqueue_ok = ones if enqueue_ok is None else enqueue_ok
    launch_ok = ones if launch_ok is None else launch_ok
    tokens = (jnp.full((T,), INF, dtype=jnp.float32)
              if tokens is None else tokens)
    num_considerable = (jnp.asarray(T, dtype=jnp.int32)
                        if num_considerable is None else num_considerable)
    pool_quota = (jnp.full((4,), INF, dtype=jnp.float32)
                  if pool_quota is None else pool_quota)
    group_quota = (jnp.full((4,), INF, dtype=jnp.float32)
                   if group_quota is None else group_quota)
    pool_base = jnp.sum(usage * (valid & ~pending)[:, None], axis=0)[:4]
    group_base = pool_base if group_base is None else group_base
    (order, num_ranked, dru, assign, *_rest) = _pool_cycle_one(
        usage, quota, shares, first_idx, user_rank, pending, valid,
        enqueue_ok, launch_ok, tokens, num_considerable, pool_quota,
        group_quota, pool_base, group_base, job_res, cmask, avail, capacity,
        gpu_mode, max_over_quota_jobs, considerable_cap)
    return order, num_ranked, dru, assign


def make_pool_cycle(mesh, *, gpu_mode: bool = False,
                    max_over_quota_jobs: int = 100,
                    considerable_cap: Optional[int] = None,
                    structured: bool = False, compact: bool = False):
    """Build the jitted pool-sharded cycle for a mesh.  With
    ``structured=True`` the cycle takes StructuredPoolCycleInputs (no dense
    cmask transfer); with ``compact=True`` (implies structured) it takes
    CompactPoolCycleInputs — the minimum-transfer wire form the production
    fused driver sends — expanded on device by ``expand_compact``."""
    try:
        from jax import shard_map
        _replication_kw = "check_vma"
    except ImportError:  # jax < 0.6 ships shard_map under experimental,
        # where the replication-check kwarg is still called check_rep
        from jax.experimental.shard_map import shard_map
        _replication_kw = "check_rep"
    from jax.sharding import PartitionSpec as P

    # pools shard over every mesh axis: ("pool",) single-slice, or
    # ("dcn", "pool") with slice-independent pool blocks
    axes = tuple(mesh.axis_names)
    spec = P(axes)
    if compact:
        structured = True
        in_type = CompactPoolCycleInputs
    else:
        in_type = StructuredPoolCycleInputs if structured else PoolCycleInputs

    def cycle_body(inp) -> PoolCycleResult:
        if compact:
            inp = expand_compact(inp)
        # Pass 1 (cheap, vmapped): per-pool RUNNING usage for pool quota and
        # for the quota-group all_gather.
        pool_base = jax.vmap(
            lambda u, p, v: jnp.sum(u * (v & ~p)[:, None], axis=0)[:4]
        )(inp.usage, inp.pending, inp.valid)

        # Reconciliation collective #1: running usage + group ids of every
        # pool, so each pool can enforce its quota-group's cap against the
        # global running total (reference: scheduler.clj:2125-2157). On a
        # 1-D mesh this rides ICI; on ("dcn", "pool") it is the only
        # cross-slice traffic, sized [pools, 4] + [pools].
        base_all, gid_all = pool_base, inp.group_id
        for axis in reversed(axes):
            base_all = jax.lax.all_gather(base_all, axis, axis=0, tiled=True)
            gid_all = jax.lax.all_gather(gid_all, axis, axis=0, tiled=True)
        group_base = jax.vmap(
            lambda gid: jnp.sum(
                base_all * ((gid_all == gid) & (gid >= 0))[:, None], axis=0)
        )(inp.group_id)

        # Pass 2: the full fused cycle per local pool.
        common = (inp.usage, inp.quota, inp.shares, inp.first_idx,
                  inp.user_rank, inp.pending, inp.valid, inp.enqueue_ok,
                  inp.launch_ok, inp.tokens, inp.num_considerable,
                  inp.pool_quota, inp.group_quota, pool_base, group_base,
                  inp.job_res)
        if structured:
            per_pool = functools.partial(
                _pool_cycle_structured, gpu_mode=gpu_mode,
                max_over_quota_jobs=max_over_quota_jobs,
                considerable_cap=considerable_cap)
            extra = (inp.host_gpu, inp.host_blocked, inp.exc_id,
                     inp.exc_mask, inp.avail, inp.capacity)
        else:
            per_pool = functools.partial(
                _pool_cycle_one, gpu_mode=gpu_mode,
                max_over_quota_jobs=max_over_quota_jobs,
                considerable_cap=considerable_cap)
            extra = (inp.cmask, inp.avail, inp.capacity)
        (order, num_ranked, dru, assign, match_valid, queue_ok, accepted,
         matched_usage, queue_rows, n_queue, cand_row, cand_assign,
         cand_qpos) = jax.vmap(per_pool)(*common, *extra)

        # Reconciliation collective #2: global matched usage + placement
        # count (cycle telemetry, scheduler.clj:1210-1280).
        matched_usage_global = matched_usage
        for axis in reversed(axes):
            matched_usage_global = jax.lax.all_gather(
                matched_usage_global, axis, axis=0, tiled=True)
        total = jax.lax.psum(jnp.sum((assign >= 0).astype(jnp.int32)), axes)
        return PoolCycleResult(order=order, num_ranked=num_ranked, dru=dru,
                               assign=assign, match_valid=match_valid,
                               queue_ok=queue_ok, accepted=accepted,
                               matched_usage=matched_usage_global,
                               total_matched=total, queue_rows=queue_rows,
                               n_queue=n_queue, cand_row=cand_row,
                               cand_assign=cand_assign, cand_qpos=cand_qpos)

    # pool-sharded on every field except the device-resident base mirrors,
    # which are replicated (every shard gathers its own pools' rows)
    replicated = {"res_base", "disk_base"}
    in_spec = in_type(*(P() if f in replicated else spec
                        for f in in_type._fields))
    sharded = shard_map(
        cycle_body, mesh=mesh,
        in_specs=(in_spec,),
        out_specs=PoolCycleResult(
            order=spec, num_ranked=spec, dru=spec, assign=spec,
            match_valid=spec, queue_ok=spec, accepted=spec,
            matched_usage=P(), total_matched=P(), queue_rows=spec,
            n_queue=spec, cand_row=spec, cand_assign=spec, cand_qpos=spec),
        **{_replication_kw: False})
    # instrumented by the CALLER: sched/fused.py wraps make_pool_cycle's
    # product as instrument_jit("fused.pool_cycle", ...) — wrapping here
    # too would double-count every compile
    return jax.jit(sharded)  # cs-lint: allow=jit-uninstrumented
