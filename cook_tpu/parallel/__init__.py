from .mesh import POOL_AXIS, pool_mesh  # noqa: F401
from .sharded import (  # noqa: F401
    PoolCycleInputs,
    PoolCycleResult,
    make_pool_cycle,
)
