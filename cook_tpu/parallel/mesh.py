"""Device mesh helpers for pool-sharded scheduling.

The TPU-build equivalent of the reference's per-pool concurrency (reference:
per-pool handlers round-robin triggered, scheduler.clj:2491-2517): pools
shard across a 1-D "pool" mesh axis; cross-pool reconciliation (quota groups,
global DRU telemetry) rides ICI collectives (SURVEY.md section 2.7).
"""

from __future__ import annotations

from typing import Dict, List, Optional

import jax
import numpy as np
from jax.sharding import Mesh

POOL_AXIS = "pool"
DCN_AXIS = "dcn"


class ShardAlignmentError(ValueError):
    """The PartitionMap's pool groups and the mesh pool-shard layout
    disagree: a pool's write-plane partition and its resident-buffer
    shard would be owned by DIFFERENT controller processes (double-owned
    or orphaned resident state).  Raised at daemon boot — a config
    error, never a silent split-brain."""


def shard_of_partition(partition: int, count: int, n_shards: int) -> int:
    """Which controller shard owns write-plane ``partition``: partitions
    map onto shards in contiguous blocks, so a shard's pools are also a
    contiguous block of the pool-stacked [P, ...] mesh arrays — the same
    slice ``parallel.mesh.pool_sharding`` commits to that shard's
    devices.  ``count`` must divide evenly into ``n_shards`` blocks."""
    if n_shards < 1:
        raise ShardAlignmentError(f"shards must be >= 1, got {n_shards}")
    if count % n_shards != 0:
        raise ShardAlignmentError(
            f"{count} write-plane partitions do not divide over "
            f"{n_shards} controller shards; partition blocks must be "
            "equal so every shard's resident slice has one owner")
    if not 0 <= partition < count:
        raise ShardAlignmentError(
            f"partition {partition} out of range [0, {count})")
    return partition // (count // n_shards)


def shard_of_pool(pmap, pool: str, n_shards: int) -> int:
    """Controller shard owning ``pool``: its PartitionMap partition's
    contiguous block (``pmap`` is a state.partition.PartitionMap)."""
    return shard_of_partition(pmap.partition_of(pool), pmap.count, n_shards)


def validate_shard_alignment(pmap, n_shards: int,
                             declared: Optional[Dict[str, int]] = None
                             ) -> Dict[int, List[str]]:
    """Boot-time cross-check (ISSUE 19 satellite): the PartitionMap's
    pool groups and the mesh ``pool_sharding`` layout must be the SAME
    partition.  ``declared`` is the operator's explicit pool -> mesh
    shard table (config ``partitions.shard_pools``); every declared pool
    must land on the shard its write-plane partition routes to, and
    every declared shard index must exist.  Returns the validated
    shard -> sorted pool names layout (explicit pools only; hash-routed
    pools follow their partition block by construction).  Raises
    :class:`ShardAlignmentError` with the offending pool on mismatch —
    a mismatched declaration would silently double-own or orphan the
    pool's resident buffers."""
    layout: Dict[int, List[str]] = {s: [] for s in range(n_shards)}
    for pool in sorted(getattr(pmap, "pools", {}) or {}):
        layout[shard_of_pool(pmap, pool, n_shards)].append(pool)
    for pool, shard in sorted((declared or {}).items()):
        if not 0 <= int(shard) < n_shards:
            raise ShardAlignmentError(
                f"shard_pools[{pool!r}] = {shard} but only shards "
                f"[0, {n_shards}) exist")
        owner = shard_of_pool(pmap, pool, n_shards)
        if int(shard) != owner:
            raise ShardAlignmentError(
                f"pool {pool!r} is declared on mesh shard {shard} but "
                f"its write-plane partition {pmap.partition_of(pool)} "
                f"belongs to controller shard {owner}: the partition "
                "map and the mesh pool_sharding layout must agree "
                "(one partition = one process = one mesh shard)")
        if pool not in layout[owner]:
            layout[owner].append(pool)
            layout[owner].sort()
    return layout


def pool_mesh(n_devices: Optional[int] = None) -> Mesh:
    """1-D mesh over the pool axis; single-slice, collectives ride ICI."""
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (POOL_AXIS,))


def multislice_pool_mesh(n_slices: int,
                         devices_per_slice: Optional[int] = None) -> Mesh:
    """2-D ("dcn", "pool") mesh for multi-slice topologies: pools shard over
    BOTH axes (each slice owns an independent pool block — pool cycles never
    communicate within a cycle except reconciliation), so the only
    cross-slice traffic is the small matched-usage all-gather / placement
    psum, which is exactly what belongs on DCN; everything bandwidth-heavy
    stays slice-local on ICI (SURVEY.md section 5 distributed-backend
    mapping)."""
    devices = jax.devices()
    if devices_per_slice is None:
        if len(devices) % n_slices != 0:
            raise ValueError(
                f"{len(devices)} devices not divisible into {n_slices} "
                "slices; pass devices_per_slice explicitly")
        devices_per_slice = len(devices) // n_slices
    need = n_slices * devices_per_slice
    if len(devices) < need:
        raise ValueError(f"need {need} devices, have {len(devices)}")
    grid = np.array(devices[:need]).reshape(n_slices, devices_per_slice)
    return Mesh(grid, (DCN_AXIS, POOL_AXIS))


def pool_sharding(mesh: Mesh):
    """NamedSharding that splits a [P, ...] pool-stacked array over every
    mesh axis — the committed placement for DEVICE-RESIDENT cycle state
    (sched/fused.py resident pack): each pool shard owns its own slice of
    the resident rows/flags buffers, so the per-cycle delta scatter and
    the fused cycle's shard_map read the same owner-local memory instead
    of resharding an uncommitted host upload every dispatch."""
    from jax.sharding import NamedSharding, PartitionSpec
    return NamedSharding(mesh, PartitionSpec(mesh.axis_names))
