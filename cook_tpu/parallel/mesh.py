"""Device mesh helpers for pool-sharded scheduling.

The TPU-build equivalent of the reference's per-pool concurrency (reference:
per-pool handlers round-robin triggered, scheduler.clj:2491-2517): pools
shard across a 1-D "pool" mesh axis; cross-pool reconciliation (quota groups,
global DRU telemetry) rides ICI collectives (SURVEY.md section 2.7).
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh

POOL_AXIS = "pool"
DCN_AXIS = "dcn"


def pool_mesh(n_devices: Optional[int] = None) -> Mesh:
    """1-D mesh over the pool axis; single-slice, collectives ride ICI."""
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (POOL_AXIS,))


def multislice_pool_mesh(n_slices: int,
                         devices_per_slice: Optional[int] = None) -> Mesh:
    """2-D ("dcn", "pool") mesh for multi-slice topologies: pools shard over
    BOTH axes (each slice owns an independent pool block — pool cycles never
    communicate within a cycle except reconciliation), so the only
    cross-slice traffic is the small matched-usage all-gather / placement
    psum, which is exactly what belongs on DCN; everything bandwidth-heavy
    stays slice-local on ICI (SURVEY.md section 5 distributed-backend
    mapping)."""
    devices = jax.devices()
    if devices_per_slice is None:
        if len(devices) % n_slices != 0:
            raise ValueError(
                f"{len(devices)} devices not divisible into {n_slices} "
                "slices; pass devices_per_slice explicitly")
        devices_per_slice = len(devices) // n_slices
    need = n_slices * devices_per_slice
    if len(devices) < need:
        raise ValueError(f"need {need} devices, have {len(devices)}")
    grid = np.array(devices[:need]).reshape(n_slices, devices_per_slice)
    return Mesh(grid, (DCN_AXIS, POOL_AXIS))


def pool_sharding(mesh: Mesh):
    """NamedSharding that splits a [P, ...] pool-stacked array over every
    mesh axis — the committed placement for DEVICE-RESIDENT cycle state
    (sched/fused.py resident pack): each pool shard owns its own slice of
    the resident rows/flags buffers, so the per-cycle delta scatter and
    the fused cycle's shard_map read the same owner-local memory instead
    of resharding an uncommitted host upload every dispatch."""
    from jax.sharding import NamedSharding, PartitionSpec
    return NamedSharding(mesh, PartitionSpec(mesh.axis_names))
