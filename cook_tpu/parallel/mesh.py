"""Device mesh helpers for pool-sharded scheduling.

The TPU-build equivalent of the reference's per-pool concurrency (reference:
per-pool handlers round-robin triggered, scheduler.clj:2491-2517): pools
shard across a 1-D "pool" mesh axis; cross-pool reconciliation (quota groups,
global DRU telemetry) rides ICI collectives (SURVEY.md section 2.7).
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh

POOL_AXIS = "pool"


def pool_mesh(n_devices: Optional[int] = None) -> Mesh:
    """1-D mesh over the pool axis. With multi-slice topologies a 2-D
    ("slice", "pool") mesh would put independent pools on DCN and keep
    reconciliation collectives on ICI; single-slice uses all devices."""
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (POOL_AXIS,))
