"""Lint engine: walk the package, run the passes, diff against the
checked-in baseline (see package doc and docs/ANALYSIS.md).

Suppression surfaces, in precedence order:

1. **inline pragma** — a ``# cs-lint: allow=<check-id>`` comment on the
   flagged line (or the line above) suppresses that check there; use it
   when the justification reads best at the site.
2. **baseline** — ``analysis/baseline.json`` holds
   ``{"suppressions": [{"fingerprint": ..., "justification": ...}]}``
   entries.  Fingerprints are ``check:path:scope:detail`` — line-number
   free, so edits above a flagged site don't churn the baseline.  Every
   entry MUST carry a one-line justification; stale entries (matching
   nothing) are reported so the baseline can only shrink honestly.
"""

from __future__ import annotations

import ast
import json
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Dict, List, Optional

_PRAGMA = "# cs-lint: allow="


@dataclass
class Finding:
    check: str      #: pass/check id, e.g. "lock-blocking-call"
    path: str       #: repo-relative path
    line: int
    scope: str      #: enclosing function qualname (or surface name)
    detail: str     #: stable token (dotted call, kernel name, ...)
    message: str
    suppressed_by: Optional[str] = None  #: "pragma" | "baseline"

    @property
    def fingerprint(self) -> str:
        return f"{self.check}:{self.path}:{self.scope}:{self.detail}"

    def to_doc(self) -> Dict[str, Any]:
        return {"check": self.check, "path": self.path,
                "line": self.line, "scope": self.scope,
                "detail": self.detail, "message": self.message,
                "fingerprint": self.fingerprint,
                **({"suppressed_by": self.suppressed_by}
                   if self.suppressed_by else {})}


#: machine-readable result document version (``--json`` consumers pin
#: this; bump on any breaking shape change and note it in
#: docs/ANALYSIS.md)
SCHEMA_VERSION = 2


@dataclass
class LintResult:
    findings: List[Finding] = field(default_factory=list)
    suppressed: List[Finding] = field(default_factory=list)
    stale_baseline: List[str] = field(default_factory=list)
    files_scanned: int = 0
    errors: List[str] = field(default_factory=list)
    #: whole-program analysis stats (call resolution coverage,
    #: fixpoint iterations) — the `unresolved` bucket made explicit
    callgraph: Dict[str, Any] = field(default_factory=dict)
    #: static may-be-held-at-acquisition edges (family-normalized
    #: docs), for `--lock-coverage` and the /debug/health diff
    lock_edges: List[Dict[str, Any]] = field(default_factory=list)
    #: set when findings were restricted to a changed-files set
    changed_only: bool = False

    @property
    def ok(self) -> bool:
        """Zero unsuppressed findings (the exit-0 contract).  Parse
        errors also fail (an unparseable file is an unlinted file), and
        so do STALE baseline entries — the CLI and the tier-1 self-lint
        golden must render the same verdict on the same tree, and the
        baseline may only shrink honestly.  (In ``--changed`` mode the
        stale check is skipped — entries for unchanged files are not
        stale just because those files were filtered out; the full-repo
        pass stays the gate.)"""
        return (not self.findings and not self.errors
                and not self.stale_baseline)

    def to_doc(self) -> Dict[str, Any]:
        return {"schema": SCHEMA_VERSION,
                "ok": self.ok,
                "files_scanned": self.files_scanned,
                "summary": {
                    "findings": len(self.findings),
                    "suppressed": len(self.suppressed),
                    "stale_baseline": len(self.stale_baseline),
                    "errors": len(self.errors),
                    "changed_only": self.changed_only,
                },
                "callgraph": dict(self.callgraph),
                "findings": [f.to_doc() for f in self.findings],
                "suppressed": [f.to_doc() for f in self.suppressed],
                "stale_baseline": list(self.stale_baseline),
                "lock_edges": list(self.lock_edges),
                "errors": list(self.errors)}


def default_baseline_path() -> Path:
    return Path(__file__).resolve().parent / "baseline.json"


def load_baseline(path: Optional[Path] = None) -> Dict[str, str]:
    """fingerprint -> justification."""
    path = Path(path) if path is not None else default_baseline_path()
    if not path.exists():
        return {}
    doc = json.loads(path.read_text(encoding="utf-8"))
    out: Dict[str, str] = {}
    for entry in doc.get("suppressions", []):
        out[entry["fingerprint"]] = entry.get("justification", "")
    return out


def _pragma_allows(src_lines: List[str], line: int, check: str) -> bool:
    for ln in (line, line - 1):
        if 1 <= ln <= len(src_lines):
            text = src_lines[ln - 1]
            i = text.find(_PRAGMA)
            if i >= 0:
                # a malformed pragma (nothing after allow=) suppresses
                # nothing — it must not crash the run
                tokens = text[i + len(_PRAGMA):].split()
                allowed = tokens[0].rstrip(",;") if tokens else ""
                if allowed in (check, "all"):
                    return True
    return False


def run_lint(package_root: Optional[Path] = None,
             docs_root: Optional[Path] = None,
             baseline: Optional[Path] = None,
             changed: Optional[set] = None) -> LintResult:
    """Run every pass over ``package_root`` (default: the installed
    cook_tpu package) and the registry diff against ``docs_root``
    (default: ``<repo>/docs`` next to the package when present).

    ``changed`` (a set of finding paths — package-relative like
    ``state/store.py``, or doc paths like ``docs/ANALYSIS.md``)
    restricts REPORTED findings to those files: the whole-program
    analysis still runs over the full tree (interprocedural summaries
    need every module), only the report is filtered — the
    ``cs lint --changed`` sub-second inner loop.  Stale-baseline
    enforcement is skipped in that mode (docs/ANALYSIS.md exit
    contract); the full-repo pass remains the tier-1 gate."""
    from .passes import PASSES, registry_completeness
    from .summaries import run_interprocedural

    if package_root is None:
        package_root = Path(__file__).resolve().parent.parent
    package_root = Path(package_root)
    if docs_root is None:
        cand = package_root.parent / "docs"
        docs_root = cand if cand.exists() else None
    base = load_baseline(baseline)
    result = LintResult()
    raw: List[tuple] = []  # (finding, src_lines)
    trees: Dict[str, ast.Module] = {}
    sources: Dict[str, List[str]] = {}

    for path in sorted(package_root.rglob("*.py")):
        if "__pycache__" in path.parts:
            continue
        relpath = path.relative_to(package_root).as_posix()
        try:
            src = path.read_text(encoding="utf-8")
            tree = ast.parse(src, filename=str(path))
        except (OSError, SyntaxError) as e:
            result.errors.append(f"{relpath}: {e}")
            continue
        result.files_scanned += 1
        trees[relpath] = tree
        src_lines = src.splitlines()
        sources[relpath] = src_lines
        for _name, fn in PASSES:
            for f in fn(path, relpath, tree, src_lines):
                raw.append((f, src_lines))

    # whole-program passes: call graph + effect-summary fixpoint
    # (docs/ANALYSIS.md interprocedural section).  An internal failure
    # here is an ERROR, not a silent pass skip.
    try:
        interproc = run_interprocedural(package_root, trees)
    except Exception as e:  # pragma: no cover - analysis bug surface
        result.errors.append(f"interprocedural analysis failed: {e!r}")
    else:
        result.callgraph = interproc.stats
        result.lock_edges = [e.to_doc() for _k, e in
                             sorted(interproc.edges.items())]
        for f in interproc.findings:
            raw.append((f, sources.get(f.path, [])))

    for f in registry_completeness(package_root, docs_root):
        raw.append((f, []))

    seen_fingerprints = set()
    for f, src_lines in raw:
        seen_fingerprints.add(f.fingerprint)
        if src_lines and _pragma_allows(src_lines, f.line, f.check):
            f.suppressed_by = "pragma"
            result.suppressed.append(f)
        elif f.fingerprint in base:
            f.suppressed_by = "baseline"
            result.suppressed.append(f)
        else:
            result.findings.append(f)
    if changed is not None:
        result.changed_only = True
        result.findings = [f for f in result.findings
                           if f.path in changed]
        result.stale_baseline = []
    else:
        result.stale_baseline = sorted(
            fp for fp in base if fp not in seen_fingerprints)
    # deterministic order — byte-stable across runs for the same tree
    result.findings.sort(
        key=lambda f: (f.path, f.line, f.check, f.detail))
    return result
