"""Static registry extractor: harvest observability names from call
sites and diff them against the docs registries.

One implementation shared by the lint CLI's registry-completeness pass
and the tier-1 doc-check tests (tests/test_observability.py) — the three
runtime harvesters that used to live inline in the tests are retired
onto this module so the contract cannot drift between the two surfaces.

Harvested surfaces:

=====================  =====================================  =================
what                   call-site pattern                      registry
=====================  =====================================  =================
metric names           ``registry.counter_inc/gauge_set/       docs/OBSERVABILITY.md
                       observe[_many]/.time("cook_...")``
span names             ``tracing.span("...")`` /               docs/OBSERVABILITY.md
                       ``tracer.record_finished("...")``
fault points           ``injector/_faults.fire("...")`` /      docs/ROBUSTNESS.md
                       ``should_fire("...")`` / ``arm("...")``
CycleRecord fields     ``flight.CycleRecord.to_doc()`` keys    docs/OBSERVABILITY.md
debug endpoints        ``API_ROUTES`` entries under            docs/OBSERVABILITY.md
                       ``/debug`` / ``/metrics``               (endpoint table)
=====================  =====================================  =================
"""

from __future__ import annotations

import re
from pathlib import Path
from typing import Dict, Iterable, Set

_METRIC_RE = re.compile(
    r'(?:counter_inc|gauge_set|gauge_clear|observe_many|observe|\.time)\('
    r'\s*["\'](cook_[a-z0-9_]+)')
_SPAN_RE = re.compile(
    r'(?:tracing\.span|tracer\.span|record_finished)\(\s*["\']([^"\']+)')
_FAULT_RE = re.compile(
    r'(?:\.fire|\.should_fire|injector\.arm)\(\s*\n?\s*'
    r'["\']([a-z0-9._]+)["\']')
# observability-plane route registrations (rest/api.py API_ROUTES): the
# operator-facing /debug/* and /metrics* surface must appear in the
# OBSERVABILITY.md endpoint table — a panel nobody can discover is a
# panel nobody uses
_ROUTE_RE = re.compile(
    r'\(\s*"(?:GET|POST|DELETE|PUT|PATCH)",\s*"(/(?:debug|metrics)[^"]*)"')
# a backticked endpoint row in the doc: optional method word, the path,
# optional ?query= suffix; <uuid>-style placeholders normalize to the
# route table's {uuid} form
_DOC_ROUTE_RE = re.compile(
    r'`(?:(?:GET|POST|DELETE|PUT|PATCH)\s+)?'
    r'(/(?:debug|metrics)[^`?\s]*)(?:\?[^`]*)?`')


def _py_files(root: Path) -> Iterable[Path]:
    for path in sorted(Path(root).rglob("*.py")):
        if "__pycache__" in path.parts:
            continue
        yield path


def _harvest_all(root: Path,
                 patterns: Dict[str, re.Pattern]) -> Dict[str, Set[str]]:
    """One pass over the tree: each file is read once and every pattern
    applied to it (run_lint + the four doc-check tests would otherwise
    re-read ~100 files per surface)."""
    out: Dict[str, Set[str]] = {key: set() for key in patterns}
    for path in _py_files(root):
        try:
            text = path.read_text(encoding="utf-8")
        except OSError:
            continue
        for key, pattern in patterns.items():
            for m in pattern.finditer(text):
                name = m.group(1)
                # placeholder names in docstrings/examples ("...") are
                # not real call sites
                if any(c.isalnum() for c in name):
                    out[key].add(name)
    return out


def _harvest(root: Path, pattern: re.Pattern) -> Set[str]:
    return _harvest_all(root, {"only": pattern})["only"]


def harvest_metrics(root: Path) -> Set[str]:
    """Every metric NAME emitted anywhere under ``root``."""
    return _harvest(root, _METRIC_RE)


def harvest_spans(root: Path) -> Set[str]:
    """Every span name opened (or recorded post-hoc) under ``root``."""
    return _harvest(root, _SPAN_RE)


def harvest_fault_points(root: Path) -> Set[str]:
    """Every fault-point name consulted or armed under ``root``.
    Only dotted names count (``store.journal.append``): the sim's
    ``injector.arm(point, ...)`` loops over variables, which don't
    match, and test-local synthetic points are out of scope."""
    return {n for n in _harvest(root, _FAULT_RE) if "." in n}


def harvest_endpoints(root: Path) -> Set[str]:
    """Every ``/debug*`` / ``/metrics*`` route path registered in an
    ``API_ROUTES``-style table under ``root``."""
    return _harvest(root, _ROUTE_RE)


def documented_endpoints(doc_text: str) -> Set[str]:
    """The endpoint paths the doc's tables register (backticked, method
    word and ``?query=`` suffix tolerated, ``<x>`` == ``{x}``)."""
    return {re.sub(r"<([^<>]+)>", r"{\1}", m.group(1))
            for m in _DOC_ROUTE_RE.finditer(doc_text)}


def cycle_record_fields() -> Set[str]:
    """The exported ``/debug/cycles`` schema — ``to_doc()`` keys of a
    fresh CycleRecord (some slots are renamed on export)."""
    from ..utils.flight import CycleRecord
    return set(CycleRecord(1, "fused").to_doc())


def journal_record_kinds() -> Set[str]:
    """The DECLARED journal record kinds — the protocol registry
    ``state.store.JOURNAL_RECORD_KINDS`` (docs/ROBUSTNESS.md
    replay-completeness contract).  The static diff against written /
    handled kinds lives in the journal-record pass
    (:func:`cook_tpu.analysis.summaries.journal_record_findings`);
    this accessor is the runtime-facing twin for tests and tooling."""
    from ..state.store import JOURNAL_RECORD_KINDS
    return set(JOURNAL_RECORD_KINDS)


def documented(doc_text: str, name: str, metric: bool = False) -> bool:
    """Is ``name`` registered in the doc?  Registries reference names in
    backticks; counters may be registered under their exposed ``_total``
    form."""
    if f"`{name}`" in doc_text:
        return True
    return metric and f"`{name}_total`" in doc_text


def diff_registries(package_root: Path, docs_root: Path
                    ) -> Dict[str, Set[str]]:
    """All four registry diffs at once: surface -> set of names used in
    code but missing from the registry doc.  Empty sets everywhere =
    the registries are complete."""
    obs = (Path(docs_root) / "OBSERVABILITY.md")
    rob = (Path(docs_root) / "ROBUSTNESS.md")
    obs_text = obs.read_text(encoding="utf-8") if obs.exists() else ""
    rob_text = rob.read_text(encoding="utf-8") if rob.exists() else ""
    harvested = _harvest_all(package_root, {
        "metric": _METRIC_RE, "span": _SPAN_RE, "fault": _FAULT_RE,
        "endpoint": _ROUTE_RE})
    doc_endpoints = documented_endpoints(obs_text)
    out: Dict[str, Set[str]] = {
        "metric": {n for n in harvested["metric"]
                   if not documented(obs_text, n, metric=True)},
        "span": {n for n in harvested["span"]
                 if not documented(obs_text, n)},
        "fault-point": {n for n in harvested["fault"] if "." in n
                        if not documented(rob_text, n)},
        "endpoint": {n for n in harvested["endpoint"]
                     if n not in doc_endpoints},
        # the CycleRecord schema comes from the IMPORTED flight module,
        # so this surface only applies when scanning the real package
        # (fixture trees have no /debug/cycles schema to drift)
        "cycle-field": ({n for n in cycle_record_fields()
                         if not documented(obs_text, n)}
                        if (Path(package_root) / "utils"
                            / "flight.py").exists() else set()),
    }
    return out
