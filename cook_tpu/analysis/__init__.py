"""Repo-native static analysis (``cs lint`` / ``python -m cook_tpu.lint``).

System-specific static checking in the Engler et al. (SOSP'01) sense:
the invariants this repo's review rounds kept re-finding by hand are
machine-checked here —

* **lock-discipline** — no blocking work (fsync, sleep, socket/RPC,
  replication ack waits) lexically inside ``with self._lock``/``_mu``
  blocks or in functions documented to run with a lock held, except the
  explicitly baselined by-design sites (the WAL fsync IS the contract);
* **jit-hygiene** — every ``jax.jit``/``pjit`` site wrapped in
  ``ops.telemetry.instrument_jit`` (recompile storms must be visible),
  no host ``np.`` calls, wall-clock/RNG, or Python branches on traced
  values inside jitted kernel bodies;
* **registry-completeness** — every metric / span / fault-point /
  CycleRecord field harvested from call sites must appear in the docs
  registries (docs/OBSERVABILITY.md, docs/ROBUSTNESS.md), replacing the
  three runtime doc-check tests with one extractor shared by test and
  CLI (:mod:`cook_tpu.analysis.registry`);
* **interprocedural effect summaries** — a whole-repo call graph
  (:mod:`cook_tpu.analysis.callgraph`) plus a per-function effect
  fixpoint (:mod:`cook_tpu.analysis.summaries`) extend the lexical
  passes over call chains: transitive blocking-under-lock, a static
  lock-order edge set diffed against the dynamic sanitizer's observed
  edges (``cs lint --lock-coverage``, ``/debug/health`` → ``locks``),
  verified ``_locked``/"caller holds" contracts, and the
  journal-record protocol-completeness registry
  (``state.store.JOURNAL_RECORD_KINDS``).

Findings flow through a checked-in baseline (``analysis/baseline.json``)
so the repo lints clean and NEW violations fail tier-1.  The dynamic
half of the rail — the runtime lock-order sanitizer — lives in
``cook_tpu/utils/locks.py``.  See docs/ANALYSIS.md.
"""

from .engine import Finding, LintResult, run_lint  # noqa: F401
