"""Whole-repo call graph for the interprocedural effect analysis.

The per-function lint passes (passes.py) are LEXICAL: they see only the
statements written inside one ``with self._lock`` block and must trust
the ``_locked``-suffix / "caller holds" convention.  This module builds
the call graph those passes lack, in the compositional style RacerD
(Blackshear et al., OOPSLA'18) showed scales to exactly this shape of
codebase: parse every module once, resolve calls bottom-up, and let the
summary fixpoint (summaries.py) propagate effects over the edges.

Resolution tiers, most to least precise:

``direct``
    module-level functions and imported symbols by name, class
    constructors (→ ``__init__``), ``Class.method`` classmethod form.
``self``
    ``self.m(...)`` dispatched against the enclosing class and its
    repo-known base chain.
``typed``
    ``x.m(...)`` where ``x``'s class is known — from a parameter
    annotation, a local ``x = ClassName(...)`` / ``x = self.attr``
    assignment, or a ``self.attr = ClassName(...)`` /
    ``self.attr: ClassName`` binding harvested class-wide.
``unique``
    bounded dynamic dispatch: an attribute call whose method name is
    defined by exactly ONE repo class (and is not a common stdlib-ish
    name) resolves there.
``dynamic``
    everything else that could still be repo code — callback variables
    being called (``sub(tx_id, events)``), attribute calls whose name
    matches two or more repo classes.  These land in the explicit
    **unresolved bucket** reported as coverage; the lock-edge analysis
    over-approximates them against the *escaping set* (every function
    whose reference is ever taken as a value), and the blocking
    analysis deliberately ignores them (a may-block guess through an
    unresolved callback would drown the report in noise — the dynamic
    sanitizer owns that residue).

Lock identity: attributes assigned ``named_lock("store")`` /
``named_rlock(...)`` / ``NamedLock(...)`` resolve to their declared
name (an f-string / ``"store" + sfx`` suffix keeps the literal prefix,
i.e. the rank FAMILY); plain ``threading.Lock()``-style mutex
attributes get a pseudo name ``~Class.attr`` — they participate in
blocking and contract checks but not in the named lock-order graph.
"""

from __future__ import annotations

import ast
import builtins
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Dict, List, Optional, Set, Tuple

_BUILTIN_NAMES = frozenset(dir(builtins))

#: attribute names treated as mutexes (shared with passes.py's lexical
#: pass — keep in sync)
LOCK_ATTRS = {"_lock", "_mu", "_notify_lock"}

#: method names too common to trust the unique-definition fallback on:
#: resolving `.get()` to the one repo class defining `get` would wire
#: half the codebase to it
COMMON_METHOD_NAMES = frozenset({
    "get", "put", "set", "add", "pop", "append", "extend", "items",
    "keys", "values", "update", "copy", "clear", "close", "open",
    "start", "stop", "run", "send", "recv", "read", "write", "join",
    "count", "index", "sort", "split", "strip", "encode", "decode",
    "wait", "notify", "notify_all", "acquire", "release", "submit",
    "flush", "load", "loads", "dump", "dumps", "format", "group",
    "match", "search", "findall", "sub", "info", "debug", "warning",
    "error", "exception", "exists", "mkdir", "name", "next", "reset",
    "snapshot", "poll", "fire", "step", "to_doc", "tell", "seek",
})

#: "caller holds <lock>" docstring parser (the repo contract idiom).
#: Accepts ``caller holds _lock`` / ``caller holds self._lock`` /
#: ``caller holds ``self._lock``⁠`` / ``caller holds the store lock``.
CONTRACT_RE = re.compile(
    r"caller holds\s+(?:the\s+)?`*(?:self\.)?"
    r"(?:(?P<attr>_[A-Za-z0-9_]+|[A-Za-z]\w*_lock|[A-Za-z]\w*_mu)"
    r"|(?P<named>[A-Za-z][\w.\[\]]*)`*\s+lock)", re.IGNORECASE)


def family(name: str) -> str:
    """Rank family of a lock name (utils/locks.py): the base with any
    bracketed per-instance suffix stripped (``store[p2]`` → ``store``)."""
    return name.split("[", 1)[0]


def parse_contract_lock(doc: str) -> Tuple[bool, Optional[str]]:
    """(has_caller_holds_contract, lock token or None).

    The token is the raw docstring form: an attribute (``_lock``,
    ``_mat_lock``) or a named-lock word from the "the X lock" phrasing
    (``store``).  ``(True, None)`` = the contract names no lock — the
    lexical pass warns (``lock-contract-unnamed``) and the
    interprocedural verifier has nothing to verify."""
    low = (doc or "").lower()
    if "caller holds" not in low:
        return False, None
    m = CONTRACT_RE.search(doc or "")
    if m is None:
        return True, None
    attr = m.group("attr")
    if attr:
        return True, attr
    named = m.group("named")
    # "caller holds the lock" backtracks into matching the article
    # itself as the name — an unnamed contract, not a lock called "the"
    if named and named.lower() in ("the", "a", "an", "its", "this",
                                   "that", "own", "same"):
        return True, None
    return True, named


@dataclass
class LockRef:
    """A resolved lock identity at a use site."""
    name: str          #: family name ("store") or pseudo "~Class.attr"
    named: bool        #: True when created via named_lock/NamedLock

    @property
    def attr_tail(self) -> str:
        return self.name.rsplit(".", 1)[-1]


@dataclass
class CallSite:
    callee: str                 #: resolved function id
    line: int
    kind: str                   #: direct|self|typed|unique|ctor
    held: Tuple[str, ...]       #: lock names held lexically at the site


@dataclass
class DynamicSite:
    """An unresolved call (callback variable / ambiguous dispatch).

    ``candidates`` bounds the dispatch when the method name narrows it
    (every repo method of that name) — the edge over-approximation
    uses it instead of the whole escaping set.  ``counted=False``
    marks common-name attribute calls (``.get()``, ``.poll()``) that
    are kept OUT of the coverage denominator (they would drown the
    signal) but still contribute bounded edges, so the static edge set
    stays a superset of anything runtime can observe."""
    name: str
    line: int
    held: Tuple[str, ...]
    candidates: Tuple[str, ...] = ()
    counted: bool = True


@dataclass
class FuncInfo:
    fid: str
    module: str
    relpath: str
    cls: Optional[str]          #: class id ("state.store.Store") or None
    name: str
    qualscope: str              #: file-local qualname ("Store.transact")
    line: int
    calls: List[CallSite] = field(default_factory=list)
    dynamic_calls: List[DynamicSite] = field(default_factory=list)
    external_calls: int = 0
    #: direct blocking ops: (op label, dotted call, line, held locks)
    blocks: List[Tuple[str, str, int, Tuple[str, ...]]] = \
        field(default_factory=list)
    #: direct lock acquisitions: (LockRef, line, held-at-acquisition)
    acquires: List[Tuple[LockRef, int, Tuple[str, ...]]] = \
        field(default_factory=list)
    spawns_thread: bool = False
    #: lock this function runs under BY CONTRACT (``_locked`` suffix /
    #: "caller holds" docstring), resolved to a LockRef
    requires_lock: Optional[LockRef] = None
    requires_source: Optional[str] = None   #: "suffix" | "docstring"
    #: contract present but no lock nameable (warned by the verifier)
    contract_unnamed: bool = False


@dataclass
class ClassInfo:
    cid: str
    module: str
    name: str
    base_names: List[str] = field(default_factory=list)
    bases: List[str] = field(default_factory=list)      #: resolved cids
    methods: Dict[str, str] = field(default_factory=dict)  #: name -> fid
    attr_types: Dict[str, str] = field(default_factory=dict)
    lock_attrs: Dict[str, LockRef] = field(default_factory=dict)


@dataclass
class ModuleInfo:
    module: str
    relpath: str
    tree: ast.Module
    #: name -> ("func", fid) | ("class", cid) | ("module", modname)
    symbols: Dict[str, Tuple[str, str]] = field(default_factory=dict)
    constants: Dict[str, Any] = field(default_factory=dict)


@dataclass
class CallGraph:
    package: str
    functions: Dict[str, FuncInfo] = field(default_factory=dict)
    classes: Dict[str, ClassInfo] = field(default_factory=dict)
    modules: Dict[str, ModuleInfo] = field(default_factory=dict)
    #: function ids whose reference escapes as a VALUE (callback
    #: registration, thread target, stored handler) — the bounded
    #: over-approximation target for dynamic call sites
    escaping: Set[str] = field(default_factory=set)
    #: method name -> cids defining it (dispatch fallback index)
    method_index: Dict[str, List[str]] = field(default_factory=dict)

    # ------------------------------------------------------------ lookups
    def resolve_method(self, cid: str, name: str,
                       _seen: Optional[Set[str]] = None) -> Optional[str]:
        """Method lookup through the repo-known base chain."""
        seen = _seen or set()
        while cid and cid not in seen:
            seen.add(cid)
            ci = self.classes.get(cid)
            if ci is None:
                return None
            fid = ci.methods.get(name)
            if fid is not None:
                return fid
            for base in ci.bases:
                got = self.resolve_method(base, name, seen)
                if got is not None:
                    return got
            return None
        return None

    def class_lock(self, cid: str, attr: str) -> Optional[LockRef]:
        seen: Set[str] = set()
        stack = [cid]
        while stack:
            c = stack.pop()
            if c in seen:
                continue
            seen.add(c)
            ci = self.classes.get(c)
            if ci is None:
                continue
            ref = ci.lock_attrs.get(attr)
            if ref is not None:
                return ref
            stack.extend(ci.bases)
        return None

    def class_attr_type(self, cid: str, attr: str) -> Optional[str]:
        seen: Set[str] = set()
        stack = [cid]
        while stack:
            c = stack.pop()
            if c in seen:
                continue
            seen.add(c)
            ci = self.classes.get(c)
            if ci is None:
                continue
            t = ci.attr_types.get(attr)
            if t is not None:
                return t
            stack.extend(ci.bases)
        return None

    def stats(self) -> Dict[str, Any]:
        resolved = sum(len(f.calls) for f in self.functions.values())
        dynamic = sum(1 for f in self.functions.values()
                      for ds in f.dynamic_calls if ds.counted)
        external = sum(f.external_calls for f in self.functions.values())
        total = resolved + dynamic
        return {
            "functions": len(self.functions),
            "classes": len(self.classes),
            "modules": len(self.modules),
            "calls_resolved": resolved,
            "calls_unresolved": dynamic,
            "calls_external": external,
            "escaping_functions": len(self.escaping),
            "resolution_coverage": round(resolved / total, 4)
            if total else 1.0,
        }


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

def _dotted(node: ast.AST) -> str:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    elif isinstance(node, ast.Call):
        parts.append(_dotted(node.func) + "()")
    return ".".join(reversed(parts))


def _str_prefix(node: ast.AST) -> Optional[Tuple[str, bool]]:
    """Best-effort ``(literal, exact)`` of a string expression: a
    constant (exact), or the constant head of an f-string /
    ``"a" + x`` (a prefix).  This is how ``named_rlock("store" +
    _sfx)`` / ``f"store[p{i}]"`` resolve to their rank FAMILY while an
    exact ``"store[p0]"`` literal keeps its sibling-distinguishing
    suffix."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value, True
    if isinstance(node, ast.JoinedStr):
        if node.values and isinstance(node.values[0], ast.Constant):
            return str(node.values[0].value), len(node.values) == 1
        return None
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        got = _str_prefix(node.left)
        return (got[0], False) if got else None
    return None


_LOCK_CTORS = ("named_lock", "named_rlock", "NamedLock", "NamedRLock")
_PLAIN_LOCK_CTORS = ("Lock", "RLock")


def _lock_from_ctor(call: ast.Call, consts: Dict[str, Any],
                    owner: str, attr: str) -> Optional[LockRef]:
    """LockRef when ``call`` constructs a mutex, else None."""
    head = _dotted(call.func).rsplit(".", 1)[-1]
    if head in _LOCK_CTORS:
        name = None
        if call.args:
            got = _str_prefix(call.args[0])
            if got is not None:
                # an exact literal keeps its suffix (sibling checks);
                # a computed suffix collapses to the rank family
                name = got[0] if got[1] else family(got[0])
            elif isinstance(call.args[0], ast.Name):
                const = consts.get(call.args[0].id)
                if isinstance(const, str):
                    name = const
        if name is not None:
            return LockRef(name=name, named=True)
        return LockRef(name=f"~{owner}.{attr}", named=False)
    if head in _PLAIN_LOCK_CTORS:
        return LockRef(name=f"~{owner}.{attr}", named=False)
    return None


def _module_name(relpath: str) -> str:
    mod = relpath[:-3] if relpath.endswith(".py") else relpath
    mod = mod.replace("/", ".")
    if mod.endswith(".__init__"):
        mod = mod[: -len(".__init__")]
    return mod


# ---------------------------------------------------------------------------
# phase 1: modules, classes, symbols
# ---------------------------------------------------------------------------

def _collect_module(cg: CallGraph, relpath: str,
                    tree: ast.Module) -> None:
    module = _module_name(relpath)
    mi = ModuleInfo(module=module, relpath=relpath, tree=tree)
    cg.modules[module] = mi
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Constant):
            mi.constants[node.targets[0].id] = node.value.value
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fid = f"{module}.{node.name}"
            mi.symbols[node.name] = ("func", fid)
            cg.functions[fid] = FuncInfo(
                fid=fid, module=module, relpath=relpath, cls=None,
                name=node.name, qualscope=node.name, line=node.lineno)
        elif isinstance(node, ast.ClassDef):
            cid = f"{module}.{node.name}"
            mi.symbols[node.name] = ("class", cid)
            ci = ClassInfo(cid=cid, module=module, name=node.name,
                           base_names=[_dotted(b) for b in node.bases])
            cg.classes[cid] = ci
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                    fid = f"{cid}.{sub.name}"
                    ci.methods[sub.name] = fid
                    cg.functions[fid] = FuncInfo(
                        fid=fid, module=module, relpath=relpath,
                        cls=cid, name=sub.name,
                        qualscope=f"{node.name}.{sub.name}",
                        line=sub.lineno)


def _resolve_imports(cg: CallGraph) -> None:
    pkg = cg.package
    for mi in cg.modules.values():
        parts = mi.module.split(".") if mi.module else []
        is_pkg = mi.relpath.endswith("__init__.py")
        # the package a relative import anchors at: the module itself
        # for a package __init__, its parent otherwise
        pkg_parts = parts if is_pkg else parts[:-1]
        for node in ast.walk(mi.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    target = alias.name
                    if target == pkg or target.startswith(pkg + "."):
                        target = target[len(pkg):].lstrip(".")
                    bound = alias.asname or alias.name.split(".")[0]
                    if target in cg.modules:
                        mi.symbols[bound] = ("module", target)
                    else:
                        mi.symbols.setdefault(
                            bound, ("external", alias.name))
            elif isinstance(node, ast.ImportFrom):
                if node.level:
                    anchor = pkg_parts[: len(pkg_parts)
                                       - (node.level - 1)] \
                        if node.level - 1 <= len(pkg_parts) else []
                    base = ".".join(
                        anchor + ([p for p in
                                   (node.module or "").split(".") if p]))
                else:
                    base = node.module or ""
                    if base == pkg:
                        base = ""
                    elif base.startswith(pkg + "."):
                        base = base[len(pkg) + 1:]
                for alias in node.names:
                    bound = alias.asname or alias.name
                    sub = f"{base}.{alias.name}" if base else alias.name
                    src = cg.modules.get(base)
                    if src is not None and alias.name in src.symbols:
                        mi.symbols[bound] = src.symbols[alias.name]
                    elif sub in cg.modules:
                        mi.symbols[bound] = ("module", sub)
                    else:
                        mi.symbols.setdefault(
                            bound, ("external",
                                    f"{node.module or '.'}."
                                    f"{alias.name}"))

def _link_classes(cg: CallGraph) -> None:
    # class base linkage (after symbols settle)
    for ci in cg.classes.values():
        mi = cg.modules[ci.module]
        for bname in ci.base_names:
            head = bname.split(".", 1)[0]
            sym = mi.symbols.get(head)
            if sym and sym[0] == "class":
                ci.bases.append(sym[1])
            elif sym and sym[0] == "module" and "." in bname:
                tail = bname.split(".", 1)[1]
                src = cg.modules.get(sym[1])
                if src:
                    s2 = src.symbols.get(tail)
                    if s2 and s2[0] == "class":
                        ci.bases.append(s2[1])
            elif bname in [c.name for c in cg.classes.values()
                           if c.module == ci.module]:
                ci.bases.append(f"{ci.module}.{bname}")
    for ci in cg.classes.values():
        for mname in ci.methods:
            cg.method_index.setdefault(mname, []).append(ci.cid)


# ---------------------------------------------------------------------------
# phase 2: class attribute types + lock attrs
# ---------------------------------------------------------------------------

def _class_symbol(mi: ModuleInfo, cg: CallGraph,
                  node: ast.AST) -> Optional[str]:
    """cid when ``node`` names a repo class (Name or module.Attr)."""
    if isinstance(node, ast.Name):
        sym = mi.symbols.get(node.id)
        if sym and sym[0] == "class":
            return sym[1]
    elif isinstance(node, ast.Attribute) and isinstance(
            node.value, ast.Name):
        sym = mi.symbols.get(node.value.id)
        if sym and sym[0] == "module":
            src = cg.modules.get(sym[1])
            if src:
                s2 = src.symbols.get(node.attr)
                if s2 and s2[0] == "class":
                    return s2[1]
    return None


def _collect_class_attrs(cg: CallGraph) -> None:
    for ci in cg.classes.values():
        mi = cg.modules[ci.module]
        cls_node = None
        for node in mi.tree.body:
            if isinstance(node, ast.ClassDef) and node.name == ci.name:
                cls_node = node
                break
        if cls_node is None:
            continue
        for node in ast.walk(cls_node):
            target = value = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                target, value = node.targets[0], node.value
            elif isinstance(node, ast.AnnAssign):
                target, value = node.target, node.value
            if not (isinstance(target, ast.Attribute)
                    and isinstance(target.value, ast.Name)
                    and target.value.id == "self"):
                continue
            attr = target.attr
            if isinstance(value, ast.Call):
                ref = _lock_from_ctor(value, mi.constants,
                                      ci.name, attr)
                if ref is not None:
                    ci.lock_attrs.setdefault(attr, ref)
                    continue
                cid = _class_symbol(mi, cg, value.func)
                if cid is not None:
                    ci.attr_types.setdefault(attr, cid)
                    continue
            if isinstance(node, ast.AnnAssign):
                cid = _class_symbol(mi, cg, node.annotation)
                if cid is not None:
                    ci.attr_types.setdefault(attr, cid)


# ---------------------------------------------------------------------------
# phase 3: per-function body walk
# ---------------------------------------------------------------------------

#: imported lazily to avoid a cycle at module import time
def _blocking_table():
    from .passes import BLOCKING_CALLS
    return BLOCKING_CALLS


class _BodyWalker(ast.NodeVisitor):
    """One function body: calls (with held-lock sets), lock regions
    (``with`` items and manual ``.acquire()``), blocking ops, escaping
    references, thread spawns."""

    def __init__(self, cg: CallGraph, fi: FuncInfo,
                 params: Dict[str, str]):
        self.cg = cg
        self.fi = fi
        self.mi = cg.modules[fi.module]
        self.locals: Dict[str, str] = dict(params)  #: var -> cid
        self.held: List[str] = []
        if fi.requires_lock is not None:
            self.held.append(fi.requires_lock.name)
        self._blocking = _blocking_table()

    # ---------------------------------------------------------- type env
    def _type_of(self, node: ast.AST) -> Optional[str]:
        if isinstance(node, ast.Name):
            if node.id == "self" and self.fi.cls:
                return self.fi.cls
            return self.locals.get(node.id)
        if isinstance(node, ast.Attribute):
            base = self._type_of(node.value)
            if base is not None:
                return self.cg.class_attr_type(base, node.attr)
        if isinstance(node, ast.Call):
            cid = _class_symbol(self.mi, self.cg, node.func)
            return cid
        return None

    def _lock_of(self, node: ast.AST) -> Optional[LockRef]:
        """LockRef when ``node`` is a mutex expression."""
        if isinstance(node, ast.Attribute):
            attr = node.attr
            owner = self._type_of(node.value)
            if owner is not None:
                ref = self.cg.class_lock(owner, attr)
                if ref is not None:
                    return ref
            if attr in LOCK_ATTRS or attr.endswith("_lock"):
                oname = (self.cg.classes[owner].name
                         if owner in self.cg.classes else "*")
                return LockRef(name=f"~{oname}.{attr}", named=False)
        elif isinstance(node, ast.Name):
            # a local alias of a lock is rare; only typed attrs resolve
            pass
        return None

    # --------------------------------------------------------- assignment
    def visit_Assign(self, node):  # noqa: N802
        t = self._type_of(node.value)
        if t is not None:
            for target in node.targets:
                if isinstance(target, ast.Name):
                    self.locals[target.id] = t
        self.generic_visit(node)

    def visit_AnnAssign(self, node):  # noqa: N802
        if isinstance(node.target, ast.Name):
            cid = _class_symbol(self.mi, self.cg, node.annotation)
            if cid is not None:
                self.locals[node.target.id] = cid
        self.generic_visit(node)

    # ------------------------------------------------------------ regions
    def visit_With(self, node):  # noqa: N802
        acquired = 0
        for item in node.items:
            ref = self._lock_of(item.context_expr)
            if ref is None:
                self.visit(item.context_expr)
                if item.optional_vars is not None:
                    self.visit(item.optional_vars)
            else:
                self.fi.acquires.append(
                    (ref, item.context_expr.lineno, tuple(self.held)))
                self.held.append(ref.name)
                acquired += 1
        for child in node.body:
            self.visit(child)
        if acquired:
            del self.held[-acquired:]

    visit_AsyncWith = visit_With

    # a nested def / lambda is a NEW execution context: it is analyzed
    # as its own function node; do not descend here
    def visit_FunctionDef(self, node):  # noqa: N802
        pass

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):  # noqa: N802
        pass

    # -------------------------------------------------------------- calls
    def visit_Call(self, node):  # noqa: N802
        name = _dotted(node.func)
        held = tuple(self.held)
        # manual lock acquisition: `self._notify_lock.acquire(...)` /
        # `cluster.kill_lock.acquire_read()` holds the lock from here
        # on; the matching `.release*()` (the try/finally idiom) ends
        # the region in visit order.  Imprecision over-holds (a
        # conditionally-failed try-acquire still counts), never
        # under-holds.
        if isinstance(node.func, ast.Attribute) and node.func.attr in (
                "acquire", "acquire_read", "acquire_write"):
            ref = self._lock_of(node.func.value)
            if ref is not None:
                self.fi.acquires.append((ref, node.lineno, held))
                if ref.name not in self.held:
                    self.held.append(ref.name)
        elif isinstance(node.func, ast.Attribute) and node.func.attr in (
                "release", "release_read", "release_write"):
            ref = self._lock_of(node.func.value)
            if ref is not None and ref.name in self.held:
                # remove the innermost hold of that name
                for i in range(len(self.held) - 1, -1, -1):
                    if self.held[i] == ref.name:
                        del self.held[i]
                        break
        # direct blocking ops (the lexical pass's table)
        for sub, op in self._blocking:
            if sub in name:
                self.fi.blocks.append((op, name, node.lineno, held))
                break
        if ".Thread" in name or name == "Thread":
            self.fi.spawns_thread = True
        self._resolve_call(node, name, held)
        # arguments may carry escaping references / nested calls
        for arg in node.args:
            self.visit(arg)
        for kw in node.keywords:
            self.visit(kw.value)
        # the func expression itself: visit attribute bases for escaping
        if isinstance(node.func, ast.Attribute):
            self.visit(node.func.value)

    def _add(self, callee: str, line: int, kind: str,
             held: Tuple[str, ...]) -> None:
        self.fi.calls.append(CallSite(callee=callee, line=line,
                                      kind=kind, held=held))

    def _resolve_call(self, node: ast.Call, name: str,
                      held: Tuple[str, ...]) -> None:
        fn = node.func
        if isinstance(fn, ast.Name):
            sym = self.mi.symbols.get(fn.id)
            if sym and sym[0] == "func":
                self._add(sym[1], node.lineno, "direct", held)
            elif sym and sym[0] == "class":
                init = self.cg.resolve_method(sym[1], "__init__")
                if init is not None:
                    self._add(init, node.lineno, "ctor", held)
                else:
                    self.fi.external_calls += 1
            elif sym is not None or fn.id in _BUILTIN_NAMES:
                # an external import or a builtin
                self.fi.external_calls += 1
            else:
                # a bare variable being called: callback dispatch —
                # the explicit unresolved bucket
                self.fi.dynamic_calls.append(
                    DynamicSite(name=fn.id, line=node.lineno, held=held))
            return
        if not isinstance(fn, ast.Attribute):
            self.fi.external_calls += 1
            return
        mname = fn.attr
        # module alias: utils.fsatomic.write_atomic_text(...)
        if isinstance(fn.value, ast.Name):
            sym = self.mi.symbols.get(fn.value.id)
            if sym and sym[0] == "module":
                src = self.cg.modules.get(sym[1])
                s2 = src.symbols.get(mname) if src else None
                if s2 and s2[0] == "func":
                    self._add(s2[1], node.lineno, "direct", held)
                    return
                if s2 and s2[0] == "class":
                    init = self.cg.resolve_method(s2[1], "__init__")
                    if init is not None:
                        self._add(init, node.lineno, "ctor", held)
                    else:
                        self.fi.external_calls += 1
                    return
                self.fi.external_calls += 1
                return
            if sym and sym[0] == "class":
                got = self.cg.resolve_method(sym[1], mname)
                if got is not None:
                    self._add(got, node.lineno, "direct", held)
                    return
        # super().__init__ / super().m()
        if isinstance(fn.value, ast.Call) and \
                _dotted(fn.value.func) == "super" and self.fi.cls:
            ci = self.cg.classes.get(self.fi.cls)
            for base in (ci.bases if ci else []):
                got = self.cg.resolve_method(base, mname)
                if got is not None:
                    self._add(got, node.lineno, "self", held)
                    return
            self.fi.external_calls += 1
            return
        owner = self._type_of(fn.value)
        if owner is not None:
            got = self.cg.resolve_method(owner, mname)
            if got is not None:
                kind = "self" if (isinstance(fn.value, ast.Name)
                                  and fn.value.id == "self") else "typed"
                self._add(got, node.lineno, kind, held)
                return
            # typed but method unknown on that class: attr fallthrough
        if mname in COMMON_METHOD_NAMES:
            # too generic to attribute either way: counting these as
            # "unresolved" would drown the coverage signal in `.get()`s
            # — but when repo classes DO define the name, they bound
            # the possible dispatch, and the edge over-approximation
            # must still see it (superset invariant)
            cands = self._method_candidates(mname)
            if cands:
                self.fi.dynamic_calls.append(DynamicSite(
                    name=name, line=node.lineno, held=held,
                    candidates=cands, counted=False))
            self.fi.external_calls += 1
            return
        candidates = self.cg.method_index.get(mname, [])
        if len(candidates) == 1:
            got = self.cg.resolve_method(candidates[0], mname)
            if got is not None:
                self._add(got, node.lineno, "unique", held)
                return
        if candidates:
            self.fi.dynamic_calls.append(DynamicSite(
                name=name, line=node.lineno, held=held,
                candidates=self._method_candidates(mname)))
        else:
            self.fi.external_calls += 1

    def _method_candidates(self, mname: str) -> Tuple[str, ...]:
        """Every repo method of this name — the bounded dispatch set
        for an ambiguous attribute call."""
        out = {self.cg.resolve_method(cid, mname)
               for cid in self.cg.method_index.get(mname, ())}
        return tuple(sorted(fid for fid in out if fid is not None))

    # ----------------------------------------------------------- escaping
    def visit_Name(self, node):  # noqa: N802
        if isinstance(node.ctx, ast.Load):
            sym = self.mi.symbols.get(node.id)
            if sym and sym[0] == "func":
                self.cg.escaping.add(sym[1])

    def visit_Attribute(self, node):  # noqa: N802
        # a bound-method reference taken as a value: self.m / obj.m
        if isinstance(node.ctx, ast.Load):
            owner = self._type_of(node.value)
            if owner is not None:
                got = self.cg.resolve_method(owner, node.attr)
                if got is not None:
                    self.cg.escaping.add(got)
        self.generic_visit(node)


def _param_types(cg: CallGraph, mi: ModuleInfo,
                 node: ast.AST) -> Dict[str, str]:
    out: Dict[str, str] = {}
    args = getattr(node, "args", None)
    if args is None:
        return out
    for a in list(args.args) + list(args.kwonlyargs):
        if a.annotation is not None:
            cid = _class_symbol(mi, cg, a.annotation)
            if cid is None and isinstance(a.annotation, ast.Constant) \
                    and isinstance(a.annotation.value, str):
                sym = mi.symbols.get(a.annotation.value.strip('"'))
                if sym and sym[0] == "class":
                    cid = sym[1]
            if cid is not None:
                out[a.arg] = cid
    return out


def _nested_functions(cg: CallGraph, fi: FuncInfo,
                      node: ast.AST) -> List[Tuple[FuncInfo, ast.AST]]:
    """Register nested defs + lambdas as their own (escaping) function
    nodes — the repo's callback idiom passes closures into subscriber
    lists, and the dynamic-call over-approximation needs their effect
    summaries."""
    out: List[Tuple[FuncInfo, ast.AST]] = []
    # IMMEDIATE nested functions only — each nested function walks its
    # own children when its turn comes (no double registration)
    found: List[ast.AST] = []
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            found.append(n)
            continue
        stack.extend(ast.iter_child_nodes(n))
    for sub in found:
        nm = getattr(sub, "name", None) or f"<lambda@{sub.lineno}>"
        nfid = f"{fi.fid}.{nm}"
        if nfid in cg.functions:
            nfid = f"{nfid}@{sub.lineno}"
        nfi = FuncInfo(
            fid=nfid, module=fi.module, relpath=fi.relpath,
            cls=fi.cls, name=nm,
            qualscope=f"{fi.qualscope}.{nm}", line=sub.lineno)
        cg.functions[nfid] = nfi
        # a nested function is reachable only through a value
        # reference; treat it as escaping so dynamic call sites can
        # conservatively reach it
        cg.escaping.add(nfid)
        out.append((nfi, sub))
    return out


def _analyze_function(cg: CallGraph, fi: FuncInfo,
                      node: ast.AST) -> None:
    mi = cg.modules[fi.module]
    doc = ast.get_docstring(node) if not isinstance(
        node, ast.Lambda) else None
    has_contract, token = parse_contract_lock(doc or "")
    suffix = fi.name.endswith("_locked")
    if has_contract or suffix:
        if has_contract and token is None:
            # "caller holds" with no parseable lock name: the verifier
            # warns (lock-contract-unnamed) — the convention is only
            # checkable once the contract names its lock
            fi.contract_unnamed = True
        ref = _contract_lock_ref(cg, fi, token)
        if ref is not None:
            fi.requires_lock = ref
            fi.requires_source = "docstring" if token else "suffix"
        else:
            fi.contract_unnamed = True
    walker = _BodyWalker(cg, fi, _param_types(cg, mi, node))
    body = node.body if isinstance(node.body, list) else [node.body]
    for child in body:
        walker.visit(child)


def _contract_lock_ref(cg: CallGraph, fi: FuncInfo,
                       token: Optional[str]) -> Optional[LockRef]:
    """Resolve a contract token ('_lock' / 'store') — or, for a bare
    ``_locked`` suffix, the class's conventional mutex — to a LockRef."""
    cls = cg.classes.get(fi.cls) if fi.cls else None
    if token is None:
        # `_locked` suffix alone: the class's `_lock` attribute, the
        # class's SINGLE lock when unambiguous, else the conventional
        # pseudo `_lock` (the suffix names the class mutex by
        # convention; callers holding `with self._lock` verify against
        # the same pseudo name)
        if cls is not None:
            ref = cg.class_lock(cls.cid, "_lock")
            if ref is not None:
                return ref
            if len(cls.lock_attrs) == 1:
                return next(iter(cls.lock_attrs.values()))
            return LockRef(name=f"~{cls.name}._lock", named=False)
        return None
    if token.startswith("_") or token.endswith("_lock") \
            or token.endswith("_mu"):
        # ATTRIBUTE-style token ("_lock", "kill_lock", "_refresh_mu"):
        # resolve against the class's lock attrs, else a pseudo lock
        # that call-site holders of the same attribute match by tail
        if cls is not None:
            ref = cg.class_lock(cls.cid, token)
            if ref is not None:
                return ref
            return LockRef(name=f"~{cls.name}.{token}", named=False)
        return LockRef(name=f"~*.{token}", named=False)
    # named form ("the store lock"): token IS the family name
    return LockRef(name=family(token), named=True)


# ---------------------------------------------------------------------------
# entry point
# ---------------------------------------------------------------------------

def build_callgraph(package_root: Path,
                    trees: Dict[str, ast.Module]) -> CallGraph:
    """Build the whole-program call graph from pre-parsed modules
    (``relpath -> ast.Module``, as the lint engine already holds)."""
    cg = CallGraph(package=Path(package_root).name)
    for relpath, tree in sorted(trees.items()):
        _collect_module(cg, relpath, tree)
    # two rounds so one level of package re-export (`from .store import
    # Store` in state/__init__.py, consumed as `from .state import
    # Store` elsewhere) resolves regardless of module order
    _resolve_imports(cg)
    _resolve_imports(cg)
    _link_classes(cg)
    _collect_class_attrs(cg)
    # analyze bodies: module-level functions + methods, then nested
    for relpath, tree in sorted(trees.items()):
        module = _module_name(relpath)
        todo: List[Tuple[FuncInfo, ast.AST]] = []
        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                todo.append((cg.functions[f"{module}.{node.name}"], node))
            elif isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        fid = f"{module}.{node.name}.{sub.name}"
                        todo.append((cg.functions[fid], sub))
        i = 0
        while i < len(todo):
            fi, node = todo[i]
            todo.extend(_nested_functions(cg, fi, node))
            _analyze_function(cg, fi, node)
            i += 1
    return cg
