"""Interprocedural effect summaries + the whole-program lint passes.

Built on the call graph (callgraph.py), this module computes one
**effect summary** per function by fixpoint — the RacerD shape
(Blackshear et al., OOPSLA'18): summaries compose bottom-up over call
edges instead of re-analyzing bodies per context —

``blocks``
    blocking operations reachable by executing the function (seeded
    from the lexical pass's op table: fsync, sleep, socket, HTTP,
    subprocess, fsatomic writes, ``wait_acked``), each with one
    representative call chain;
``acquires``
    locks that may be acquired during execution (``with`` regions and
    manual ``.acquire()`` sites), again with a chain;
``spawns_thread``
    reachable ``threading.Thread`` construction;
``requires_lock``
    the lock a ``_locked``-suffix / "caller holds" function runs under
    BY CONTRACT (callgraph.py parses the docstring form) — and the
    belief-inference move (Engler et al., SOSP'01): the convention is
    *verified*, every resolved call site must hold the named lock.

On top of the summaries, four passes:

``lock-transitive-blocking``
    a blocking effect reachable through ≥1 call while a lock is held —
    the depth-0 (lexical) case stays with passes.py; findings are
    checked against the SAME ``ALLOWED_BLOCKING`` allowlist the dynamic
    monitor uses (parsed from the scanned tree's ``utils/locks.py``).
    Calls through the *unresolved* bucket contribute nothing here (a
    guess through a callback would drown the report); the dynamic
    sanitizer owns that residue, and the coverage stats say how much
    there is.
``lock-order-static`` / ``lock-sibling-static``
    the static may-be-held-at-acquisition edge set, rank-checked
    against the declared table and the sibling-family no-nesting rule.
    Same-NAME re-entrancy (``store`` held, ``store`` re-acquired — the
    RLock idiom) adds no edge; dynamic call sites over-approximate
    against the escaping-function set and contribute ``dynamic`` edges
    to the coverage diff but never violations.
``lock-contract-unverified`` / ``lock-contract-unnamed``
    the requires_lock verifier.
``journal-record-*`` / ``journal-raw-write``
    protocol completeness for the journal record kinds: every kind
    written at a ``*journal_file*.write(seal_record(...))`` site (the
    checksummed appender, state/integrity.py; legacy
    ``json.dumps(...)`` payloads still harvest) must have a replay
    handler (``_apply_journal_record`` / ``_replay_records``), be
    declared in the ``JOURNAL_RECORD_KINDS`` registry, and the
    read-replica tail must route whole records through
    ``_replay_records`` — so a new record kind can never silently
    vanish on a follower again.  ``journal-raw-write`` flags any
    journal write whose payload bypasses ``seal_record`` — an
    un-enveloped line is invisible to the torn-vs-corrupt verdict
    (docs/ROBUSTNESS.md WAL v2).

The static edge set is exported (family-normalized) for the
static-vs-dynamic coverage diff on ``cs lint --lock-coverage`` and
``GET /debug/health`` → ``locks`` (utils/locks.py owns the observed
half).
"""

from __future__ import annotations

import ast
import threading
from collections import deque
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Dict, List, Optional, Set, Tuple

from .callgraph import (CallGraph, FuncInfo, LockRef, build_callgraph,
                        family)
from .engine import Finding


# ---------------------------------------------------------------------------
# the declared contract, parsed from the scanned tree
# ---------------------------------------------------------------------------

def load_lock_contract(trees: Dict[str, ast.Module]
                       ) -> Tuple[Dict[str, int],
                                  Set[Tuple[str, str]]]:
    """(declared ranks, allowed blocking) parsed from the scanned
    tree's ``utils/locks.py`` — the analysis consults the SAME contract
    the dynamic sanitizer enforces, without importing the scanned code
    (fixture trees stay hermetic; absent file = empty contract)."""
    ranks: Dict[str, int] = {}
    allowed: Set[Tuple[str, str]] = set()
    tree = trees.get("utils/locks.py")
    if tree is None:
        return ranks, allowed
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            name, value = node.targets[0].id, node.value
        elif isinstance(node, ast.AnnAssign) \
                and isinstance(node.target, ast.Name) \
                and node.value is not None:
            name, value = node.target.id, node.value
        else:
            continue
        node = ast.Assign(targets=[ast.Name(id=name)], value=value)
        if name == "_DECLARED_ORDER" and isinstance(node.value, ast.Dict):
            for k, v in zip(node.value.keys, node.value.values):
                if isinstance(k, ast.Constant) \
                        and isinstance(v, ast.Constant):
                    ranks[str(k.value)] = int(v.value)
        elif name == "ALLOWED_BLOCKING" and isinstance(
                node.value, ast.Set):
            for elt in node.value.elts:
                if isinstance(elt, ast.Tuple) and len(elt.elts) == 2 \
                        and all(isinstance(e, ast.Constant)
                                for e in elt.elts):
                    allowed.add((str(elt.elts[0].value),
                                 str(elt.elts[1].value)))
    return ranks, allowed


# ---------------------------------------------------------------------------
# the fixpoint
# ---------------------------------------------------------------------------

@dataclass
class Summaries:
    #: fid -> {op label -> representative callee chain (fids)}
    blocks: Dict[str, Dict[str, Tuple[str, ...]]] = \
        field(default_factory=dict)
    #: fid -> {lock name -> (LockRef, representative chain)}
    acquires: Dict[str, Dict[str, Tuple[LockRef, Tuple[str, ...]]]] = \
        field(default_factory=dict)
    #: fids that may construct a thread (directly or transitively)
    spawns_thread: Set[str] = field(default_factory=set)
    iterations: int = 0

    def to_doc_for(self, fid: str) -> Dict[str, Any]:
        return {
            "blocks": sorted(self.blocks.get(fid, ())),
            "acquires": sorted(self.acquires.get(fid, ())),
            "spawns_thread": fid in self.spawns_thread,
        }


def compute_summaries(cg: CallGraph) -> Summaries:
    """Worklist fixpoint over the call graph.  Each map only grows and
    is bounded by (functions × ops) / (functions × locks), so
    termination is structural; on this tree it settles in a few
    thousand relaxations (<1 s)."""
    s = Summaries()
    callers: Dict[str, Set[str]] = {}
    for fid, fi in cg.functions.items():
        s.blocks[fid] = {op: () for (op, _name, _ln, _held) in fi.blocks}
        s.acquires[fid] = {ref.name: (ref, ())
                           for (ref, _ln, _held) in fi.acquires}
        if fi.spawns_thread:
            s.spawns_thread.add(fid)
        for cs in fi.calls:
            callers.setdefault(cs.callee, set()).add(fid)
    work = deque(cg.functions)
    while work:
        g = work.popleft()
        s.iterations += 1
        gb, ga = s.blocks.get(g), s.acquires.get(g)
        if gb is None:
            continue
        g_spawns = g in s.spawns_thread
        for f in callers.get(g, ()):
            changed = False
            fb, fa = s.blocks[f], s.acquires[f]
            for op, chain in gb.items():
                if op not in fb:
                    fb[op] = (g,) + chain
                    changed = True
            for ln, (ref, chain) in ga.items():
                if ln not in fa:
                    fa[ln] = (ref, (g,) + chain)
                    changed = True
            if g_spawns and f not in s.spawns_thread:
                s.spawns_thread.add(f)
                changed = True
            if changed:
                work.append(f)
    return s


def _short(fid: str) -> str:
    parts = fid.split(".")
    return ".".join(parts[-2:]) if len(parts) > 1 else fid


def _chain_str(fi: FuncInfo, chain: Tuple[str, ...], tail: str) -> str:
    return " -> ".join([_short(fi.fid)] + [_short(c) for c in chain]
                       + [tail])


def _allowed(lock: str, op: str,
             allowed: Set[Tuple[str, str]]) -> bool:
    return (lock, op) in allowed or (family(lock), op) in allowed


# ---------------------------------------------------------------------------
# pass: transitive blocking-under-lock
# ---------------------------------------------------------------------------

def transitive_blocking_findings(
        cg: CallGraph, s: Summaries,
        allowed: Set[Tuple[str, str]]) -> List[Finding]:
    findings: List[Finding] = []
    seen: Set[Tuple[str, str, str, str]] = set()
    for fid, fi in cg.functions.items():
        for cs in fi.calls:
            if not cs.held:
                continue
            callee = cg.functions.get(cs.callee)
            cblocks = s.blocks.get(cs.callee)
            if not cblocks:
                continue
            for op, chain in cblocks.items():
                for lock in cs.held:
                    if _allowed(lock, op, allowed):
                        continue
                    # report at the frame NEAREST the op whose contract
                    # documents the lock: when the callee itself runs
                    # under this lock by contract, its own body is the
                    # better (deeper) report site — skip the duplicate
                    if callee is not None \
                            and callee.requires_lock is not None \
                            and _lock_matches(lock,
                                              callee.requires_lock):
                        continue
                    key = (fid, lock, cs.callee, op)
                    if key in seen:
                        continue
                    seen.add(key)
                    findings.append(Finding(
                        check="lock-transitive-blocking",
                        path=fi.relpath, line=cs.line,
                        scope=fi.qualscope,
                        detail=f"{family(lock)}:{_short(cs.callee)}:{op}",
                        message=(
                            f"call chain "
                            f"{_chain_str(fi, (cs.callee,) + chain, op)}"
                            f" blocks ({op}) while holding '{lock}' — "
                            "not in locks.ALLOWED_BLOCKING; move the "
                            "blocking tail off the lock or baseline "
                            "the design")))
    return findings


def _lock_matches(held: str, req: LockRef) -> bool:
    """Does holding ``held`` satisfy a requires_lock on ``req``?  Named
    locks match by rank family; pseudo (unnamed) locks match by
    attribute tail (``~Store._lock`` vs ``~*._lock``)."""
    if held == req.name:
        return True
    if req.named and not held.startswith("~"):
        return family(held) == family(req.name)
    if held.startswith("~"):
        # pseudo holds match by attribute tail — against a pseudo
        # requirement, or against a named-token requirement whose
        # token is really an attribute the holder's class never
        # resolved (`kill_lock`)
        tail = held.rsplit(".", 1)[-1]
        if req.name.startswith("~"):
            return tail == req.name.rsplit(".", 1)[-1]
        return tail == req.name
    return False


# ---------------------------------------------------------------------------
# pass: requires_lock verification
# ---------------------------------------------------------------------------

def contract_findings(cg: CallGraph) -> List[Finding]:
    findings: List[Finding] = []
    seen: Set[Tuple[str, str]] = set()
    for fid, fi in cg.functions.items():
        if fi.contract_unnamed:
            findings.append(Finding(
                check="lock-contract-unnamed", path=fi.relpath,
                line=fi.line, scope=fi.qualscope, detail=fi.name,
                message=(f"`{fi.name}` declares a lock-held contract "
                         "(docstring/_locked suffix) without a "
                         "resolvable lock name — use the `caller holds "
                         "self._lock` idiom so the interprocedural "
                         "verifier can check every call site")))
        for cs in fi.calls:
            callee = cg.functions.get(cs.callee)
            if callee is None or callee.requires_lock is None:
                continue
            req = callee.requires_lock
            if any(_lock_matches(h, req) for h in cs.held):
                continue
            if (fid, cs.callee) in seen:
                continue
            seen.add((fid, cs.callee))
            findings.append(Finding(
                check="lock-contract-unverified", path=fi.relpath,
                line=cs.line, scope=fi.qualscope,
                detail=f"{_short(cs.callee)}:{req.name}",
                message=(
                    f"`{_short(cs.callee)}` runs with '{req.name}' "
                    "held by contract, but this call site does not "
                    "provably hold it — wrap the call in the lock, fix "
                    "the contract docstring, or baseline the design")))
    return findings


# ---------------------------------------------------------------------------
# pass: static lock-order graph
# ---------------------------------------------------------------------------

@dataclass
class LockEdge:
    src: str                    #: full lock name as held
    dst: str                    #: full lock name acquired
    kind: str                   #: "resolved" | "dynamic"
    path: str
    line: int
    scope: str
    chain: str                  #: human-readable sample chain

    @property
    def fam(self) -> Tuple[str, str]:
        return (family(self.src), family(self.dst))

    def to_doc(self) -> Dict[str, Any]:
        return {"from": family(self.src), "to": family(self.dst),
                "kind": self.kind, "via": self.chain,
                "site": f"{self.path}:{self.line}"}


def compute_lock_edges(cg: CallGraph, s: Summaries
                       ) -> Dict[Tuple[str, str], LockEdge]:
    """The static may-be-held-at-acquisition edge set over NAMED locks.

    ``resolved`` edges flow through direct/typed/unique call edges and
    lexical nesting; ``dynamic`` edges over-approximate callback call
    sites against every escaping function's acquisition summary (the
    bounded treatment of dynamic dispatch — they join the coverage
    diff, never the violation list)."""
    edges: Dict[Tuple[str, str], LockEdge] = {}

    def named(lock: str) -> bool:
        return not lock.startswith("~")

    def add(src: str, ref: LockRef, kind: str, fi: FuncInfo,
            line: int, chain: str) -> None:
        if not named(src) or not ref.named:
            return
        if src == ref.name:
            return  # same-name re-entrancy (the RLock idiom): no edge
        key = (family(src), family(ref.name))
        prev = edges.get(key)
        if prev is None or (prev.kind == "dynamic"
                            and kind == "resolved"):
            edges[key] = LockEdge(src=src, dst=ref.name, kind=kind,
                                  path=fi.relpath, line=line,
                                  scope=fi.qualscope, chain=chain)

    # escaping-set acquisition union, for dynamic sites
    esc_acquires: Dict[str, Tuple[LockRef, str]] = {}
    for efid in cg.escaping:
        for name, (ref, chain) in s.acquires.get(efid, {}).items():
            if ref.named and name not in esc_acquires:
                esc_acquires[name] = (
                    ref, _chain_str(cg.functions[efid], chain,
                                    f"acquire {name}"))

    for fid, fi in cg.functions.items():
        for (ref, line, held) in fi.acquires:
            for src in held:
                add(src, ref, "resolved", fi, line,
                    f"{_short(fid)} acquires {ref.name}")
        for cs in fi.calls:
            if not cs.held:
                continue
            for name, (ref, chain) in s.acquires.get(cs.callee,
                                                     {}).items():
                for src in cs.held:
                    add(src, ref, "resolved", fi, cs.line,
                        _chain_str(fi, (cs.callee,) + chain,
                                   f"acquire {name}"))
        for ds in fi.dynamic_calls:
            if not ds.held:
                continue
            if ds.candidates:
                # ambiguous ATTRIBUTE dispatch: the method name bounds
                # the possible callees — over-approximate against the
                # candidate set, not the whole escaping set
                pool: Dict[str, Tuple[LockRef, str]] = {}
                for cand in ds.candidates:
                    for name, (ref, chain) in s.acquires.get(
                            cand, {}).items():
                        if ref.named and name not in pool:
                            pool[name] = (ref, _chain_str(
                                cg.functions[cand], chain,
                                f"acquire {name}"))
            else:
                # a bare callback variable: anything that escaped
                pool = esc_acquires
            for name, (ref, chain) in pool.items():
                for src in ds.held:
                    add(src, ref, "dynamic", fi, ds.line,
                        f"{_short(fid)} -> <{ds.name}> ... {chain}")
    return edges


def lock_order_findings(edges: Dict[Tuple[str, str], LockEdge],
                        ranks: Dict[str, int]) -> List[Finding]:
    findings: List[Finding] = []
    for edge in edges.values():
        if edge.kind != "resolved":
            continue  # dynamic over-approximation: coverage only
        sf, df = family(edge.src), family(edge.dst)
        rs, rd = ranks.get(sf), ranks.get(df)
        if sf == df:
            # distinct names, one rank family: the sibling no-nesting
            # rule (utils/locks.py partitioned-store contract)
            findings.append(Finding(
                check="lock-sibling-static", path=edge.path,
                line=edge.line, scope=edge.scope,
                detail=f"{edge.src}->{edge.dst}",
                message=(
                    f"'{edge.dst}' may be acquired while holding "
                    f"sibling '{edge.src}' (rank family '{sf}') via "
                    f"{edge.chain} — sibling locks of a rank family "
                    "may never nest (ABBA-unorderable)")))
        elif rs is not None and rd is not None and rd < rs:
            findings.append(Finding(
                check="lock-order-static", path=edge.path,
                line=edge.line, scope=edge.scope,
                detail=f"{sf}->{df}",
                message=(
                    f"'{edge.dst}' (rank {rd}) may be acquired while "
                    f"holding '{edge.src}' (rank {rs}) via "
                    f"{edge.chain} — violates the declared lock-order "
                    "contract (utils/locks.py)")))
    return findings


# ---------------------------------------------------------------------------
# pass: journal-record protocol completeness
# ---------------------------------------------------------------------------

#: handler functions whose constant keys count as "replayed"
_HANDLER_FNS = ("_apply_journal_record", "_replay_records")
#: the declared registry's module-level name
_KIND_TABLE = "JOURNAL_RECORD_KINDS"


def _const_keys(node: ast.AST) -> Set[str]:
    out: Set[str] = set()
    if isinstance(node, ast.Dict):
        for k in node.keys:
            if isinstance(k, ast.Constant) and isinstance(k.value, str):
                out.add(k.value)
    return out


def _dotted_parts(node: ast.AST) -> str:
    parts: List[str] = []
    b = node
    while isinstance(b, ast.Attribute):
        parts.append(b.attr)
        b = b.value
    if isinstance(b, ast.Name):
        parts.append(b.id)
    return ".".join(parts)


def _call_fname(call: ast.Call) -> str:
    return call.func.attr if isinstance(call.func, ast.Attribute) else (
        call.func.id if isinstance(call.func, ast.Name) else "")


def _payload_call(arg: ast.AST,
                  local_assigns: Dict[str, ast.AST]
                  ) -> Optional[ast.Call]:
    """The outermost call feeding a journal ``write(...)`` payload
    (``seal_record(rec)`` / ``json.dumps(rec)``), following one level
    of local alias and stripping ``+ "\\n"``."""
    for _ in range(2):
        while isinstance(arg, ast.BinOp):
            arg = arg.left
        if isinstance(arg, ast.Call):
            return arg
        if isinstance(arg, ast.Name) and arg.id in local_assigns:
            arg = local_assigns[arg.id]
            continue
        return None
    return None


def _dumps_payload(arg: ast.AST,
                   local_assigns: Dict[str, ast.AST]
                   ) -> Optional[ast.AST]:
    """The dict/name inside a journal payload expression — the sealed
    form ``seal_record(rec)`` (state/integrity.py — the record rides a
    CRC32C envelope) or the legacy ``json.dumps(rec) + "\\n"`` —
    following one level of local alias."""
    call = _payload_call(arg, local_assigns)
    if call is None or not call.args:
        return None
    fname = _call_fname(call)
    if "seal" in fname:
        inner = call.args[0]
        # seal_record(json.dumps(...)) never occurs, but a sealed
        # payload may itself be aliased one level
        if isinstance(inner, ast.Call) and "dumps" in _call_fname(inner) \
                and inner.args:
            return inner.args[0]
        return inner
    if "dumps" in fname:
        return call.args[0]
    return None


def _record_keys_in_fn(fn: ast.AST, rec_names: Set[str]) -> Set[str]:
    """Keys assigned into the record dicts named in ``rec_names``
    within one writer function (dict literal init + subscript
    assignment)."""
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id in rec_names:
                    out |= _const_keys(node.value)
                elif isinstance(t, ast.Subscript) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id in rec_names \
                        and isinstance(t.slice, ast.Constant) \
                        and isinstance(t.slice.value, str):
                    out.add(t.slice.value)
        elif isinstance(node, ast.AnnAssign) and node.value is not None \
                and isinstance(node.target, ast.Name) \
                and node.target.id in rec_names:
            out |= _const_keys(node.value)
    return out


def journal_record_findings(trees: Dict[str, ast.Module]
                            ) -> List[Finding]:
    """Protocol-completeness registry for the journal record kinds.

    Harvests, purely statically:

    - **written** kinds — at every ``<...journal_file...>.write(
      seal_record(rec))`` site (or the legacy ``json.dumps(rec)``
      form), the constant keys of ``rec`` (dict-literal init +
      ``rec["k"] = ...`` assignments in the same function, or an
      inline dict literal);
    - **handled** kinds — constant ``rec.get("k")`` / ``rec["k"]`` keys
      inside the replay handlers (``_apply_journal_record`` /
      ``_replay_records``);
    - **declared** kinds — the ``JOURNAL_RECORD_KINDS`` registry.

    A written kind without a handler is how a record type silently
    vanishes on replay/follower-tail; a written kind missing from the
    registry is an undocumented protocol extension; a declared kind
    never written is a stale registry entry.  The read-replica tail
    must route whole records through ``_replay_records`` (the epoch
    fence + handler table live there), or every kind is follower-lost.
    """
    written: Dict[str, Tuple[str, int]] = {}
    handled: Set[str] = set()
    declared: Dict[str, Tuple[str, int]] = {}
    raw_writes: List[Tuple[str, int]] = []
    writer_seen = False
    replica_files: List[str] = []
    replica_calls_replay = False

    for relpath, tree in sorted(trees.items()):
        if relpath.endswith("read_replica.py"):
            replica_files.append(relpath)
        for node in tree.body:
            if isinstance(node, (ast.Assign, ast.AnnAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                if any(isinstance(t, ast.Name) and t.id == _KIND_TABLE
                       for t in targets) and node.value is not None:
                    for k in _const_keys(node.value):
                        declared[k] = (relpath, node.lineno)
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if node.name in _HANDLER_FNS:
                    for sub in ast.walk(node):
                        if isinstance(sub, ast.Call) and isinstance(
                                sub.func, ast.Attribute) \
                                and sub.func.attr == "get" and sub.args \
                                and isinstance(sub.args[0], ast.Constant) \
                                and isinstance(sub.args[0].value, str):
                            handled.add(sub.args[0].value)
                        elif isinstance(sub, ast.Subscript) \
                                and isinstance(sub.slice, ast.Constant) \
                                and isinstance(sub.slice.value, str):
                            handled.add(sub.slice.value)
                # writer sites in this function.  The repo idiom
                # aliases the handle and the line:
                #     f = self._journal_file
                #     line = seal_record(rec)
                #     f.write(line)
                # so both the write target and the payload resolve
                # through one level of local assignment.
                local_assigns: Dict[str, ast.AST] = {}
                aliases: Set[str] = set()
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Assign) \
                            and len(sub.targets) == 1 \
                            and isinstance(sub.targets[0], ast.Name):
                        nm = sub.targets[0].id
                        local_assigns.setdefault(nm, sub.value)
                        if "journal_file" in _dotted_parts(sub.value):
                            aliases.add(nm)
                rec_names: Set[str] = set()
                inline_keys: Set[str] = set()
                fn_writes = False
                for sub in ast.walk(node):
                    if not (isinstance(sub, ast.Call)
                            and isinstance(sub.func, ast.Attribute)
                            and sub.func.attr == "write" and sub.args):
                        continue
                    base = sub.func.value
                    target_parts = _dotted_parts(base)
                    is_journal = "journal_file" in target_parts or (
                        isinstance(base, ast.Name)
                        and base.id in aliases)
                    if not is_journal:
                        continue
                    # every journal write must route through the
                    # checksummed appender (state/integrity.seal_record)
                    # — a bare json.dumps line has no CRC envelope, so
                    # replay can't tell a torn tail from mid-file
                    # corruption for it
                    call = _payload_call(sub.args[0], local_assigns)
                    if call is None or "seal" not in _call_fname(call):
                        raw_writes.append((relpath, sub.lineno))
                    payload = _dumps_payload(sub.args[0], local_assigns)
                    if payload is None:
                        continue
                    fn_writes = True
                    if isinstance(payload, ast.Dict):
                        inline_keys |= _const_keys(payload)
                    elif isinstance(payload, ast.Name):
                        rec_names.add(payload.id)
                if fn_writes:
                    writer_seen = True
                    keys = inline_keys | _record_keys_in_fn(
                        node, rec_names)
                    for k in keys:
                        written.setdefault(k, (relpath, node.lineno))
                if replica_files and relpath == replica_files[-1]:
                    for sub in ast.walk(node):
                        if isinstance(sub, ast.Call) and isinstance(
                                sub.func, ast.Attribute) and \
                                sub.func.attr == "_replay_records":
                            replica_calls_replay = True

    findings: List[Finding] = []
    for relpath, line in raw_writes:
        findings.append(Finding(
            check="journal-raw-write", path=relpath, line=line,
            scope="journal", detail="write",
            message=("journal write bypasses the checksummed appender — "
                     "route the record through state/integrity."
                     "seal_record so replay can tell a torn tail from "
                     "mid-file corruption (docs/ROBUSTNESS.md WAL v2)")))
    if not writer_seen:
        return findings
    for kind, (relpath, line) in sorted(written.items()):
        if kind not in handled:
            findings.append(Finding(
                check="journal-record-unhandled", path=relpath,
                line=line, scope="journal", detail=kind,
                message=(f"journal record kind '{kind}' is written but "
                         "has no handler in _apply_journal_record/"
                         "_replay_records — it would silently vanish "
                         "on replay, checkpoint re-seed, and the "
                         "read-replica tail")))
        if declared and kind not in declared:
            findings.append(Finding(
                check="journal-record-undeclared", path=relpath,
                line=line, scope="journal", detail=kind,
                message=(f"journal record kind '{kind}' is missing "
                         f"from {_KIND_TABLE} — declare its replay + "
                         "checkpoint semantics in the registry "
                         "(state/store.py)")))
    for kind, (relpath, line) in sorted(declared.items()):
        if kind not in written:
            findings.append(Finding(
                check="journal-record-stale", path=relpath, line=line,
                scope="journal", detail=kind,
                message=(f"{_KIND_TABLE} declares record kind "
                         f"'{kind}' but no journal writer emits it — "
                         "remove the stale registry entry")))
    for relpath in replica_files:
        if not replica_calls_replay:
            findings.append(Finding(
                check="journal-record-tail", path=relpath, line=1,
                scope="read_replica", detail="_replay_records",
                message=("the read-replica tail does not route records "
                         "through Store._replay_records — record "
                         "kinds and the epoch-fence skip rule would "
                         "drift from the leader's replay")))
    return findings


# ---------------------------------------------------------------------------
# the one-call bundle the engine uses
# ---------------------------------------------------------------------------

@dataclass
class InterprocResult:
    findings: List[Finding]
    edges: Dict[Tuple[str, str], LockEdge]
    stats: Dict[str, Any]


def run_interprocedural(package_root: Path,
                        trees: Dict[str, ast.Module]) -> InterprocResult:
    cg = build_callgraph(package_root, trees)
    s = compute_summaries(cg)
    ranks, allowed = load_lock_contract(trees)
    edges = compute_lock_edges(cg, s)
    findings: List[Finding] = []
    findings += transitive_blocking_findings(cg, s, allowed)
    findings += contract_findings(cg)
    findings += lock_order_findings(edges, ranks)
    findings += journal_record_findings(trees)
    stats = cg.stats()
    stats["fixpoint_iterations"] = s.iterations
    stats["static_lock_edges"] = len(edges)
    return InterprocResult(findings=findings, edges=edges, stats=stats)


# ---------------------------------------------------------------------------
# static edge export for /debug/health (lazy, cached, computed once)
# ---------------------------------------------------------------------------

_EDGE_CACHE: Dict[str, Any] = {"edges": None, "error": None,
                               "started": False}
_EDGE_MU = threading.Lock()
_EDGE_DONE = threading.Event()


def _compute_static_edges() -> List[str]:
    package_root = Path(__file__).resolve().parent.parent
    trees: Dict[str, ast.Module] = {}
    for path in sorted(package_root.rglob("*.py")):
        if "__pycache__" in path.parts:
            continue
        rel = path.relative_to(package_root).as_posix()
        try:
            trees[rel] = ast.parse(path.read_text(encoding="utf-8"))
        except (OSError, SyntaxError):
            continue
    cg = build_callgraph(package_root, trees)
    s = compute_summaries(cg)
    edges = compute_lock_edges(cg, s)
    return sorted({f"{a}->{b}" for (a, b) in edges})


def _run_edge_compute() -> None:
    try:
        got = _compute_static_edges()
        with _EDGE_MU:
            _EDGE_CACHE["edges"] = got
            _EDGE_CACHE["error"] = None
    except Exception as e:  # pragma: no cover - analysis bug surface
        # a FAILED computation must stay distinguishable from "zero
        # edges": caching [] here would make every observed edge read
        # as a phantom resolution gap on /debug/health and fail the
        # tier-1 teardown with a misleading message
        with _EDGE_MU:
            _EDGE_CACHE["error"] = repr(e)
    finally:
        _EDGE_DONE.set()


def static_edge_error() -> Optional[str]:
    """repr() of the failed static-edge computation, None while
    pending or after success — the health surface renders it."""
    with _EDGE_MU:
        return _EDGE_CACHE["error"]


def static_edge_families(wait: bool = False) -> Optional[List[str]]:
    """The package's static lock-edge set, family-normalized
    (``["store.notify->store", ...]``), for the observed-vs-static
    coverage diff.  Computed ONCE per process off a background thread;
    ``wait=False`` (the health endpoint, which must never stall on a
    ~1 s source scan) returns None until the result lands;
    ``wait=True`` (tests, the tier-1 teardown) joins the in-flight
    computation — never a duplicate run — and RAISES if it failed."""
    start = False
    with _EDGE_MU:
        if _EDGE_CACHE["edges"] is not None:
            return list(_EDGE_CACHE["edges"])
        if not _EDGE_CACHE["started"]:
            _EDGE_CACHE["started"] = True
            start = True
    if start:
        threading.Thread(target=_run_edge_compute, daemon=True,
                         name="cook-static-edges").start()
    if not wait:
        return None
    _EDGE_DONE.wait()
    with _EDGE_MU:
        if _EDGE_CACHE["edges"] is not None:
            return list(_EDGE_CACHE["edges"])
        raise RuntimeError("static lock-edge computation failed: "
                           f"{_EDGE_CACHE['error']}")
