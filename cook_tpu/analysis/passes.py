"""Repo-specific AST lint passes (see package doc and docs/ANALYSIS.md).

Each pass is a callable ``(path, relpath, tree, src_lines) -> [Finding]``
registered in :data:`PASSES`.  Findings carry a line-independent
fingerprint (check:file:scope:detail) so the baseline survives edits
above the flagged site.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .engine import Finding

# --------------------------------------------------------------------------
# helpers
# --------------------------------------------------------------------------

#: attribute names treated as mutexes when they appear in a `with` item
LOCK_ATTRS = {"_lock", "_mu", "_notify_lock"}

#: dotted-call substrings that BLOCK (syscall/RPC/sleep) — finding one
#: inside a lock-held region is the lock-discipline violation.  Condition
#: waits (`_cv.wait`) are excluded: they release their lock while waiting.
BLOCKING_CALLS: Tuple[Tuple[str, str], ...] = (
    # (match substring of the dotted call name, canonical op label)
    ("os.fsync", "os.fsync"),
    ("time.sleep", "time.sleep"),
    ("write_atomic_text", "fsatomic.fsync"),   # fsyncs internally
    ("write_atomic_int", "fsatomic.fsync"),
    ("wait_acked", "repl.wait_acked"),         # bounded native wait
    ("socket.create_connection", "socket"),
    (".connect", "socket"),
    (".sendall", "socket"),
    (".recv", "socket"),
    (".accept", "socket"),
    ("urlopen", "http"),
    ("getresponse", "http"),
    ("subprocess.", "subprocess"),
)

#: wall-clock / RNG calls that must not appear inside jitted bodies
#: (kernel results must be pure functions of their inputs)
WALLCLOCK_CALLS = ("time.time", "time.perf_counter", "time.monotonic",
                  "datetime.now", "datetime.utcnow", "random.",
                  "np.random", "uuid.uuid")


def _dotted(node: ast.AST) -> str:
    """Best-effort dotted name of a call target ('os.fsync',
    'self._repl_server.wait_acked', ...)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    elif isinstance(node, ast.Call):
        parts.append(_dotted(node.func) + "()")
    return ".".join(reversed(parts))


def _lock_name(item: ast.withitem) -> Optional[str]:
    """The lock a `with` item guards, when its context expression is a
    mutex attribute (self._lock, store._lock, self._mu, ...)."""
    expr = item.context_expr
    if isinstance(expr, ast.Attribute) and (
            expr.attr in LOCK_ATTRS or expr.attr.endswith("_lock")):
        return _dotted(expr)
    return None


class _ScopeWalker(ast.NodeVisitor):
    """Tracks the enclosing function qualname while visiting."""

    def __init__(self):
        self.scope: List[str] = []

    def qualname(self) -> str:
        return ".".join(self.scope) or "<module>"

    def visit_FunctionDef(self, node):  # noqa: N802
        self.scope.append(node.name)
        self.generic_visit(node)
        self.scope.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ClassDef(self, node):  # noqa: N802
        self.scope.append(node.name)
        self.generic_visit(node)
        self.scope.pop()


# --------------------------------------------------------------------------
# pass 1: lock-discipline
# --------------------------------------------------------------------------

def _is_lock_scoped_fn(node: ast.FunctionDef) -> Optional[str]:
    """Functions that run with a lock HELD by contract even though no
    `with` is lexically visible: the repo idiom is a `_locked` suffix
    or a caller-holds docstring naming the lock (the `self._lock`
    idiom).  Returns the held-lock display token (the parsed name, or
    the function name for a bare suffix), None when no contract
    applies.  The docstring parser has ONE home —
    callgraph.parse_contract_lock — shared with the interprocedural
    requires_lock verifier, which also warns (`lock-contract-unnamed`)
    when the contract names no lock."""
    from .callgraph import parse_contract_lock
    doc = ast.get_docstring(node) or ""
    has_contract, token = parse_contract_lock(doc)
    if token is not None:
        return token
    if has_contract or node.name.endswith("_locked"):
        return f"<{node.name}>"
    return None


class _LockDiscipline(_ScopeWalker):
    def __init__(self, relpath: str):
        super().__init__()
        self.relpath = relpath
        self.findings: List[Finding] = []
        # stack of lock names currently lexically held
        self._held: List[str] = []

    def visit_FunctionDef(self, node):  # noqa: N802
        self.scope.append(node.name)
        contract_lock = _is_lock_scoped_fn(node)
        if contract_lock is not None:
            self._held.append(contract_lock)
            for child in node.body:
                self.visit(child)
            self._held.pop()
        else:
            # a nested def is a NEW execution context: what it does when
            # CALLED is not "under" the enclosing with-block
            held, self._held = self._held, []
            for child in node.body:
                self.visit(child)
            self._held = held
        self.scope.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):  # noqa: N802
        held, self._held = self._held, []
        self.generic_visit(node)
        self._held = held

    def visit_With(self, node):  # noqa: N802
        # with-items evaluate IN ORDER: `with self._lock, sock.connect()`
        # runs the connect while the lock is held, and a non-lock
        # context expression (`with socket.create_connection(...)`)
        # under an outer held lock is a blocking call like any other —
        # so each item's context_expr is visited with the locks
        # acquired so far, THEN the item's own lock (if any) joins the
        # held set for the rest of the statement
        acquired = 0
        for item in node.items:
            name = _lock_name(item)
            if name is None:
                self.visit(item.context_expr)
                if item.optional_vars is not None:
                    self.visit(item.optional_vars)
            else:
                self._held.append(name)
                acquired += 1
        for child in node.body:
            self.visit(child)
        if acquired:
            del self._held[-acquired:]

    def visit_Call(self, node):  # noqa: N802
        if self._held:
            name = _dotted(node.func)
            for sub, op in BLOCKING_CALLS:
                if sub in name:
                    self.findings.append(Finding(
                        check="lock-blocking-call",
                        path=self.relpath, line=node.lineno,
                        scope=self.qualname(), detail=name,
                        message=(f"blocking call `{name}` ({op}) while "
                                 f"holding {self._held[-1]} — move it "
                                 "off the lock or baseline it with the "
                                 "design justification")))
                    break
        self.generic_visit(node)


def lock_discipline(path: Path, relpath: str, tree: ast.Module,
                    src_lines: Sequence[str]) -> List[Finding]:
    walker = _LockDiscipline(relpath)
    walker.visit(tree)
    return walker.findings


# --------------------------------------------------------------------------
# pass 2: jit-hygiene
# --------------------------------------------------------------------------

def _is_jax_jit(node: ast.AST) -> bool:
    """`jax.jit` / `pjit` / `pjit.pjit` as a call target or decorator
    head."""
    name = _dotted(node)
    return name in ("jax.jit", "jit", "pjit", "pjit.pjit", "jax.pjit")


def _partial_jit(call: ast.Call) -> bool:
    """`functools.partial(jax.jit, ...)` decorator form."""
    return (_dotted(call.func).endswith("partial") and call.args
            and _is_jax_jit(call.args[0]))


def _static_argnames(call: Optional[ast.Call]) -> Set[str]:
    out: Set[str] = set()
    if call is None:
        return out
    for kw in call.keywords:
        if kw.arg == "static_argnames" and isinstance(
                kw.value, (ast.Tuple, ast.List)):
            for elt in kw.value.elts:
                if isinstance(elt, ast.Constant):
                    out.add(str(elt.value))
        elif kw.arg == "static_argnames" and isinstance(
                kw.value, ast.Constant):
            out.add(str(kw.value.value))
    return out


class _JitBodyChecker(_ScopeWalker):
    """Checks inside ONE jitted body: host numpy, wall-clock/RNG, and
    Python branches on (non-static) traced parameters."""

    def __init__(self, relpath: str, owner: str,
                 params: Set[str], findings: List[Finding]):
        super().__init__()
        self.relpath = relpath
        self.owner = owner
        self.params = params
        self.findings = findings

    def _flag(self, check: str, node: ast.AST, detail: str,
              message: str) -> None:
        self.findings.append(Finding(
            check=check, path=self.relpath, line=node.lineno,
            scope=self.owner, detail=detail, message=message))

    def visit_Attribute(self, node):  # noqa: N802
        if isinstance(node.value, ast.Name) and node.value.id == "np":
            self._flag("jit-host-numpy", node, f"np.{node.attr}",
                       f"host numpy call `np.{node.attr}` inside jitted "
                       f"body `{self.owner}` — runs per trace, not per "
                       "call; use jnp or hoist to staging")
        self.generic_visit(node)

    def visit_Call(self, node):  # noqa: N802
        name = _dotted(node.func)
        for sub in WALLCLOCK_CALLS:
            if name.startswith(sub) or f".{sub}" in name:
                self._flag("jit-wallclock", node, name,
                           f"wall-clock/RNG call `{name}` inside jitted "
                           f"body `{self.owner}` — kernels must be pure "
                           "functions of their inputs")
                break
        self.generic_visit(node)

    def _check_test(self, node, test: ast.expr, kind: str) -> None:
        for sub in ast.walk(test):
            if isinstance(sub, ast.Name) and sub.id in self.params:
                self._flag("jit-traced-branch", node, sub.id,
                           f"Python `{kind}` on traced parameter "
                           f"`{sub.id}` inside jitted body "
                           f"`{self.owner}` — branches on traced values "
                           "fail (or silently retrace); use lax.cond / "
                           "jnp.where or mark the arg static")
                return

    def visit_If(self, node):  # noqa: N802
        self._check_test(node, node.test, "if")
        self.generic_visit(node)

    def visit_While(self, node):  # noqa: N802
        self._check_test(node, node.test, "while")
        self.generic_visit(node)


class _JitHygiene(_ScopeWalker):
    def __init__(self, relpath: str, check_bodies: bool):
        super().__init__()
        self.relpath = relpath
        self.check_bodies = check_bodies
        self.findings: List[Finding] = []
        #: names bound to a bare jit object, keyed (name, scope) so two
        #: same-named definitions in different scopes never collide
        self.jit_names: Dict[Tuple[str, str], int] = {}
        #: names passed through instrument_jit(...) — the later-rebinding
        #: idiom (`kernel = instrument_jit("k", kernel)`) is module-level,
        #: so it only vouches for MODULE-scope definitions; nested/class
        #: scopes must instrument inline
        self.instrumented: Set[str] = set()
        self._instrument_depth = 0

    # -- collection --------------------------------------------------------
    def visit_FunctionDef(self, node):  # noqa: N802
        jit_call: Optional[ast.Call] = None
        jitted = False
        for dec in node.decorator_list:
            if _is_jax_jit(dec):
                jitted = True
            elif isinstance(dec, ast.Call) and (_is_jax_jit(dec.func)
                                                or _partial_jit(dec)):
                jitted = True
                jit_call = dec
        if jitted:
            self.jit_names[(node.name, self.qualname())] = node.lineno
            if self.check_bodies:
                statics = _static_argnames(jit_call)
                params = {a.arg for a in node.args.args
                          + node.args.kwonlyargs} - statics - {"self"}
                checker = _JitBodyChecker(
                    self.relpath, node.name, params, self.findings)
                for child in node.body:
                    checker.visit(child)
        self.scope.append(node.name)
        self.generic_visit(node)
        self.scope.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Call(self, node):  # noqa: N802
        name = _dotted(node.func)
        if name.endswith("instrument_jit"):
            for arg in node.args:
                if isinstance(arg, ast.Name):
                    self.instrumented.add(arg.id)
            self._instrument_depth += 1
            self.generic_visit(node)
            self._instrument_depth -= 1
            return
        if _is_jax_jit(node.func) and node.args:
            if self._instrument_depth == 0:
                # bare jax.jit(...) call: OK only if its bound name is
                # instrumented later in this module
                target = self._assign_target(node)
                if target is None:
                    self.findings.append(Finding(
                        check="jit-uninstrumented", path=self.relpath,
                        line=node.lineno, scope=self.qualname(),
                        detail=_dotted(node.args[0]) or "<expr>",
                        message=("`jax.jit` site not wrapped in "
                                 "ops.telemetry.instrument_jit — its "
                                 "recompiles are invisible to "
                                 "cook_jit_compile_total and the flight "
                                 "recorder")))
                else:
                    self.jit_names[(target, self.qualname())] = \
                        node.lineno
            if self.check_bodies and isinstance(node.args[0], ast.Lambda):
                lam = node.args[0]
                statics = _static_argnames(node)
                params = {a.arg for a in lam.args.args} - statics
                checker = _JitBodyChecker(
                    self.relpath, self.qualname() + ".<lambda>", params,
                    self.findings)
                checker.visit(lam.body)
        self.generic_visit(node)

    def _assign_target(self, call: ast.Call) -> Optional[str]:
        parent = getattr(call, "_parent", None)
        if isinstance(parent, ast.Assign) and len(parent.targets) == 1 \
                and isinstance(parent.targets[0], ast.Name):
            return parent.targets[0].id
        return None


def jit_hygiene(path: Path, relpath: str, tree: ast.Module,
                src_lines: Sequence[str]) -> List[Finding]:
    # parent links for the assign-target lookup
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            child._parent = node  # type: ignore[attr-defined]
    # body checks apply to kernel code: ops/ and the fused cycle
    check_bodies = relpath.startswith("ops/") or relpath in (
        "sched/fused.py",)
    walker = _JitHygiene(relpath, check_bodies)
    walker.visit(tree)
    for (name, scope), line in walker.jit_names.items():
        if name not in walker.instrumented or scope != "<module>":
            walker.findings.append(Finding(
                check="jit-uninstrumented", path=relpath, line=line,
                scope=scope, detail=name,
                message=(f"jitted callable `{name}` is never wrapped in "
                         "ops.telemetry.instrument_jit — its recompiles "
                         "are invisible to cook_jit_compile_total and "
                         "the flight recorder")))
    return walker.findings


# --------------------------------------------------------------------------
# pass 3: registry-completeness (docs diff; module-level, not per-file)
# --------------------------------------------------------------------------

def registry_completeness(package_root: Path,
                          docs_root: Optional[Path]) -> List[Finding]:
    from . import registry as _registry
    if docs_root is None or not Path(docs_root).exists():
        return []
    doc_for = {"metric": "docs/OBSERVABILITY.md",
               "span": "docs/OBSERVABILITY.md",
               "cycle-field": "docs/OBSERVABILITY.md",
               "fault-point": "docs/ROBUSTNESS.md",
               "endpoint": "docs/OBSERVABILITY.md"}
    findings: List[Finding] = []
    for surface, missing in _registry.diff_registries(
            package_root, docs_root).items():
        for name in sorted(missing):
            findings.append(Finding(
                check=f"registry-{surface}", path=doc_for[surface],
                line=1, scope=surface, detail=name,
                message=(f"{surface} `{name}` is used in cook_tpu/ but "
                         f"not registered in {doc_for[surface]}")))
    return findings


# --------------------------------------------------------------------------
# pass: pallas module-level jnp constants (the capture pitfall)
# --------------------------------------------------------------------------

def _expr_uses_jnp(node: ast.AST) -> bool:
    """True when an expression references jnp / jax.numpy (an array
    BUILT at import time)."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id == "jnp":
            return True
        if isinstance(sub, ast.Attribute):
            d = _dotted(sub)
            if d.startswith("jnp.") or d.startswith("jax.numpy."):
                return True
    return False


def pallas_module_constants(path: Path, relpath: str, tree: ast.Module,
                           src_lines: Sequence[str]) -> List[Finding]:
    """No module-level ``jnp`` constants in ``ops/pallas_*.py``: a jnp
    array built at import time is CAPTURED by every pallas kernel that
    references it — it pins a device buffer for the process lifetime,
    breaks interpret/compiled parity across backends, and (on TPU) is
    constant-folded into the Mosaic binary where a python literal would
    have stayed a scalar.  ops/pallas_match.py documents the pitfall by
    hand (`_BIG = 2**31 - 1  # python literal ...`); this pass enforces
    it for every pallas module (ISSUE 14 satellite)."""
    name = Path(relpath).name
    if not (relpath.startswith("ops/") and name.startswith("pallas_")
            and name.endswith(".py")):
        return []
    findings: List[Finding] = []
    for node in tree.body:  # module level ONLY: function bodies trace
        targets: List[ast.expr] = []
        value: Optional[ast.AST] = None
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        if value is None or not _expr_uses_jnp(value):
            continue
        tnames = ", ".join(
            t.id for t in targets if isinstance(t, ast.Name)) or "<target>"
        findings.append(Finding(
            check="pallas-module-constant", path=relpath,
            line=node.lineno, scope="<module>", detail=tnames,
            message=(f"module-level jnp constant `{tnames}` in a pallas "
                     "module: import-time jnp arrays are captured by "
                     "every kernel trace (device-buffer pin, "
                     "interpret/compiled drift) — use a python literal "
                     "and build arrays inside the kernel/entry point")))
    return findings


# --------------------------------------------------------------------------
# pass: partition isolation (the multi-controller ownership boundary)
# --------------------------------------------------------------------------

#: modules allowed to index/iterate sibling partition stores: the
#: PartitionedStore / UserSummaryExchange facade itself
PARTITION_FACADE_FILES = ("state/partition.py",)


class _PartitionIsolation(_ScopeWalker):
    def __init__(self, relpath: str):
        super().__init__()
        self.relpath = relpath
        self.findings: List[Finding] = []

    def _is_partitions_attr(self, node: ast.AST) -> bool:
        return (isinstance(node, ast.Attribute)
                and node.attr == "partitions")

    def _iter_target(self, it: ast.AST) -> Optional[ast.AST]:
        """The `.partitions` attribute an iteration walks, unwrapping
        enumerate()/reversed()/list()."""
        if self._is_partitions_attr(it):
            return it
        if isinstance(it, ast.Call) and isinstance(it.func, ast.Name) \
                and it.func.id in ("enumerate", "reversed", "list",
                                   "tuple", "sorted"):
            for arg in it.args:
                if self._is_partitions_attr(arg):
                    return arg
        return None

    def _flag(self, node: ast.AST, attr: ast.AST, how: str) -> None:
        owner = _dotted(attr.value) or "<expr>"  # type: ignore[attr-defined]
        self.findings.append(Finding(
            check="partition-isolation", path=self.relpath,
            line=node.lineno, scope=self.qualname(),
            detail=f"{owner}.partitions",
            message=(f"direct cross-partition store access "
                     f"(`{owner}.partitions` {how}): one shard process "
                     "owns one partition's write plane — sibling state "
                     "crosses only via UserSummaryExchange / the "
                     "PartitionedStore facade (state/partition.py)")))

    def visit_Subscript(self, node):  # noqa: N802
        if self._is_partitions_attr(node.value):
            self._flag(node, node.value, "subscript")
        self.generic_visit(node)

    def visit_For(self, node):  # noqa: N802
        attr = self._iter_target(node.iter)
        if attr is not None:
            self._flag(node, attr, "iteration")
        self.generic_visit(node)

    visit_AsyncFor = visit_For

    def visit_comprehension(self, node):  # noqa: N802
        attr = self._iter_target(node.iter)
        if attr is not None:
            self._flag(node.iter, attr, "iteration")
        self.generic_visit(node)


def partition_isolation(path: Path, relpath: str, tree: ast.Module,
                        src_lines: Sequence[str]) -> List[Finding]:
    """Forbid reaching THROUGH the partition boundary: subscripting or
    iterating a ``.partitions`` store list anywhere outside the
    state/partition.py facade.  In the multi-controller deployment each
    partition's Store lives in a different PROCESS — code that indexes a
    sibling partition's store only works single-process and silently
    breaks the scale-out contract (cross-pool reads must ride the
    bounded UserSummaryExchange; routed writes go through
    PartitionedStore).  Reading a ``PartitionConfig.partitions`` field
    is fine — only indexing/iterating the store list is flagged."""
    if relpath in PARTITION_FACADE_FILES:
        return []
    walker = _PartitionIsolation(relpath)
    walker.visit(tree)
    return walker.findings


#: the per-file passes, in run order
PASSES = (
    ("lock-discipline", lock_discipline),
    ("jit-hygiene", jit_hygiene),
    ("pallas-module-constant", pallas_module_constants),
    ("partition-isolation", partition_isolation),
)
