"""cook_tpu — a TPU-native multitenant fair-share batch scheduler.

A ground-up rebuild of the capabilities of Cook (Two Sigma's fair-share batch
scheduler, reference at /root/reference): DRU-based fair-share ranking,
offer/bin-packing job->host matching, preemptive rebalancing, quotas/shares/
rate limits, pluggable compute-cluster backends, REST API + clients, and a
faster-than-real-time trace-replay simulator.

Unlike the reference (Clojure + Java Fenzo), the per-cycle scheduling hot path
is implemented as jitted, batched JAX/XLA computations:

- ``cook_tpu.ops.dru``      — fair-share (DRU) ranking as segmented prefix sums
                              (reference: scheduler/src/cook/scheduler/dru.clj)
- ``cook_tpu.ops.match``    — jobs x offers bin-packing assignment kernels
                              (reference: Fenzo scheduleOnce, scheduler.clj:617-687)
- ``cook_tpu.ops.rebalance``— preemption victim search
                              (reference: scheduler/src/cook/rebalancer.clj:320-407)
- ``cook_tpu.parallel``     — per-pool sharding over a TPU mesh (shard_map) with
                              ICI collectives for cross-pool reconciliation

The control plane (transactional store, state machines, cluster backends, REST,
policy) stays host-side, mirroring the reference's layer map (SURVEY.md section 1).

Clients and integrations: ``cook_tpu.client`` (Python JobClient),
``cook_tpu.native.jobclient`` (the embeddable C++ client, ctypes-bound),
``cook_tpu.cli`` (the ``cs`` command line), and ``cook_tpu.ecosystem``
(ServiceFarm fleets + the dask CookCluster backend).
"""

__version__ = "0.1.0"
