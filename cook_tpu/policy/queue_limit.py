"""Pending-queue length limits enforced at submission.

Mirrors the reference's queue limits (reference:
scheduler/src/cook/queue_limit.clj:56-188): per-pool and per-pool-per-user
caps on the number of pending (waiting) jobs; a submission that would exceed
either cap is rejected before anything is transacted.  Counts are maintained
incrementally from the store's tx feed plus a periodic full re-query (the
reference updates on submit/kill and re-queries on an interval).
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from ..state.schema import JobState
from ..state.store import Store


class QueueLimits:
    def __init__(self, store: Store,
                 per_pool_limit: int = 1_000_000,
                 per_user_limit: int = 1_000_000,
                 user_overrides: Optional[Dict[str, int]] = None):
        self.store = store
        self.per_pool_limit = per_pool_limit
        self.per_user_limit = per_user_limit
        self.user_overrides = dict(user_overrides or {})
        self._lock = threading.Lock()
        self._pool_counts: Dict[str, int] = {}
        self._pool_user_counts: Dict[str, Dict[str, int]] = {}
        self.refresh()
        store.subscribe(self._on_events)

    # ----------------------------------------------------------- accounting
    def refresh(self) -> None:
        """Full re-query (reference: query-queue-lengths)."""
        pools: Dict[str, int] = {}
        pool_users: Dict[str, Dict[str, int]] = {}
        for job in self.store.jobs_where(
                lambda j: j.state is JobState.WAITING):
            pools[job.pool] = pools.get(job.pool, 0) + 1
            users = pool_users.setdefault(job.pool, {})
            users[job.user] = users.get(job.user, 0) + 1
        with self._lock:
            self._pool_counts = pools
            self._pool_user_counts = pool_users

    def _on_events(self, tx_id: int, events) -> None:
        for e in events:
            if e.kind == "job-created":
                self._bump(e.data["pool"], e.data["user"], +1)
            elif e.kind == "job-state":
                job = self.store.job(e.data["uuid"])
                if job is None:
                    continue
                if e.data.get("new") == "waiting":
                    self._bump(job.pool, job.user, +1)
                elif e.data.get("old") == "waiting":
                    self._bump(job.pool, job.user, -1)

    def _bump(self, pool: str, user: str, delta: int) -> None:
        with self._lock:
            self._pool_counts[pool] = max(
                0, self._pool_counts.get(pool, 0) + delta)
            users = self._pool_user_counts.setdefault(pool, {})
            users[user] = max(0, users.get(user, 0) + delta)

    # ------------------------------------------------------------ interface
    def user_limit(self, user: str) -> int:
        return self.user_overrides.get(user, self.per_user_limit)

    def check_submission(self, pool: str, user: str,
                         n_jobs: int) -> Optional[str]:
        """None when allowed; else a rejection message."""
        with self._lock:
            pool_count = self._pool_counts.get(pool, 0)
            user_count = self._pool_user_counts.get(pool, {}).get(user, 0)
        if pool_count + n_jobs > self.per_pool_limit:
            return (f"queue limit exceeded for pool {pool}: "
                    f"{pool_count} pending, limit {self.per_pool_limit}")
        if user_count + n_jobs > self.user_limit(user):
            return (f"queue limit exceeded for user {user} in pool {pool}: "
                    f"{user_count} pending, limit {self.user_limit(user)}")
        return None

    def counts(self) -> Dict[str, Dict]:
        with self._lock:
            return {
                "pools": dict(self._pool_counts),
                "users": {p: dict(u) for p, u in self._pool_user_counts.items()},
            }
