from .plugins import (  # noqa: F401
    FileUrlGenerator,
    InstanceCompletionHandler,
    JobAdjuster,
    JobLaunchFilter,
    JobRouter,
    JobSubmissionModifier,
    JobSubmissionValidator,
    PluginRegistry,
    PluginResult,
    PoolSelector,
)
from .queue_limit import QueueLimits  # noqa: F401
from .rate_limit import (  # noqa: F401
    RateLimits,
    TokenBucketRateLimiter,
    UnlimitedRateLimiter,
    pool_user_key,
    submission_limiter,
)
