"""Token-bucket rate limiting.

Mirrors the reference's rate-limit engine (reference:
scheduler/src/cook/rate_limit/token_bucket_filter.clj — lazy-replenish token
buckets — and rate_limit/generic.clj:86-157 — a keyed cache of buckets with
an enforce? flag).  Instances cover the same three planes the reference
wires (rate_limit.clj:30-56): job submission per user, per-user-per-pool
launches, and per-compute-cluster launches.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Dict, Optional


@dataclass
class _Bucket:
    tokens: float
    last_update_s: float


class TokenBucketRateLimiter:
    """Keyed token buckets: ``bucket_size`` capacity, replenished at
    ``tokens_per_minute``; going into debt is allowed (the caller spends
    first, then asks ``time_until_out_of_debt``), matching the reference's
    earn-then-spend filter semantics."""

    def __init__(self, tokens_per_minute: float, bucket_size: float,
                 enforce: bool = True,
                 clock=time.monotonic):
        self.tokens_per_minute = float(tokens_per_minute)
        self.bucket_size = float(bucket_size)
        self.enforce = enforce
        self._clock = clock
        self._buckets: Dict[str, _Bucket] = {}
        self._lock = threading.Lock()
        # adaptive-admission handle (sched/admission.py): the effective
        # refill rate is tokens_per_minute * refill_scale.  Replenishment
        # is lazy, so a scale change applies from the NEXT refresh on —
        # tokens already earned are never clawed back.
        self._refill_scale = 1.0

    @property
    def refill_scale(self) -> float:
        return self._refill_scale

    def set_refill_scale(self, scale: float) -> None:
        """Scale the refill rate by ``scale`` in [0, 1] (the admission
        level).  Buckets are refreshed lazily, so tokens accrued under
        the old scale stay earned; only future replenishment slows."""
        with self._lock:
            # settle every bucket at the OLD rate first so the scale
            # change is not applied retroactively to elapsed time
            for key in list(self._buckets):
                self._refresh(key)
            self._refill_scale = min(max(float(scale), 0.0), 1.0)

    def _effective_rate(self) -> float:
        return self.tokens_per_minute * self._refill_scale

    def _refresh(self, key: str) -> _Bucket:
        now = self._clock()
        bucket = self._buckets.get(key)
        if bucket is None:
            bucket = _Bucket(tokens=self.bucket_size, last_update_s=now)
            self._buckets[key] = bucket
        else:
            earned = (now - bucket.last_update_s) / 60.0 \
                * self._effective_rate()
            bucket.tokens = min(self.bucket_size, bucket.tokens + earned)
            bucket.last_update_s = now
        return bucket

    def get_token_count(self, key: str) -> float:
        with self._lock:
            return self._refresh(key).tokens

    def spend(self, key: str, n: float = 1.0) -> None:
        with self._lock:
            bucket = self._refresh(key)
            bucket.tokens -= n

    def within_limit(self, key: str) -> bool:
        """True when the key has tokens (or enforcement is off)."""
        if not self.enforce:
            return True
        return self.get_token_count(key) > 0

    def try_spend(self, key: str, n: float = 1.0,
                  max_keys: int = 65536) -> bool:
        """Atomic check-and-spend: admit only when the key holds >= n full
        tokens (a separate check-then-spend would let N concurrent callers
        all pass on one token).

        The bucket map is bounded at ~``max_keys`` (keys are
        caller-controlled for HTTP clients).  Eviction preference:
        (1) fully-refilled buckets — lossless, an evicted key is
        recreated in the same full state; (2) longest-untouched buckets
        that are NOT in debt — evicting a throttled (in-debt) client
        would recreate it full and forgive the throttle; (3) only when
        everything is in debt (pathological flood), oldest-touched
        regardless — bounded memory wins."""
        if not self.enforce:
            return True
        with self._lock:
            if len(self._buckets) > max_keys:
                import heapq
                need = max(1024, len(self._buckets) - max_keys)
                full = [k for k, b in self._buckets.items()
                        if b.tokens >= self.bucket_size and k != key]
                for k in full[:need]:
                    del self._buckets[k]
                need -= min(need, len(full))
                if need > 0:
                    solvent = [k for k, b in self._buckets.items()
                               if b.tokens >= 0 and k != key]
                    pool = solvent if len(solvent) >= need else [
                        k for k in self._buckets if k != key]
                    for k in heapq.nsmallest(
                            need, pool,
                            key=lambda k: self._buckets[k].last_update_s):
                        del self._buckets[k]
            bucket = self._refresh(key)
            if bucket.tokens < n:
                return False
            bucket.tokens -= n
            return True

    def time_until_out_of_debt_s(self, key: str) -> float:
        with self._lock:
            tokens = self._refresh(key).tokens
            rate = self._effective_rate()
        if tokens >= 0:
            return 0.0
        if rate <= 0:
            return float("inf")
        return -tokens / rate * 60.0

    def retry_after_s(self, key: str, n: float = 1.0) -> float:
        """Seconds until ``key`` can afford ``n`` tokens at the current
        (scaled) refill rate — the honest ``Retry-After`` value for an
        admission 429.  Infinite when the scaled rate is zero."""
        with self._lock:
            tokens = self._refresh(key).tokens
            rate = self._effective_rate()
        short = n - tokens
        if short <= 0:
            return 0.0
        if rate <= 0:
            return float("inf")
        return short / rate * 60.0

    def flush(self) -> None:
        with self._lock:
            self._buckets.clear()

    def saturation(self) -> float:
        """Worst-key consumption fraction in [0, 1] — the launch-token
        saturation signal (sched/fleet.py): 0 = every bucket full,
        1 = some key fully spent (or in debt, which clamps).  Buckets
        are lazily refreshed first, so a key idle since its last spend
        reads its EARNED-BACK level, not its historical debt."""
        if self.bucket_size <= 0:
            return 0.0
        with self._lock:
            if not self._buckets:
                return 0.0
            low = min(self._refresh(key).tokens
                      for key in list(self._buckets))
        return min(max(1.0 - low / self.bucket_size, 0.0), 1.0)


class UnlimitedRateLimiter:
    """The no-op limiter used when a plane is unconfigured."""

    enforce = False
    refill_scale = 1.0

    def get_token_count(self, key: str) -> float:
        return float("inf")

    def spend(self, key: str, n: float = 1.0) -> None:
        pass

    def within_limit(self, key: str) -> bool:
        return True

    def try_spend(self, key: str, n: float = 1.0,
                  max_keys: int = 65536) -> bool:
        return True

    def set_refill_scale(self, scale: float) -> None:
        pass

    def time_until_out_of_debt_s(self, key: str) -> float:
        return 0.0

    def retry_after_s(self, key: str, n: float = 1.0) -> float:
        return 0.0

    def flush(self) -> None:
        pass

    def saturation(self) -> float:
        return 0.0


def pool_user_key(pool: str, user: str) -> str:
    return f"{pool}/{user}"


def submission_limiter(admission_conf, clock=time.monotonic):
    """Build the submission-side per-user limiter from an
    ``config.AdmissionConfig`` (rest/api.py front door).  Unconfigured
    (disabled, or refill 0) -> the no-op limiter, matching the other
    planes' unconfigured behavior."""
    if admission_conf is None or not getattr(admission_conf, "enabled",
                                             False):
        return UnlimitedRateLimiter()
    rate = float(getattr(admission_conf, "submissions_per_minute", 0.0))
    if rate <= 0:
        return UnlimitedRateLimiter()
    burst = float(getattr(admission_conf, "submission_burst", 0.0)) or rate
    return TokenBucketRateLimiter(rate, burst, enforce=True, clock=clock)


@dataclass
class RateLimits:
    """The three rate-limit planes (reference: rate_limit.clj)."""

    job_submission: object = None    # key: user
    job_launch: object = None        # key: pool/user
    cluster_launch: object = None    # key: cluster name

    def __post_init__(self):
        self.job_submission = self.job_submission or UnlimitedRateLimiter()
        self.job_launch = self.job_launch or UnlimitedRateLimiter()
        self.cluster_launch = self.cluster_launch or UnlimitedRateLimiter()
