"""Incremental configuration: portioned value rollouts.

Mirrors the reference's incremental config plane (reference:
scheduler/src/cook/config_incremental.clj:89-110): a key maps to a list of
{value, portion} entries; a job resolves to one value by hashing its uuid
into [0, 1) and walking the cumulative portions — so "90% old image, 10%
new image" rollouts are stable per job and adjustable without restarts.
"""

from __future__ import annotations

import hashlib
import threading
from typing import Any, Dict, List, Optional, Tuple


def _uuid_to_unit_interval(uuid: str) -> float:
    digest = hashlib.sha256(uuid.encode()).digest()
    return int.from_bytes(digest[:8], "big") / float(2**64)


class IncrementalConfig:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._configs: Dict[str, List[Tuple[Any, float]]] = {}

    def set(self, key: str, values: List[Dict[str, Any]]) -> None:
        """values: [{"value": ..., "portion": 0.9}, ...]; portions must sum
        to ~1 (validated like the reference's schema)."""
        self.set_many({key: values})

    def set_many(self, configs: Dict[str, List[Dict[str, Any]]]) -> None:
        """Validate every key, then commit atomically — a rejected request
        must change nothing."""
        validated: Dict[str, List[Tuple[Any, float]]] = {}
        for key, values in configs.items():
            entries = [(v["value"], float(v["portion"])) for v in values]
            total = sum(p for _v, p in entries)
            if entries and abs(total - 1.0) > 1e-6:
                raise ValueError(
                    f"portions for {key} sum to {total}, expected 1")
            validated[key] = entries
        with self._lock:
            self._configs.update(validated)

    def delete(self, key: str) -> None:
        with self._lock:
            self._configs.pop(key, None)

    def resolve(self, key: str, job_uuid: str, default: Any = None) -> Any:
        with self._lock:
            entries = self._configs.get(key)
        if not entries:
            return default
        x = _uuid_to_unit_interval(job_uuid)
        cumulative = 0.0
        for value, portion in entries:
            cumulative += portion
            if x < cumulative:
                return value
        return entries[-1][0]

    def all(self) -> Dict[str, List[Dict[str, Any]]]:
        with self._lock:
            return {k: [{"value": v, "portion": p} for v, p in entries]
                    for k, entries in self._configs.items()}
