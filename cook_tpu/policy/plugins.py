"""Plugin extension points.

Mirrors the reference's eight plugin protocols (reference:
scheduler/src/cook/plugins/definitions.clj:18-67) with config-driven
registration (plugins/*.clj factory loading) and the launch filter's
accept/defer cache (plugins/launch.clj:140).
"""

from __future__ import annotations

import importlib
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..state.schema import Instance, Job


@dataclass
class PluginResult:
    """accepted/deferred verdict with an optional retry time (reference:
    plugins/definitions.clj FilterResult)."""

    status: str  # "accepted" | "rejected" | "deferred"
    message: str = ""
    cache_expires_at_s: Optional[float] = None

    @classmethod
    def accepted(cls, message: str = "", ttl_s: Optional[float] = None):
        return cls("accepted", message,
                   time.time() + ttl_s if ttl_s else None)

    @classmethod
    def rejected(cls, message: str = ""):
        return cls("rejected", message)

    @classmethod
    def deferred(cls, message: str = "", ttl_s: float = 60.0):
        return cls("deferred", message, time.time() + ttl_s)


class JobSubmissionValidator:
    """Accept/reject a job at submission (definitions.clj JobSubmissionValidator)."""

    def validate(self, job: Job) -> PluginResult:
        return PluginResult.accepted()


class JobSubmissionModifier:
    """Rewrite a job at submission time (definitions.clj JobSubmissionModifier)."""

    def modify(self, job: Job) -> Job:
        return job


class JobLaunchFilter:
    """Accept/defer a job right before it becomes considerable
    (definitions.clj JobLaunchFilter)."""

    def check(self, job: Job) -> PluginResult:
        return PluginResult.accepted()


class InstanceCompletionHandler:
    """Side effect after an instance completes (definitions.clj
    InstanceCompletionHandler)."""

    def on_completion(self, job: Job, instance: Instance) -> None:
        pass


class PoolSelector:
    """Pick the pool for a submitted job (definitions.clj PoolSelector)."""

    def select(self, job: Job, default_pool: str) -> str:
        return job.pool or default_pool


class JobAdjuster:
    """Adjust a job just before matching (definitions.clj JobAdjuster)."""

    def adjust(self, job: Job) -> Job:
        return job


class JobRouter:
    """Route a job to a scheduling variant (definitions.clj JobRouter)."""

    def route(self, job: Job) -> Optional[str]:
        return None


class FileUrlGenerator:
    """Build sandbox file-access URLs for an instance (definitions.clj
    FileUrlGenerator)."""

    def url(self, instance: Instance, path: str) -> Optional[str]:
        return None


@dataclass
class PluginRegistry:
    validators: List[JobSubmissionValidator] = field(default_factory=list)
    modifiers: List[JobSubmissionModifier] = field(default_factory=list)
    launch_filters: List[JobLaunchFilter] = field(default_factory=list)
    completion_handlers: List[InstanceCompletionHandler] = field(default_factory=list)
    pool_selector: PoolSelector = field(default_factory=PoolSelector)
    adjusters: List[JobAdjuster] = field(default_factory=list)
    router: JobRouter = field(default_factory=JobRouter)
    file_url_generator: FileUrlGenerator = field(default_factory=FileUrlGenerator)
    # launch-filter verdict cache: job uuid -> result (plugins/launch.clj:140)
    _launch_cache: Dict[str, PluginResult] = field(default_factory=dict)

    # ------------------------------------------------------------ lifecycle
    @classmethod
    def from_config(cls, spec: Dict[str, Any]) -> "PluginRegistry":
        """Instantiate plugins from dotted-path factory names, the moral
        equivalent of the reference's symbol-resolving factory-fn loading.

        Each entry is either a dotted path string (no-arg construction) or
        ``{"factory": path, "kwargs": {...}}`` for parameterized plugins
        like PoolMoverPlugin."""

        def build(entry):
            if isinstance(entry, str):
                path, kwargs = entry, {}
            else:
                path, kwargs = entry["factory"], entry.get("kwargs", {})
            module, _, attr = path.rpartition(".")
            return getattr(importlib.import_module(module), attr)(**kwargs)

        reg = cls()
        slots = {
            "validators": reg.validators, "modifiers": reg.modifiers,
            "launch_filters": reg.launch_filters,
            "completion_handlers": reg.completion_handlers,
            "adjusters": reg.adjusters,
        }
        for slot, target in slots.items():
            for entry in spec.get(slot, []):
                target.append(build(entry))
        for slot in ("pool_selector", "router", "file_url_generator"):
            entry = spec.get(slot)
            if entry:
                setattr(reg, slot, build(entry))
        return reg

    # ------------------------------------------------------------- dispatch
    def validate_submission(self, job: Job) -> Optional[str]:
        for v in self.validators:
            result = v.validate(job)
            if result.status != "accepted":
                return result.message or "rejected by submission plugin"
        return None

    def modify_submission(self, job: Job) -> Job:
        for m in self.modifiers:
            job = m.modify(job)
        for a in self.adjusters:
            job = a.adjust(job)
        return job

    def launch_verdict_cached(self, uuid: str):
        """Non-materializing probe of the launch-verdict cache: True/False
        when a live cached verdict exists for the job uuid, None on miss
        (callers then fetch the entity and call launch_allowed).  Lets the
        columnar fused pack skip entity deep-copies in steady state."""
        cached = self._launch_cache.get(uuid)
        if cached is None:
            return None
        if (cached.cache_expires_at_s is not None
                and cached.cache_expires_at_s <= time.time()):
            return None
        return cached.status == "accepted"

    def cache_launch_verdict(self, uuid: str, allowed: bool,
                             ttl_s: float = 60.0) -> None:
        """Record a verdict without materializing a Job (used for rows whose
        entity has vanished from the store but not yet from the index)."""
        r = (PluginResult.accepted() if allowed
             else PluginResult.rejected("cached"))
        r.cache_expires_at_s = time.time() + ttl_s
        self._launch_cache[uuid] = r

    def launch_allowed(self, job: Job) -> bool:
        """Cached accept/defer check used by considerable-job selection."""
        if not self.launch_filters:
            return True
        cached = self._launch_cache.get(job.uuid)
        now = time.time()
        if cached is not None and (cached.cache_expires_at_s is None
                                   or cached.cache_expires_at_s > now):
            return cached.status == "accepted"
        verdict = PluginResult.accepted()
        for f in self.launch_filters:
            verdict = f.check(job)
            if verdict.status != "accepted":
                break
        if verdict.cache_expires_at_s is None:
            verdict.cache_expires_at_s = now + 60.0
        self._launch_cache[job.uuid] = verdict
        if len(self._launch_cache) > 4096:
            self._launch_cache = {
                k: v for k, v in self._launch_cache.items()
                if v.cache_expires_at_s is None or v.cache_expires_at_s > now}
        return verdict.status == "accepted"

    def on_instance_completion(self, job: Job, instance: Instance) -> None:
        for h in self.completion_handlers:
            try:
                h.on_completion(job, instance)
            except Exception:  # pragma: no cover - plugin errors are isolated
                import logging
                logging.getLogger(__name__).exception(
                    "completion plugin failed")


class PoolMoverPlugin(JobSubmissionModifier):
    """Migrate a portion of configured users' jobs to a destination pool at
    submission time (reference: plugins/pool_mover.clj — gradual pool
    migration driven by per-user portions).

    ``moves`` maps source pool -> {"destination": pool, "users": {user:
    portion}}; a job moves when the fraction derived from its uuid hash is
    below the user's portion, so rollouts are deterministic per job and
    tunable per user (same portion mechanism as incremental config).
    """

    def __init__(self, moves: Optional[Dict[str, Dict[str, Any]]] = None):
        self.moves = moves or {}
        for src, rule in self.moves.items():
            if "destination" not in rule:
                raise ValueError(
                    f"pool-mover rule for {src!r} missing 'destination'")

    def modify(self, job: Job) -> Job:
        from .incremental import _uuid_to_unit_interval

        rule = self.moves.get(job.pool)
        if not rule:
            return job
        portion = rule.get("users", {}).get(job.user)
        if portion is None:
            return job
        if _uuid_to_unit_interval(job.uuid) < float(portion):
            job.pool = rule["destination"]
        return job
