"""Pipelined optimistic match cycles: overlap device dispatch with host
apply (the Omega shape — Schwarzkopf et al., EuroSys'13 — over the fused
cycle kernel).

The synchronous driver (sched/fused.py) serializes every cycle:
pack -> upload -> dispatch -> BLOCKING fetch -> transactional launch.  On
a tunneled chip the blocking fetch pays the full device sync + tunnel RTT
every cycle, and the device sits idle while the host runs the launch
path; bench's ``pipeline`` section proved years of cycles ago that depth-k
pipelining amortizes that round trip to noise, but the production driver
never used it.  This module is the production form.

One :meth:`PipelinedCycleDriver.step` at depth 2:

1. **fetch** the in-flight cycle *k* — its compact outputs have been
   copying device->host asynchronously since last step, so the sync wait
   is (close to) zero;
2. **stage + dispatch** cycle *k+1* against the store snapshot — which is
   *optimistically stale*: cycle *k*'s launches haven't been applied yet.
   Two host-side corrections keep the speculation coherent
   (``FusedCycleDriver.stage`` hooks):

   - cycle *k*'s fetched launch candidates are masked out of *k+1*'s
     ``launch_ok`` (back-to-back cycles must not fight over the head of
     the queue), and
   - the resources those candidates will consume are subtracted from
     *k+1*'s staged offer availability, so speculative placements stay
     feasible;

3. **apply** cycle *k* — the guard transaction and backend launch RPCs
   run on host *while the device computes k+1*.  Before launching, an
   Omega-style **reconciliation** (``fused.reconcile`` span) re-validates
   every candidate against the live store: a candidate whose job is no
   longer WAITING (launched by an overlapped cycle, killed by a user, or
   vanished) is dropped — never double-launched — and pruned from the
   published queue; a candidate whose host availability was consumed by
   an untracked overlapped launch falls back to unmatched and retries
   next cycle.  Drops are counted on the CycleRecord
   (``pipeline_conflicts``) and ``cook_pipeline_conflicts_total``.

The store's transactional launch guard (``allowed_to_start``) remains the
hard backstop underneath all of this: even a reconciliation bug cannot
double-launch, it can only waste a guard denial.

``pipeline_depth=0`` (config.PipelineConfig) never constructs this class:
the scheduler drives the synchronous FusedCycleDriver bit-for-bit as
before.  Depths above 2 are allowed but add speculation: intermediate
cycles are dispatched before their predecessors are fetched, so their
candidates can't be masked and the conflict-drop rate rises —
reconciliation absorbs it, throughput pays for it.
"""

from __future__ import annotations

import itertools
from collections import deque
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..config import PipelineConfig
from ..state.schema import Job, JobState
from ..utils import tracing
from ..utils.flight import recorder as _flight
from ..utils.metrics import registry
from .fused import F32, FusedCycleDriver, _GroupDispatch, _StagedCycle
from .matcher import MatchCycleResult


class _InFlight:
    """One optimistic cycle between dispatch and apply."""

    __slots__ = ("id", "staged", "dispatches", "fetched", "exclude",
                 "consumed", "tokens_spent", "delta", "knows", "staged_tx")

    def __init__(self, id_: int, staged: _StagedCycle,
                 dispatches: List[_GroupDispatch], staged_tx: int = -1):
        self.id = id_
        self.staged = staged
        self.dispatches = dispatches
        self.staged_tx = staged_tx
        self.fetched = False
        # computed at fetch: per-pool candidate footprint for masking the
        # NEXT stage -- pool name -> ("rows"|"uuids", epoch, ids) -- and
        # the per-host resources those candidates will consume
        self.exclude: Dict[str, tuple] = {}
        self.consumed: Dict[tuple, np.ndarray] = {}
        # pool name -> user -> launch-rate tokens this entry's assigned
        # candidates will spend (one per launch); subtracted from the
        # NEXT stage's staged token budgets so overlapped cycles cannot
        # hand the same user depth-x the configured per-cycle rate
        self.tokens_spent: Dict[str, Dict[str, float]] = {}
        # per-host overdraft this cycle's staged avail did NOT see:
        # launches applied after this cycle staged by entries whose
        # candidates were not already subtracted at stage time
        self.delta: Dict[tuple, np.ndarray] = {}
        # ids of in-flight entries whose candidate footprint WAS
        # subtracted from this entry's staged avail (no double charge)
        self.knows: set = set()


class PipelinedCycleDriver:
    """Drives FusedCycleDriver's stage/dispatch/fetch/apply phases as a
    depth-k pipeline.  ``step(scheduler)`` has the same signature and
    return contract as ``FusedCycleDriver.step``; the first call behaves
    exactly like the sync driver (stage, dispatch, fetch, apply the same
    cycle) and additionally leaves the next cycle's dispatch in flight."""

    def __init__(self, fused: FusedCycleDriver,
                 config: Optional[PipelineConfig] = None):
        self.fused = fused
        self.config = config or PipelineConfig()
        self.depth = max(1, self.config.depth)
        self._inflight: "deque[_InFlight]" = deque()
        self._ids = itertools.count(1)
        # lifetime conflict counters (the bench section reads these)
        self.conflicts_state = 0
        self.conflicts_resources = 0

    # ------------------------------------------------------------- lifecycle
    def reset(self) -> None:
        """Drop all in-flight speculation (leader handoff, degraded
        cycle).  Safe: an unapplied dispatch has transacted nothing — its
        candidates are still WAITING and re-enter the next cycle."""
        self._inflight.clear()

    def inflight(self) -> int:
        return len(self._inflight)

    # ------------------------------------------------------------------ step
    def step(self, scheduler) -> Tuple[Dict[str, List[Job]],
                                       Dict[str, MatchCycleResult]]:
        registry.gauge_set("cook_pipeline_depth", float(self.depth))
        if not self._inflight:
            entry = self._stage_dispatch(scheduler)
            self._inflight.append(entry)
        head = self._inflight[0]
        self._fetch(head)
        # keep depth-1 speculative dispatches in flight while the head's
        # launches are applied below: the device computes cycle k+1 while
        # the host transacts cycle k
        while len(self._inflight) < self.depth:
            self._inflight.append(self._stage_dispatch(
                scheduler, after=[e for e in self._inflight if e.fetched]))
        _flight.note_pipeline(self.depth, len(self._inflight) - 1)
        self._inflight.popleft()
        queues, results = self._apply(scheduler, head)
        launched = sum(len(r.launched_task_ids) for r in results.values())
        if launched == 0 and self._inflight \
                and self._store_tx() != head.staged_tx:
            # Empty-head promotion: the speculative head predated a store
            # mutation (a retry re-entered the queue, a submission landed,
            # a kill freed capacity) and produced nothing — but the next
            # in-flight cycle was staged THIS step from the current store
            # and is already computing.  Apply it now instead of idling a
            # whole cadence tick: an unproductive pipeline has no RTT to
            # hide, so the extra fetch costs what the sync driver always
            # paid.  This keeps pipelined reactivity step-equivalent to
            # the sync driver whenever the pipeline is empty-handed.
            nxt = self._inflight.popleft()
            self._fetch(nxt)
            q2, r2 = self._apply(scheduler, nxt)
            queues.update(q2)
            results.update(r2)
            while len(self._inflight) < self.depth - 1:
                self._inflight.append(self._stage_dispatch(
                    scheduler,
                    after=[e for e in self._inflight if e.fetched]))
        return queues, results

    def _store_tx(self) -> int:
        return getattr(self.fused.store, "_tx_id", -1)

    # ----------------------------------------------------------------- stage
    def _stage_dispatch(self, scheduler,
                        after: Optional[List[_InFlight]] = None) -> _InFlight:
        """Stage a cycle off the current store, masked by the candidate
        footprints of every fetched-but-unapplied entry in ``after``, and
        dispatch all its groups (async output copies start rolling)."""
        exclude: Dict[str, tuple] = {}
        avail_delta: Dict[tuple, np.ndarray] = {}
        token_delta: Dict[str, Dict[str, float]] = {}
        knows = set()
        for e in after or []:
            knows.add(e.id)
            # per-pool MERGE (plain update would keep only the last
            # entry's mask when several fetched entries cover one pool —
            # the dropped candidates would be re-picked and then burned
            # as reconcile conflicts)
            for pool_name, (kind, epoch, ids) in e.exclude.items():
                cur = exclude.get(pool_name)
                if cur is None:
                    exclude[pool_name] = (kind, epoch, ids)
                elif cur[0] == kind == "rows" and cur[1] == epoch:
                    exclude[pool_name] = (
                        "rows", epoch, np.union1d(cur[2], ids))
                elif cur[0] == kind == "uuids":
                    exclude[pool_name] = ("uuids", -1, cur[2] | ids)
                # mixed kinds / mismatched epochs: keep the newer mask
                # (reconciliation absorbs the unmasked remainder)
                else:
                    exclude[pool_name] = (kind, epoch, ids)
            for key, vec in e.consumed.items():
                cur = avail_delta.get(key)
                avail_delta[key] = vec if cur is None else cur + vec
            for pool_name, spent in e.tokens_spent.items():
                cur_pool = token_delta.setdefault(pool_name, {})
                for user, n in spent.items():
                    cur_pool[user] = cur_pool.get(user, 0.0) + n
        staged_tx = self._store_tx()
        staged = self.fused.stage(scheduler, exclude=exclude or None,
                                  avail_delta=avail_delta or None,
                                  token_delta=token_delta or None)
        dispatches = []
        for sg in staged.groups:
            with tracing.span("cycle.match", pools=len(sg.group),
                              tasks=sg.T, hosts=sg.H, gpu=sg.gpu_mode):
                dispatches.append(self.fused.dispatch_group(sg))
        entry = _InFlight(next(self._ids), staged, dispatches,
                          staged_tx=staged_tx)
        entry.knows = knows
        return entry

    # ----------------------------------------------------------------- fetch
    def _fetch(self, entry: _InFlight) -> None:
        if entry.fetched:
            return
        for gd in entry.dispatches:
            with tracing.span("cycle.match", pools=len(gd.sg.group),
                              tasks=gd.sg.T, hosts=gd.sg.H,
                              gpu=gd.sg.gpu_mode):
                self.fused.fetch_group(gd)
        entry.fetched = True
        self._candidate_footprint(entry)

    def _candidate_footprint(self, entry: _InFlight) -> None:
        """From the fetched outputs, the footprint the NEXT stage must
        speculate around: which queue rows/uuids are about to launch, and
        how much of each host they will consume.

        Gang candidates need care (docs/GANG.md): a PARTIAL gang among
        the candidates will be reset by the all-or-nothing reduction at
        apply — it launches nothing — so masking its members out of the
        next stage would let the two in-flight cycles hold complementary
        halves of the gang forever (each stage only ever sees the part
        the other isn't holding: a permanent ping-pong livelock).  Only
        COMPLETE gang cohorts enter the exclusion/consumption footprint."""
        for gd in entry.dispatches:
            # megakernel dispatches carry two extra gang-verdict arrays
            # past the four compact outputs (sched/fused.apply_group)
            cand_row, cand_assign, _qpos, _nq = gd.fetched[:4]
            for i, pp in enumerate(gd.sg.group):
                sel = np.flatnonzero((cand_row[i] >= 0)
                                     & (cand_assign[i] >= 0))
                if not len(sel):
                    continue
                hosts = cand_assign[i][sel].astype(np.int64)
                # clip padding hosts defensively (mirrors _apply_pool)
                ok = hosts < len(pp.offers)
                sel, hosts = sel[ok], hosts[ok]
                if not len(sel):
                    continue
                if pp.columnar:
                    rows = pp.rows_s[cand_row[i][sel]]
                    uuids = [str(u) for u in pp.uuid_base[rows]]
                    keep = self._whole_gang_mask(pp, uuids)
                    sel, hosts, rows = sel[keep], hosts[keep], rows[keep]
                    if not len(sel):
                        continue
                    entry.exclude[pp.pool.name] = (
                        "rows", pp.base_compactions, rows)
                    res = np.concatenate(
                        [pp.res_base[rows][:, :3],
                         pp.disk_base[rows][:, None]], axis=1).astype(F32)
                    users = [str(u) for u in pp.user_base[rows]]
                else:
                    jobs = [pp.id2job[pp.task_ids[r]]
                            for r in cand_row[i][sel]]
                    keep = self._whole_gang_mask(
                        pp, [j.uuid for j in jobs])
                    sel, hosts = sel[keep], hosts[keep]
                    jobs = [j for j, k in zip(jobs, keep) if k]
                    if not len(sel):
                        continue
                    entry.exclude[pp.pool.name] = (
                        "uuids", -1, frozenset(j.uuid for j in jobs))
                    res = np.array(
                        [[j.resources.cpus, j.resources.mem,
                          j.resources.gpus, j.resources.disk]
                         for j in jobs], dtype=F32)
                    users = [j.user for j in jobs]
                spent = entry.tokens_spent.setdefault(pp.pool.name, {})
                for user in users:
                    spent[user] = spent.get(user, 0.0) + 1.0
                for j, h in enumerate(hosts):
                    o = pp.offers[int(h)]
                    key = (o.cluster, o.hostname)
                    cur = entry.consumed.get(key)
                    entry.consumed[key] = (res[j] if cur is None
                                           else cur + res[j])

    def _whole_gang_mask(self, pp, uuids) -> np.ndarray:
        """bool mask over assigned candidates keeping non-gang jobs and
        COMPLETE gang cohorts; members of partial cohorts are dropped
        from the speculation footprint (they cannot launch — the
        reduction resets them at apply).  Membership is derived from the
        pack context's gang groups (``Group.jobs`` — the REST layer
        guarantees a gang's member set is exactly its co-submitted
        jobs), so the mask never reads the store: a candidate batch with
        zero gang members stays a structural no-op even while unrelated
        gang groups sit waiting in the pool."""
        n = len(uuids)
        keep = np.ones(n, dtype=bool)
        groups = getattr(pp.ctx, "groups", None) if pp.ctx else None
        if not groups:
            return keep
        gang_of: Dict[str, str] = {}
        for guuid, g in groups.items():
            if getattr(g, "gang", False):
                for member_uuid in getattr(g, "jobs", None) or ():
                    gang_of[member_uuid] = guuid
        if not gang_of:
            return keep
        counts: Dict[str, int] = {}
        member_gang = [gang_of.get(u) for u in uuids]
        for guuid in member_gang:
            if guuid is not None:
                counts[guuid] = counts.get(guuid, 0) + 1
        partial = {guuid for guuid, c in counts.items()
                   if c < int(getattr(groups[guuid], "gang_size", 0) or 0)}
        if partial:
            for i, guuid in enumerate(member_gang):
                if guuid in partial:
                    keep[i] = False
        return keep

    # ----------------------------------------------------------------- apply
    def _apply(self, scheduler, entry: _InFlight
               ) -> Tuple[Dict[str, List[Job]], Dict[str, MatchCycleResult]]:
        queues: Dict[str, List[Job]] = {p.name: []
                                        for p in entry.staged.pools}
        results: Dict[str, MatchCycleResult] = {}
        reconciler = self._make_reconciler(entry)
        for gd in entry.dispatches:
            self.fused.apply_group(scheduler, gd, queues, results,
                                   reconciler=reconciler)
        # propagate this entry's ACTUAL launch consumption to in-flight
        # entries that did not already subtract its candidate footprint
        # at stage time (depth > 2, or a stage that raced this apply)
        consumed: Dict[tuple, np.ndarray] = {}
        for result in results.values():
            launched = set(result.launched_job_uuids)
            for job, offer in result.matched:
                if job.uuid not in launched:
                    continue
                vec = np.array([job.resources.cpus, job.resources.mem,
                                job.resources.gpus, job.resources.disk],
                               dtype=F32)
                key = (offer.cluster, offer.hostname)
                cur = consumed.get(key)
                consumed[key] = vec if cur is None else cur + vec
        if consumed:
            for other in self._inflight:
                if entry.id in other.knows:
                    continue  # footprint already subtracted at stage
                for key, vec in consumed.items():
                    cur = other.delta.get(key)
                    other.delta[key] = vec if cur is None else cur + vec
        return queues, results

    def _make_reconciler(self, entry: _InFlight):
        """The pre-launch re-validation hook handed to _apply_pool: state
        check against the live store + per-host feasibility against the
        overdraft this entry's staged avail never saw."""

        def reconcile(pp, cand_jobs, cand_host):
            n = len(cand_jobs)
            state_drop = np.zeros(n, dtype=bool)
            res_drop = np.zeros(n, dtype=bool)
            # --- state: still WAITING?  (columnar candidates were just
            # refetched by _apply_pool's jobs_bulk, so this is current;
            # the entity pack's candidates are stale clones — refetch)
            fresh = cand_jobs if pp.columnar else \
                self.fused.store.jobs_bulk([j.uuid for j in cand_jobs])
            for i, job in enumerate(fresh):
                if job is None or job.state is not JobState.WAITING:
                    state_drop[i] = True
            # --- resources: replay the kernel's placements against the
            # staged availability minus the untracked overdraft; slots
            # are in admission order, so the drop is deterministic
            if entry.delta and pp.offers:
                H = len(pp.offers)
                over = np.zeros((H, 4), dtype=F32)
                hit = False
                for h, o in enumerate(pp.offers):
                    d = entry.delta.get((o.cluster, o.hostname))
                    if d is not None:
                        over[h] = d
                        hit = True
                if hit:
                    headroom = np.maximum(
                        pp.avail[:H].astype(np.float64) - over, 0.0)
                    # the gang pass's rescue/refill re-places against
                    # availability too — hand it the same overdraft-
                    # adjusted view or it can refill a host this very
                    # reconcile just protected
                    pp.avail_headroom = headroom.astype(F32)
                    used = np.zeros((H, 4), dtype=np.float64)
                    for i, job in enumerate(cand_jobs):
                        h = int(cand_host[i])
                        if h < 0 or state_drop[i]:
                            continue
                        req = np.array([job.resources.cpus,
                                        job.resources.mem,
                                        job.resources.gpus,
                                        job.resources.disk])
                        if np.any(used[h] + req > headroom[h] + 1e-6):
                            res_drop[i] = True
                        else:
                            used[h] += req
            ns, nr = int(state_drop.sum()), int(res_drop.sum())
            if ns:
                registry.counter_inc("cook_pipeline_conflicts", float(ns),
                                     {"reason": "state"})
                self.conflicts_state += ns
            if nr:
                registry.counter_inc("cook_pipeline_conflicts", float(nr),
                                     {"reason": "resources"})
                self.conflicts_resources += nr
            if ns or nr:
                _flight.note_pipeline_conflicts(ns + nr)
                # per-job attribution of the drops (utils/audit.py): the
                # reconcile masks already name the jobs
                from ..utils import audit as _audit
                _audit.note_skips(self.fused.store.audit, {
                    "pipeline-conflict": [
                        (cand_jobs[i].uuid,
                         {"why": "state" if state_drop[i]
                          else "resources"})
                        for i in np.flatnonzero(state_drop | res_drop)],
                }, pool=pp.pool.name)
            return state_drop, res_drop

        return reconcile
