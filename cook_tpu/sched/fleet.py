"""Fleet observability plane: federation, trace stitching, saturation.

The topology the repo runs — an elected leader, partition leases, a
follower read fleet, remote agents — produced per-PROCESS telemetry
only: each member's flight recorder, span ring, RED metrics, and SLO
burn stop at its own process boundary.  This module builds the fleet
plane in the Dapper mold (Sigelman et al. 2010: propagate ids
everywhere, collect lazily, stitch centrally) with Monarch-style
(VLDB'20) bounded per-member aggregation, off ONE topology source: the
election candidate registry (state/replication.known_members) that
coordinated promotion already maintains.

Three layers (docs/OBSERVABILITY.md "Debugging the fleet"):

1. **Trace stitching** — every member keeps spans for adopted
   traceparents in its local ring (utils/tracing.py) and serves them
   raw at ``GET /debug/trace/spans?trace_id=``; :func:`collect_trace`
   fans out, merges, and dedupes, and tracing.export_fleet_trace turns
   the merged set into ONE Perfetto export with per-process tracks.
2. **Metrics federation** — :class:`FleetScraper` pulls each member's
   ``/metrics`` (driven by the monitor sweep, self-gated to
   ``scrape_interval_seconds``), re-labels with ``{instance, role}``
   under the cardinality discipline of utils/metrics.py, and serves
   the merged view at ``GET /metrics/fleet`` + ``GET /debug/fleet``.
   An unreachable member is DATA (``cook_fleet_member_up 0`` + its
   last error), never a silent gap.  Fleet-level SLO burn is the max
   over members per series (the page-worthy number: the worst burning
   process, not the average that dilutes it).
3. **Saturation signals** — :func:`compute_saturation` derives
   normalized 0-1 ``cook_saturation{resource=}`` gauges from existing
   counters each monitor sweep (formulas below, red line in
   FleetConfig) — the explicit input contract for the adaptive
   admission layer (ROADMAP item 3).

Network fetches never run under a lock (utils/locks.py blocking
discipline): a sweep snapshots the member list, fetches lock-free, and
installs results under the lock afterwards.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..config import Config, FleetConfig
from ..utils import tracing
from ..utils.metrics import (MetricsRegistry, format_sample,
                             parse_exposition, registry as default_registry)

#: series the scraper itself publishes; a member's own copies are
#: dropped from the merged exposition (a leader that federates would
#: otherwise re-federate its own federation gauges each sweep)
_FLEET_SELF = ("cook_fleet_member_up", "cook_fleet_scrape_age_seconds",
               "cook_fleet_dropped_series", "cook_fleet_slo_burn_rate",
               "cook_fleet_members")


def _default_fetch(url: str, timeout_s: float) -> str:
    """GET ``url`` as text; urllib only (zero new dependencies)."""
    import urllib.request
    with urllib.request.urlopen(url, timeout=timeout_s) as resp:
        return resp.read().decode("utf-8", "replace")


def _clamp01(v: float) -> float:
    """NaN-safe clamp into [0, 1] — every saturation gauge's contract."""
    v = float(v)
    if v != v:  # NaN
        return 0.0
    return min(max(v, 0.0), 1.0)


# ------------------------------------------------------------- saturation
def compute_saturation(config: Config,
                       store=None, read_view=None, rate_limits=None
                       ) -> Dict[str, float]:
    """The derived saturation layer: one normalized 0-1 value per
    resource, from counters the repo already maintains.  Every key is
    ALWAYS present (an absent input reads 0.0) so the exported series
    set is stable and the admission consumer never key-errors.

    Formulas (red lines in FleetConfig; docs/OBSERVABILITY.md):

    - ``group_commit_queue`` — max over write-plane shards of
      ``pending / serving.group_commit_max_batch``: 1.0 means a full
      batch is queued behind a committer mid-fsync.
    - ``follower_staleness`` — the local read view's apply age over
      ``fleet.staleness_red_line_seconds`` (0.0 on processes without a
      read view; the fleet view shows each follower's own value).
    - ``cycle_p99`` — p99 of the flight recorder's recent fused/match
      cycle durations over the cycle-duration SLO objective.
    - ``audit_queue`` — durable audit events still buffered for the
      journal over ``fleet.audit_queue_red_line``.
    - ``launch_tokens`` — worst-key consumption fraction of the
      job-launch token bucket (1.0 = some key fully spent or in debt).
    - ``journal_head`` — max shard journal bytes since the last
      checkpoint compaction over
      ``fleet.journal_head_red_line_bytes``.
    """
    fleet = config.fleet
    out = {"group_commit_queue": 0.0, "follower_staleness": 0.0,
           "cycle_p99": 0.0, "audit_queue": 0.0, "launch_tokens": 0.0,
           "journal_head": 0.0}
    if store is not None:
        from ..state.partition import substores
        gc_max = max(int(config.serving.group_commit_max_batch), 1)
        for shard in substores(store):
            gc_stats = getattr(shard, "group_commit_stats", None)
            gc = gc_stats() if gc_stats is not None else None
            if gc is not None:
                out["group_commit_queue"] = max(
                    out["group_commit_queue"],
                    _clamp01(float(gc.get("pending", 0)) / gc_max))
            co = getattr(shard, "commit_offset", None)
            head = co() if co is not None else 0
            if head:
                out["journal_head"] = max(
                    out["journal_head"],
                    _clamp01(float(head)
                             / fleet.journal_head_red_line_bytes))
        audit = getattr(store, "audit", None)
        if audit is not None:
            pending = getattr(audit, "pending_durable_count", None)
            if pending is not None:
                out["audit_queue"] = _clamp01(
                    float(pending()) / fleet.audit_queue_red_line)
    if read_view is not None:
        out["follower_staleness"] = _clamp01(
            (read_view.age_ms() / 1000.0)
            / fleet.staleness_red_line_seconds)
    from ..utils.flight import recorder
    durations = recorder.recent_durations(("fused", "match"),
                                          config.slo.cycle_window)
    if durations:
        ordered = sorted(durations)
        p99_ms = ordered[min(int(0.99 * (len(ordered) - 1)),
                             len(ordered) - 1)]
        out["cycle_p99"] = _clamp01(
            p99_ms / (config.slo.cycle_duration_objective_s * 1000.0))
    if rate_limits is not None:
        limiter = getattr(rate_limits, "job_launch", None)
        saturation = getattr(limiter, "saturation", None)
        if saturation is not None:
            out["launch_tokens"] = _clamp01(saturation())
    return out


def publish_saturation(values: Dict[str, float],
                       registry: Optional[MetricsRegistry] = None) -> None:
    """``cook_saturation{resource=}`` gauges from a computed dict — the
    one exporter every caller (monitor sweep, follower scrape path)
    shares so the series set stays identical across roles."""
    reg = registry if registry is not None else default_registry
    for resource, value in values.items():
        reg.gauge_set("cook_saturation", round(_clamp01(value), 6),
                      labels={"resource": resource})


# ----------------------------------------------------------- trace stitch
def collect_trace(trace_id: str, members: Dict[str, Dict],
                  fetch: Optional[Callable[[str, float], str]] = None,
                  timeout_s: float = 2.0,
                  local_spans: Optional[List[Dict]] = None
                  ) -> Tuple[List[Dict], List[Dict]]:
    """Fan out ``GET /debug/trace/spans?trace_id=`` to every member,
    merge with the local ring, dedupe by ``(proc, span_id)`` — the lazy
    Dapper collection step.  Returns ``(span_docs, provenance)`` where
    provenance records per-member success/failure so a partial stitch
    is visible in the export's ``otherData`` rather than silent."""
    fetch = fetch or _default_fetch
    spans: List[Dict] = list(local_spans
                             if local_spans is not None
                             else tracing.tracer.traces(trace_id))
    provenance: List[Dict] = []
    for instance, info in sorted(members.items()):
        url = (info or {}).get("url")
        if not url:
            continue
        entry: Dict[str, Any] = {"instance": instance, "url": url}
        try:
            body = fetch(f"{url}/debug/trace/spans?trace_id={trace_id}",
                         timeout_s)
            remote = json.loads(body).get("spans") or []
            spans.extend(d for d in remote if isinstance(d, dict))
            entry.update(ok=True, spans=len(remote))
        except Exception as e:
            entry.update(ok=False, error=f"{type(e).__name__}: {e}")
        provenance.append(entry)
    seen = set()
    out: List[Dict] = []
    for d in spans:
        key = (d.get("proc"), d.get("span_id"))
        if key in seen:
            continue
        seen.add(key)
        out.append(d)
    return out, provenance


# ------------------------------------------------------------- federation
class FleetScraper:
    """Monitor-driven pull federation over the candidate registry.

    ``members_fn`` returns the current topology (state/replication.
    known_members); ``fetch`` is injectable for tests.  One sweep
    fetches every member's ``/metrics`` LOCK-FREE, then installs the
    parsed per-member records under the lock; readers
    (:meth:`merged_exposition`, :meth:`fleet_doc`) only ever see a
    complete sweep."""

    def __init__(self, cfg: FleetConfig,
                 members_fn: Callable[[], Dict[str, Dict]],
                 fetch: Optional[Callable[[str, float], str]] = None,
                 registry: Optional[MetricsRegistry] = None):
        self.cfg = cfg
        self.members_fn = members_fn
        self.fetch = fetch or _default_fetch
        self.registry = registry if registry is not None \
            else default_registry
        self._lock = threading.Lock()
        self._members: Dict[str, Dict] = {}
        self._last_sweep = 0.0
        # instance cardinality is bounded by the membership cap; the
        # guard is the backstop against a churning registry minting
        # unbounded instance label values across sweeps
        cap = int(cfg.max_members) * 2 + 16
        for name in ("cook_fleet_member_up",
                     "cook_fleet_scrape_age_seconds",
                     "cook_fleet_dropped_series"):
            self.registry.set_label_cap(name, "instance", cap)

    # ------------------------------------------------------------ scraping
    def maybe_scrape(self, now: Optional[float] = None) -> bool:
        """Sweep-gated entry point the monitor calls every sweep; a
        sweep actually runs only once per ``scrape_interval_seconds``."""
        now = time.time() if now is None else now
        with self._lock:
            if not self.cfg.enabled \
                    or now - self._last_sweep < self.cfg.scrape_interval_seconds:
                return False
            self._last_sweep = now
        self.scrape(now=now)
        return True

    def scrape(self, now: Optional[float] = None) -> Dict[str, Dict]:
        """One federation sweep: fetch, parse, re-label, publish."""
        now = time.time() if now is None else now
        members = dict(self.members_fn() or {})
        skipped = max(0, len(members) - int(self.cfg.max_members))
        if skipped:
            members = dict(sorted(members.items())
                           [:int(self.cfg.max_members)])
        records: Dict[str, Dict] = {}
        for instance, info in sorted(members.items()):
            records[instance] = self._scrape_member(instance,
                                                    info or {}, now)
        with self._lock:
            self._members = records
            self._last_sweep = now
        self._publish(records, skipped, now)
        return records

    def _scrape_member(self, instance: str, info: Dict,
                       now: float) -> Dict:
        rec: Dict[str, Any] = {
            "instance": instance, "url": info.get("url"),
            "role": str(info.get("role") or "member"),
            "self": bool(info.get("self")),
            "up": False, "error": None, "scraped_ts": now,
            "series": [], "dropped": 0,
        }
        url = rec["url"]
        if not url:
            rec["error"] = "no url published"
            return rec
        try:
            text = self.fetch(f"{url}/metrics",
                              self.cfg.scrape_timeout_seconds)
        except Exception as e:
            rec["error"] = f"{type(e).__name__}: {e}"
            return rec
        series = parse_exposition(text)
        cap = int(self.cfg.max_series_per_member)
        if len(series) > cap:
            rec["dropped"] = len(series) - cap
            series = series[:cap]
        rec["series"] = series
        rec["up"] = True
        # derived per-member health read off the scrape itself
        burn = [v for n, _l, v in series if n == "cook_slo_burn_rate"]
        rec["burn"] = max(burn) if burn else 0.0
        rec["saturation"] = {
            labels.get("resource", "?"): v
            for n, labels, v in series if n == "cook_saturation"}
        staleness = [v for n, _l, v in series
                     if n == "cook_follower_staleness_seconds"]
        rec["staleness_s"] = max(staleness) if staleness else None
        return rec

    def _publish(self, records: Dict[str, Dict], skipped: int,
                 now: float) -> None:
        """Per-member + fleet-level gauges into the process registry —
        what the local /metrics (and any UPSTREAM federation of this
        process) sees about the fleet."""
        reg = self.registry
        reg.gauge_set("cook_fleet_members", float(len(records)))
        if skipped:
            reg.counter_inc("cook_fleet_members_skipped", skipped)
        for instance, rec in records.items():
            labels = {"instance": instance, "role": rec["role"]}
            reg.gauge_set("cook_fleet_member_up",
                          1.0 if rec["up"] else 0.0, labels=labels)
            reg.gauge_set("cook_fleet_scrape_age_seconds",
                          round(max(0.0, now - rec["scraped_ts"]), 6),
                          labels={"instance": instance})
            if rec["dropped"]:
                reg.gauge_set("cook_fleet_dropped_series",
                              float(rec["dropped"]),
                              labels={"instance": instance})
        # fleet-level burn: per merged series key, the MAX over members
        # — the worst burning process pages, an average would dilute it
        reg.gauge_clear("cook_fleet_slo_burn_rate")
        for labels_key, value in self._fleet_burn(records).items():
            reg.gauge_set("cook_fleet_slo_burn_rate", value,
                          labels=dict(labels_key))

    @staticmethod
    def _fleet_burn(records: Dict[str, Dict]
                    ) -> Dict[Tuple, float]:
        out: Dict[Tuple, float] = {}
        for rec in records.values():
            for name, labels, value in rec.get("series", []):
                if name != "cook_slo_burn_rate":
                    continue
                key = tuple(sorted(labels.items()))
                out[key] = max(out.get(key, 0.0), value)
        return out

    # -------------------------------------------------------------- readers
    def members(self) -> Dict[str, Dict]:
        with self._lock:
            return dict(self._members)

    def last_sweep(self) -> float:
        with self._lock:
            return self._last_sweep

    def merged_exposition(self, now: Optional[float] = None) -> str:
        """The federated text view (``GET /metrics/fleet``): every
        member's series re-labeled with ``{instance, role}``.  A series
        that already carries an ``instance``/``role`` label (a member
        federating someone else, a pushgateway-style exporter) keeps it
        renamed ``exported_instance``/``exported_role`` — the member
        identity must win the collision, not silently lose it.
        Unreachable members contribute their up/age/error series, so
        the merged view never has gaps, only zeros."""
        now = time.time() if now is None else now
        lines: List[str] = []
        for instance, rec in sorted(self.members().items()):
            ident = {"instance": instance, "role": rec["role"]}
            lines.append(format_sample(
                "cook_fleet_member_up", ident,
                1.0 if rec["up"] else 0.0))
            lines.append(format_sample(
                "cook_fleet_scrape_age_seconds", {"instance": instance},
                round(max(0.0, now - rec["scraped_ts"]), 6)))
            if rec["dropped"]:
                lines.append(format_sample(
                    "cook_fleet_dropped_series", {"instance": instance},
                    float(rec["dropped"])))
            for name, labels, value in rec.get("series", []):
                if name in _FLEET_SELF:
                    continue
                merged = dict(labels)
                for k in ("instance", "role"):
                    if k in merged:
                        merged[f"exported_{k}"] = merged.pop(k)
                merged.update(ident)
                lines.append(format_sample(name, merged, value))
        return "\n".join(lines) + "\n" if lines else ""

    def fleet_doc(self, now: Optional[float] = None) -> Dict[str, Any]:
        """The ``GET /debug/fleet`` / ``cs debug fleet`` panel: per-
        member health (up, staleness, burn, saturation hot-spots,
        last-scrape age, error) + fleet-level burn, JSON-shaped for
        humans and the adaptive-admission consumer alike."""
        now = time.time() if now is None else now
        red = self.cfg.saturation_red_line
        members = []
        for instance, rec in sorted(self.members().items()):
            saturation = rec.get("saturation") or {}
            members.append({
                "instance": instance,
                "url": rec.get("url"),
                "role": rec.get("role"),
                "self": rec.get("self", False),
                "up": rec.get("up", False),
                "error": rec.get("error"),
                "scrape_age_s": round(
                    max(0.0, now - rec.get("scraped_ts", now)), 3),
                "series": len(rec.get("series", [])),
                "dropped_series": rec.get("dropped", 0),
                "staleness_s": rec.get("staleness_s"),
                "burn": rec.get("burn", 0.0),
                "saturation": saturation,
                "hot": sorted(r for r, v in saturation.items()
                              if v >= red),
            })
        with self._lock:
            last = self._last_sweep
        return {
            "enabled": bool(self.cfg.enabled),
            "last_sweep_ts": last,
            "sweep_age_s": round(max(0.0, now - last), 3) if last else None,
            "scrape_interval_seconds": self.cfg.scrape_interval_seconds,
            "saturation_red_line": red,
            "members": members,
            "fleet_burn": [
                {**dict(k), "burn": v}
                for k, v in sorted(self._fleet_burn(
                    self.members()).items())],
        }

    # --------------------------------------------------------- trace fanout
    def collect_trace(self, trace_id: str
                      ) -> Tuple[List[Dict], List[Dict]]:
        """Stitch one trace across the CURRENT topology (not the last
        scrape's): span rings are short-lived, so the fan-out must see
        members the federation sweep hasn't visited yet."""
        members = dict(self.members_fn() or {})
        # never fetch our own spans over HTTP: the local ring is richer
        # (it includes spans finishing mid-request) and the self-fetch
        # would deadlock a single-threaded test server
        members = {i: m for i, m in members.items()
                   if not (m or {}).get("self")}
        return collect_trace(
            trace_id, members, fetch=self.fetch,
            timeout_s=self.cfg.trace_fanout_timeout_seconds)
