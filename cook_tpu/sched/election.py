"""Leader election and HA.

The reference elects a single active scheduler through ZooKeeper/Curator and
deliberately exits on leadership loss so a supervisor restarts the process
clean (reference: cook.mesos/start-leader-selector mesos.clj:153-328,
System/exit on loss :296-313).  Same shape here:

 - :class:`FileLeaderElector` — file-lock election for single-host /
   multi-process deployments (the interface admits a ZK/k8s-lease
   implementation later);
 - the winner's URL is published next to the lock so follower (api-only)
   nodes can 307-redirect leader-only requests (reference: api-only? nodes
   config.clj:692 + leader-redirect in rest/api.clj);
 - on leadership loss the ``on_loss`` callback fires — production wiring
   should exit the process, mirroring the reference.
"""

from __future__ import annotations

import os
import threading
import time
from pathlib import Path
from typing import Callable, Optional


class LeaderElector:
    """Interface: campaign, observe, resign."""

    def campaign(self) -> None:
        raise NotImplementedError

    def resign(self) -> None:
        raise NotImplementedError

    @property
    def is_leader(self) -> bool:
        raise NotImplementedError

    def leader_url(self) -> Optional[str]:
        raise NotImplementedError


class FileLeaderElector(LeaderElector):
    def __init__(self, lock_path: str, node_url: str,
                 on_leadership: Optional[Callable[[], None]] = None,
                 on_loss: Optional[Callable[[], None]] = None,
                 poll_interval_s: float = 0.2):
        self.lock_path = Path(lock_path)
        self.url_path = Path(str(lock_path) + ".leader")
        self.node_url = node_url
        self.on_leadership = on_leadership
        self.on_loss = on_loss
        self.poll_interval_s = poll_interval_s
        self._fd: Optional[int] = None
        self._leader = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------- campaign
    def campaign(self) -> None:
        self._stop.clear()
        self._thread = threading.Thread(target=self._campaign_loop,
                                        daemon=True)
        self._thread.start()

    def _try_acquire(self) -> bool:
        import fcntl
        fd = os.open(self.lock_path, os.O_CREAT | os.O_RDWR, 0o644)
        try:
            # flock, not lockf: flock is per open-file-description, so two
            # electors in one process (tests, embedded followers) conflict
            # correctly; lockf would silently grant both
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            os.close(fd)
            return False
        self._fd = fd
        self.url_path.write_text(self.node_url)
        return True

    def _campaign_loop(self) -> None:
        while not self._stop.is_set():
            if self._try_acquire():
                self._leader = True
                if self.on_leadership:
                    self.on_leadership()
                # hold leadership until resign/stop; the lock is released by
                # process death, which is what makes failover work
                while not self._stop.is_set():
                    time.sleep(self.poll_interval_s)
                return
            time.sleep(self.poll_interval_s)

    def resign(self) -> None:
        import fcntl
        self._stop.set()
        was_leader = self._leader
        self._leader = False
        if self._fd is not None:
            try:
                fcntl.flock(self._fd, fcntl.LOCK_UN)
                os.close(self._fd)
            except OSError:
                pass
            self._fd = None
            try:
                if self.url_path.read_text() == self.node_url:
                    self.url_path.unlink()
            except OSError:
                pass
        if was_leader and self.on_loss:
            self.on_loss()
        if self._thread is not None:
            self._thread.join(timeout=2)

    @property
    def is_leader(self) -> bool:
        return self._leader

    def leader_url(self) -> Optional[str]:
        try:
            return self.url_path.read_text().strip() or None
        except OSError:
            return None
