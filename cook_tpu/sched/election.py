"""Leader election and HA.

The reference elects a single active scheduler through ZooKeeper/Curator and
deliberately exits on leadership loss so a supervisor restarts the process
clean (reference: cook.mesos/start-leader-selector mesos.clj:153-328,
System/exit on loss :296-313).  Same shape here:

 - :class:`FileLeaderElector` — file-lock election for single-host /
   multi-process deployments (the interface admits a ZK/k8s-lease
   implementation later);
 - the winner's URL is published next to the lock so follower (api-only)
   nodes can 307-redirect leader-only requests (reference: api-only? nodes
   config.clj:692 + leader-redirect in rest/api.clj);
 - on leadership loss the ``on_loss`` callback fires — production wiring
   should exit the process, mirroring the reference.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from pathlib import Path
from typing import Callable, Dict, Optional


def _safe_node_id(node_id: str) -> str:
    """Node ids become filenames / annotation keys: keep them tame."""
    return re.sub(r"[^A-Za-z0-9._-]+", "-", node_id).strip("-") or "node"


class LeaderElector:
    """Interface: campaign, observe, resign — plus the candidate-position
    plane coordinated promotion publishes through (each standby's
    replication position ``(epoch, offset, synced)`` rides the election
    medium so the winner can rank candidates and pull a missing delta
    before opening its store; state/replication.py choose_successor)."""

    #: monotonic election epoch minted at acquisition when the elector
    #: supports it (None otherwise; the store falls back to "auto")
    epoch = None

    def campaign(self) -> None:
        raise NotImplementedError

    def resign(self) -> None:
        raise NotImplementedError

    @property
    def is_leader(self) -> bool:
        raise NotImplementedError

    def leader_url(self) -> Optional[str]:
        raise NotImplementedError

    # ---------------------------------------------- candidate positions
    def publish_candidate(self, node_id: str, position: Dict) -> None:
        """Publish this node's replication position into the election
        medium (no-op for electors without a coordination surface)."""

    def read_candidates(self) -> Dict[str, Dict]:
        """All published candidate positions, keyed by node id."""
        return {}

    def clear_candidate(self, node_id: str) -> None:
        """Withdraw a candidacy (a promoted winner's stale position must
        not confuse the next election)."""


class LeaseLeaderElector(LeaderElector):
    """Distributed election over a renewable TTL lease — the k8s-native
    leader-election recipe (coordination.k8s.io/v1 Lease: holderIdentity,
    renewTime, leaseDurationSeconds; the ZooKeeper/Curator slot of the
    reference, mesos.clj:153-328, re-based on the cluster backend's own
    coordination object so no extra infrastructure is required).

    ``api`` is any object with the lease surface of the kubernetes API
    adapters (cluster/k8s/fake_api.py ``try_acquire_lease``/``get_lease``;
    cluster/k8s/real_api.py implements the same against a live apiserver).
    The lease's ``transitions`` counter is the fencing epoch: it bumps
    every time holdership changes, so a deposed leader's stale writes can
    be rejected exactly like the file elector's epoch fencing.

    On losing the lease (renewal discovers another holder) ``on_loss``
    fires — production wiring exits the process for a supervisor restart,
    mirroring the reference's System/exit on leadership loss."""

    def __init__(self, api, identity: str, node_url: str,
                 lease_name: str = "cook-scheduler-leader",
                 duration_s: float = 15.0,
                 renew_interval_s: float = 2.0,
                 on_leadership: Optional[Callable[[], None]] = None,
                 on_loss: Optional[Callable[[], None]] = None,
                 clock: Callable[[], float] = time.time):
        # NOTE clock must share the lease's renew_time_s timebase: a real
        # apiserver stamps wall-clock epoch seconds, hence time.time (NOT
        # monotonic) — staleness checks compare the two directly.
        self.api = api
        self.identity = identity
        self.node_url = node_url
        self.lease_name = lease_name
        self.duration_s = duration_s
        self.renew_interval_s = renew_interval_s
        self.on_leadership = on_leadership
        self.on_loss = on_loss
        self.clock = clock
        self.epoch: Optional[int] = None  # fencing: lease transitions
        self._leader = False
        self._last_renew_ok: Optional[float] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def campaign(self) -> None:
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="lease-elector")
        self._thread.start()

    def try_once(self) -> bool:
        """One acquire/renew attempt (exposed for deterministic tests and
        for external pacing)."""
        from ..utils.faults import injector as _faults
        _faults.fire("leader.lease",
                     lambda: ConnectionError("injected lease fault"))
        lease = self.api.try_acquire_lease(
            self.lease_name, self.identity, self.clock(),
            duration_s=self.duration_s, holder_url=self.node_url)
        if lease is not None:
            first = not self._leader
            self._leader = True
            self._last_renew_ok = self.clock()
            self.epoch = lease.transitions
            if first and self.on_leadership:
                self.on_leadership()
            return True
        if self._leader:
            # held it, lost it: a competitor acquired after our TTL lapsed
            self._drop_leadership()
        return False

    def _drop_leadership(self) -> None:
        self._leader = False
        if self.on_loss:
            self.on_loss()

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.try_once()
            except Exception:
                # a transient apiserver error must NOT kill the renewal
                # thread while this node believes it leads — that is how
                # split brain happens: we'd stop renewing, keep scheduling,
                # and a standby would acquire after the TTL.  Keep retrying;
                # if renewals keep failing past our own TTL, assume the
                # lease is lost and step down pre-emptively.
                import logging
                logging.getLogger(__name__).warning(
                    "lease renewal attempt failed", exc_info=True)
                if self._leader and self._last_renew_ok is not None and \
                        self.clock() - self._last_renew_ok > self.duration_s:
                    self._drop_leadership()
            self._stop.wait(self.renew_interval_s)

    def resign(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None
        if self._leader:
            self._leader = False
            try:
                self.api.release_lease(self.lease_name, self.identity)
            except Exception:
                pass  # standby will still take over after the TTL
            if self.on_loss:
                self.on_loss()

    @property
    def is_leader(self) -> bool:
        return self._leader

    def leader_url(self) -> Optional[str]:
        lease = self.api.get_lease(self.lease_name)
        if lease is None or not lease.holder:
            return None
        if self.clock() - lease.renew_time_s > lease.duration_s:
            return None  # stale hold: no live leader to redirect to
        return lease.holder_url or None

    # ---------------------------------------------- candidate positions
    # Candidate positions ride the Lease object's annotations (the same
    # coordination object that carries the election — no extra
    # infrastructure), one ``cook.io/candidate-<id>`` key per standby.
    _CAND_PREFIX = "cook.io/candidate-"

    def publish_candidate(self, node_id: str, position: Dict) -> None:
        annotate = getattr(self.api, "annotate_lease", None)
        if annotate is None:
            return  # adapter without annotation support: no-op
        annotate(self.lease_name,
                 {self._CAND_PREFIX + _safe_node_id(node_id):
                  json.dumps(position)})

    def read_candidates(self) -> Dict[str, Dict]:
        lease = self.api.get_lease(self.lease_name)
        annotations = getattr(lease, "annotations", None) or {}
        out: Dict[str, Dict] = {}
        for key, value in annotations.items():
            if not key.startswith(self._CAND_PREFIX):
                continue
            try:
                out[key[len(self._CAND_PREFIX):]] = json.loads(value)
            except (TypeError, ValueError):
                continue  # a torn/foreign annotation must not kill ranking
        return out

    def clear_candidate(self, node_id: str) -> None:
        annotate = getattr(self.api, "annotate_lease", None)
        if annotate is not None:
            annotate(self.lease_name,
                     {self._CAND_PREFIX + _safe_node_id(node_id): None})


def partition_lock_path(election_dir: str, partition: int) -> str:
    """The per-partition lease lock in a partitioned write plane
    (state/partition.py): PR 3's single ``cook-leader.lock`` election
    generalizes to N leases over P partitions — partition p's leader is
    whoever holds ``cook-leader-p<p>.lock`` in the shared election dir,
    with the same minted-epoch fencing, published-URL, and
    candidate-position machinery per lease."""
    return str(Path(election_dir) / f"cook-leader-p{int(partition)}.lock")


def acquire_shard_lease(election_dir: str, partition: int, node_url: str,
                        timeout_s: float = 10.0) -> "FileLeaderElector":
    """Synchronous acquire-or-die for a controller shard boot (ISSUE
    19: one partition = one process).  A shard worker cannot serve a
    single cycle without its partition's lease — unlike the daemon's
    background campaign there is nothing useful to do while waiting —
    so this blocks until the flock is held and the fencing epoch is
    minted, or raises.  The returned elector holds the lease; process
    death releases it, which is exactly what the failover path (PR 3
    candidate ranking over the same lock's sidecar files) keys on."""
    elector = FileLeaderElector(
        partition_lock_path(election_dir, partition), node_url)
    deadline = time.monotonic() + max(float(timeout_s), 0.0)
    while True:
        if elector._try_acquire():
            elector._leader = True
            return elector
        if time.monotonic() >= deadline:
            raise TimeoutError(
                f"shard worker could not acquire the partition {partition} "
                f"lease within {timeout_s}s ({elector.lock_path} is held)")
        time.sleep(0.05)


class PartitionLeaseSet:
    """N independent leader leases over P partitions: one
    :class:`FileLeaderElector` per partition lock, campaigned and
    resigned individually.  A node may lead any SUBSET of partitions —
    losing one partition's lease fires only that partition's
    ``on_loss`` while the siblings keep serving (the chaos scenario's
    "sibling partitions never stall" invariant is exactly this
    isolation)."""

    def __init__(self, election_dir: str, count: int, node_url: str,
                 on_leadership=None, on_loss=None):
        self.electors: Dict[int, FileLeaderElector] = {}
        for p in range(int(count)):
            self.electors[p] = FileLeaderElector(
                partition_lock_path(election_dir, p), node_url,
                on_leadership=(lambda pp=p: on_leadership(pp))
                if on_leadership else None,
                on_loss=(lambda pp=p: on_loss(pp)) if on_loss else None)

    def campaign(self, partition: Optional[int] = None) -> None:
        for p, elector in self.electors.items():
            if partition is None or p == partition:
                elector.campaign()

    def resign(self, partition: Optional[int] = None) -> None:
        for p, elector in self.electors.items():
            if partition is None or p == partition:
                elector.resign()

    def led_partitions(self) -> list:
        return sorted(p for p, e in self.electors.items() if e.is_leader)

    def leader_url(self, partition: int) -> Optional[str]:
        return self.electors[int(partition)].leader_url()

    def epoch(self, partition: int) -> Optional[int]:
        return self.electors[int(partition)].epoch


class FileLeaderElector(LeaderElector):
    def __init__(self, lock_path: str, node_url: str,
                 on_leadership: Optional[Callable[[], None]] = None,
                 on_loss: Optional[Callable[[], None]] = None,
                 poll_interval_s: float = 0.2):
        self.lock_path = Path(lock_path)
        self.url_path = Path(str(lock_path) + ".leader")
        self.epoch_path = Path(str(lock_path) + ".epoch")
        self.node_url = node_url
        self.on_leadership = on_leadership
        self.on_loss = on_loss
        self.poll_interval_s = poll_interval_s
        self._fd: Optional[int] = None
        self._leader = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # monotonic ELECTION EPOCH, minted under the exclusive lock on
        # every acquisition: the fencing authority for journal records in
        # the separate-directory (socket replication) topology, where a
        # node-local epoch file cannot order two hosts' claims.
        self.epoch: Optional[int] = None

    # ------------------------------------------------------------- campaign
    def campaign(self) -> None:
        self._stop.clear()
        self._thread = threading.Thread(target=self._campaign_loop,
                                        daemon=True)
        self._thread.start()

    def _try_acquire(self) -> bool:
        import fcntl

        from ..utils.faults import injector as _faults
        if _faults.should_fire("leader.lease"):
            return False  # injected election fault: this attempt loses
        # first boot on a fresh host: the shared election dir may not
        # exist yet; a missing dir must not kill the campaign loop
        os.makedirs(os.path.dirname(self.lock_path) or ".", exist_ok=True)
        fd = os.open(self.lock_path, os.O_CREAT | os.O_RDWR, 0o644)
        try:
            # flock, not lockf: flock is per open-file-description, so two
            # electors in one process (tests, embedded followers) conflict
            # correctly; lockf would silently grant both
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            os.close(fd)
            return False
        self._fd = fd
        # durable counter (fsync before rename): a power loss must not
        # regress it, or two leaderships would mint the SAME fencing
        # epoch and stale-record skipping could no longer order them
        from ..utils.fsatomic import read_int_file, write_atomic_int
        self.epoch = (read_int_file(str(self.epoch_path), 0) or 0) + 1
        write_atomic_int(str(self.epoch_path), self.epoch)
        self.url_path.write_text(self.node_url)
        return True

    def _campaign_loop(self) -> None:
        while not self._stop.is_set():
            if self._try_acquire():
                self._leader = True
                if self.on_leadership:
                    self.on_leadership()
                # hold leadership until resign/stop; the lock is released by
                # process death, which is what makes failover work
                while not self._stop.is_set():
                    time.sleep(self.poll_interval_s)
                return
            time.sleep(self.poll_interval_s)

    def resign(self) -> None:
        import fcntl
        self._stop.set()
        was_leader = self._leader
        self._leader = False
        if self._fd is not None:
            try:
                fcntl.flock(self._fd, fcntl.LOCK_UN)
                os.close(self._fd)
            except OSError:
                pass
            self._fd = None
            try:
                if self.url_path.read_text() == self.node_url:
                    self.url_path.unlink()
            except OSError:
                pass
        if was_leader and self.on_loss:
            self.on_loss()
        if self._thread is not None:
            self._thread.join(timeout=2)

    @property
    def is_leader(self) -> bool:
        return self._leader

    def leader_url(self) -> Optional[str]:
        try:
            return self.url_path.read_text().strip() or None
        except OSError:
            return None

    # ---------------------------------------------- candidate positions
    # Candidate positions live as sidecar files next to the lock
    # (``<lock>.cand.<node-id>``), written atomically — the same shared
    # medium that carries the lock, the minted epoch, and the published
    # replication address (docs/DEPLOY.md: the election authority).
    def _cand_path(self, node_id: str) -> Path:
        return Path(f"{self.lock_path}.cand.{_safe_node_id(node_id)}")

    def publish_candidate(self, node_id: str, position: Dict) -> None:
        from ..utils.fsatomic import write_atomic_text
        os.makedirs(os.path.dirname(self.lock_path) or ".", exist_ok=True)
        write_atomic_text(str(self._cand_path(node_id)),
                          json.dumps(position))

    def read_candidates(self) -> Dict[str, Dict]:
        out: Dict[str, Dict] = {}
        prefix = self.lock_path.name + ".cand."
        try:
            entries = list(self.lock_path.parent.iterdir())
        except OSError:
            return out
        for p in entries:
            if not p.name.startswith(prefix) or ".tmp." in p.name:
                # crash-orphaned atomic-write temps (now dot-prefixed,
                # but older layouts left `<cand>.tmp*` behind) must
                # never be parsed as a live candidate
                continue
            try:
                out[p.name[len(prefix):]] = json.loads(p.read_text())
            except (OSError, ValueError):
                continue  # a mid-write or corrupt sidecar never wins
        return out

    def clear_candidate(self, node_id: str) -> None:
        try:
            self._cand_path(node_id).unlink()
        except OSError:
            pass
