"""Scheduler driver: wires store, clusters, ranker, matcher, rebalancer.

The equivalent of the reference's leader process (reference:
create-datomic-scheduler scheduler.clj:2473-2522 + the cycle triggers
mesos.clj:89-110).  Cycles are explicit ``step_*`` methods so tests and the
faster-than-real-time simulator drive them deterministically; ``run()``
drives them on wall-clock threads like the reference's chime channels.

Responsibilities wired here:
 - status updates: cluster backends -> store state machines
 - tx-feed side effects: job completed -> kill its live instances
   (reference: monitor-tx-report-queue scheduler.clj:378-448)
 - per-pool rank queue (reference: pool-name->pending-jobs-atom)
 - direct-mode pools: backpressure submission without matching
   (reference: handle-kubernetes-scheduler-pool scheduler.clj:1747)
 - reapers: lingering-task killer (max-runtime, scheduler.clj:1888-1953)
   and straggler handler (scheduler.clj:1955-1986, group.clj)
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..cluster.base import ComputeCluster, LaunchSpec
from ..config import Config
from ..state.schema import (
    DruMode,
    InstanceStatus,
    Job,
    JobState,
    Pool,
    Reasons,
    SchedulerKind,
    new_uuid,
    now_ms,
)
from ..state.store import AbortTransaction, Store
from ..utils import tracing
from ..utils.flight import recorder as flight_recorder
from .matcher import MatchCycleResult, Matcher, _BackoffState
from .ranker import Ranker
from .rebalancer import Rebalancer


class Scheduler:
    def __init__(self, store: Store, config: Optional[Config] = None,
                 clusters: Optional[List[ComputeCluster]] = None,
                 rank_backend: str = "tpu", plugins=None, rate_limits=None,
                 status_queue_shards: Optional[int] = None,
                 shard_id: Optional[int] = None):
        from ..policy import PluginRegistry, RateLimits
        self.store = store
        self.config = config or Config()
        # sharded-controller identity (ISSUE 19: one partition = one
        # process = one mesh shard).  Process-wide, not per-scheduler:
        # a shard worker runs exactly one scheduler, and everything the
        # shard emits — CycleRecords, spans, the Perfetto process track
        # — must carry the same id whether or not it passed through
        # this object.
        self.shard_id = shard_id
        if shard_id is not None:
            from ..utils import flight
            flight.set_shard(shard_id)
        # fault-injection + breaker policy are config planes the scheduler
        # owns applying (docs/ROBUSTNESS.md): arming is explicit opt-in
        from ..utils.faults import injector as _faults
        from ..utils.retry import breakers as _breakers
        if self.config.faults.enabled:
            _faults.configure({"seed": self.config.faults.seed,
                               "points": self.config.faults.points})
        _breakers.configure(
            failure_threshold=self.config.circuit_breaker.failure_threshold,
            reset_timeout_s=self.config.circuit_breaker.reset_timeout_s)
        self.breakers = _breakers
        # per-job audit trail knobs (utils/audit.py): the trail lives on
        # the store (it must survive into a successor's replay), the
        # scheduler owns applying the config like faults/breakers
        store.audit.configure(self.config.audit)
        self.plugins = plugins or PluginRegistry()
        self.rate_limits = rate_limits or RateLimits()
        self.clusters: Dict[str, ComputeCluster] = {}
        self.ranker = Ranker(store, self.config, backend=rank_backend)
        self.matcher = Matcher(store, self.config, plugins=self.plugins,
                               rate_limits=self.rate_limits)
        self.rebalancer = Rebalancer(store, self.config, backend=rank_backend)
        # elastic resize plane (sched/elastic.py, docs/GANG.md
        # elasticity): grace-shrink ledger + the grow/shrink budgets the
        # optimizer loop sets; shared with the matcher (grow metering)
        # and the rebalancer (shrink-instead-of-kill)
        from .elastic import ElasticManager
        self.elastic = ElasticManager(store, self.config.elastic)
        if self.config.elastic.enabled:
            self.matcher.elastic = self.elastic
            self.rebalancer.elastic = self.elastic
        # real optimizer loop (sched/optimizer.py GoodputOptimizer):
        # built lazily by run()/step_optimize when config enables it
        self.optimizer_cycler = None
        from .monitor import Monitor
        self.monitor = Monitor(store, config=self.config)
        # launch-token saturation input (sched/fleet.py): the sweep
        # reads the same buckets the matcher admits against
        self.monitor.rate_limits = self.rate_limits
        # adaptive admission + brownout ladder (sched/admission.py):
        # leader-only — the controller recovers any journaled brownout
        # stage at construction, and each monitor sweep feeds it the
        # saturation gauges.  None when the section is disabled.
        self.admission = None
        if self.config.admission.enabled:
            from .admission import AdmissionController
            self.admission = AdmissionController(
                store, self.config, rate_limits=self.rate_limits)
            self.monitor.admission = self.admission
            # head-of-queue scaleback: the matcher shrinks its
            # considerable window by the admission level under pressure
            self.matcher.admission = self.admission
        from .heartbeat import HeartbeatTracker
        self.heartbeats = HeartbeatTracker(self.config.heartbeat_timeout_ms)
        # Heartbeat stamps and reaper sweeps follow the store's injectable
        # clock (one patch point: the simulator swaps store.clock for its
        # virtual clock and everything stays in one timebase).
        self.clock = lambda: self.store.clock()
        # pool -> ranked pending jobs, refreshed by the rank cycle
        self.pending_queues: Dict[str, List[Job]] = {}
        # pool -> last MatchCycleResult, feeds the unscheduled explainer
        self.last_match_results: Dict[str, MatchCycleResult] = {}
        # job uuid -> reserved hostname from the rebalancer
        self.reserved_hosts: Dict[str, str] = {}
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        # fused production cycle driver, created lazily on first step_cycle;
        # _pipeline wraps it when config.pipeline.depth > 0 (the pipelined
        # optimistic driver, sched/pipeline.py)
        self._fused = None
        self._pipeline = None
        # cold-start tail killer (config.PipelineConfig): persistent
        # compilation cache + boot-time warmup sweep, so first-call
        # compiles land here — inside the takeover window — and never
        # inside a live cycle.  Both are opt-in config; the cpu rank
        # backend has no fused path to warm.
        if rank_backend != "cpu":
            pl = self.config.pipeline
            if pl.compilation_cache_dir:
                from ..ops.telemetry import enable_compilation_cache
                enable_compilation_cache(pl.compilation_cache_dir)
            if pl.warmup_tasks and pl.warmup_hosts:
                self.warmup_kernels()
        # GC discipline for the production cycle: with 100k+ live entities
        # the interpreter's automatic gen2 collections (full scans of a
        # multi-million-object heap) land mid-cycle and double the p99.
        # step_cycle pauses automatic collection for its duration and
        # schedules a proactive collect + freeze OUTSIDE the cycle (in
        # flush_status_updates / the next idle point).  Entities are
        # acyclic, so ordinary refcounting frees them regardless; the
        # cycle-collector is only needed for rare cyclic garbage.
        self.gc_discipline = True
        self._gc_cycles = 0
        self._gc_collect_due = False
        # task_id -> first-seen-orphaned ms (reaper grace bookkeeping)
        self._orphan_first_seen: Dict[str, int] = {}
        # gang scheduling bookkeeping (docs/GANG.md): task -> gang group
        # uuid (populated from the launch event's gang tag, so non-gang
        # traffic pays nothing), and per-gang barrier state — a gang's
        # barrier "releases" when every member is RUNNING; the wait is
        # observed on cook_gang_barrier_wait_ms
        self._gang_of_task: Dict[str, str] = {}
        self._gang_barrier: Dict[str, Dict] = {}
        # groups whose gang policy is mid-reaction: our own kill_job
        # calls commit synchronously and re-enter _on_tx_events, which
        # must not count (or act) as a fresh policy reaction
        self._gang_policy_active: set = set()
        # backend class -> whether autoscale() takes a gangs= kwarg; the
        # backend set is fixed at construction, so probe each class once
        self._autoscale_takes_gangs: Dict[type, bool] = {}
        # Side-effect worker: cluster kills requested from a thread that
        # already holds that cluster's kill-lock read side (e.g. a tx-event
        # delivered during a launch) must run elsewhere or they self-deadlock.
        self._side_effects: "queue.Queue" = queue.Queue()
        self._side_effect_thread: Optional[threading.Thread] = None
        # Optional sharded in-order status processing (the reference's 19
        # hash-sharded agents, scheduler.clj:2370-2396; native C++ executor
        # when available). None = synchronous, for deterministic stepping.
        self._status_queue = None
        if status_queue_shards:
            from ..native import make_watch_queue
            self._status_queue = make_watch_queue(
                self._apply_status_payload, status_queue_shards)
        store.subscribe(self._on_tx_events)
        for cluster in clusters or []:
            self.add_cluster(cluster)
        if not store.pools():
            store.put_pool(Pool(name=self.config.default_pool))
        # Resume path: instances already live in a reopened store predate
        # this scheduler's tx subscription, so watch them now.
        running = store.running_instances()
        gangs = store.gang_groups_of(j for j, _i in running)
        for _job, inst in running:
            self.heartbeats.watch(inst.task_id, self.clock())
            # re-learn gang membership so barrier release + gang policy
            # keep working across a leader handoff
            if _job.group in gangs:
                self._gang_of_task[inst.task_id] = _job.group
                self._gang_barrier.setdefault(
                    _job.group, {"first_live_ms": self.clock(),
                                 "released": False})
        # Crash-consistency: sweep launch intents the previous leader left
        # open (died between match and launch-ack) against actual cluster
        # state — refund or adopt, never duplicate, never lose.
        self.reconcile_launch_intents()

    # ---------------------------------------------------------------- wiring
    def add_cluster(self, cluster: ComputeCluster) -> None:
        cluster.initialize(self._on_status_update)
        self.clusters[cluster.name] = cluster

    def launchable_clusters(self, pool_name: str) -> List[ComputeCluster]:
        """Clusters accepting ``pool_name`` whose circuit breaker allows
        launches.  A tripped breaker's cluster contributes no offers, so
        the matcher routes its demand at healthy clusters; the skip is
        noted on the cycle record (a degraded cycle explains itself)."""
        out: List[ComputeCluster] = []
        skipped = 0
        for cluster in list(self.clusters.values()):
            if not cluster.accepts_pool(pool_name):
                continue
            if not self.breakers.get(cluster.name).allow():
                skipped += 1
                continue
            out.append(cluster)
        if skipped:
            flight_recorder.note_fault("breaker-open", skipped)
        return out

    def reconcile_launch_intents(self) -> int:
        """Leader-startup sweep of open launch intents (store records for
        dispatches never confirmed).  For each intent:

        - instance missing or already past UNKNOWN -> the dispatch (or
          its failure) was observed; just drop the intent;
        - owning cluster positively knows the task -> adopt (drop intent,
          status updates flow normally);
        - owning cluster positively does NOT know the task, or is gone ->
          the crash window hit between match and launch-ack: fail the
          instance mea-culpa (CANCELLED_DURING_LAUNCH) so the job
          relaunches exactly once with no retry-budget charge;
        - cluster cannot enumerate its tasks -> leave the verdict to that
          backend's own reconciliation (remote NODE_LOSTs unknown tasks on
          reconnect) and drop the intent.

        Gang intents (tagged with their gang group uuid) are swept as
        ONE unit: any member refunded refunds every member still in the
        crash window — the sweep rolls back or adopts whole gangs, never
        leaving a partial gang live (docs/GANG.md).  Members already
        past the window are cleaned up by the gang policy reacting to
        the refunds' failure events.
        """
        swept = 0
        to_clear: List[str] = []
        # verdict pass: (task_id, refund?, gang uuid, cluster known task?)
        verdicts: List[Tuple[str, bool, str, Optional[bool]]] = []
        for intent in self.store.launch_intents():
            task_id = intent["task_id"]
            inst = self.store.instance(task_id)
            if inst is not None and inst.status is InstanceStatus.UNKNOWN:
                cluster = self.clusters.get(
                    intent.get("compute_cluster", ""))
                enumerate_tasks = getattr(cluster, "running_task_ids", None)
                known = None
                if enumerate_tasks is not None:
                    try:
                        ids = enumerate_tasks()
                        # None = the backend cannot POSITIVELY enumerate
                        # right now (e.g. an agent unreachable at
                        # startup): absence proves nothing, defer
                        known = (task_id in set(ids)
                                 if ids is not None else None)
                    except Exception:
                        known = None
                verdicts.append((task_id, known is False or cluster is None,
                                 intent.get("gang", ""), known))
            else:
                to_clear.append(task_id)
            swept += 1
        refund_gangs = {g for _t, refund, g, _k in verdicts if g and refund}
        for task_id, refund, gang, known in verdicts:
            if refund or gang in refund_gangs:
                # the refund's status update deletes the intent in
                # its own transaction; no separate clear needed
                self.store.update_instance_status(
                    task_id, InstanceStatus.FAILED,
                    reason_code=Reasons.CANCELLED_DURING_LAUNCH.code)
                if not refund:
                    # a gang-mate dragged down by a refunded sibling:
                    # the backend may know it (known True) or be unable
                    # to say (known None — an unreachable agent could
                    # still be running it); either way issue the
                    # idempotent backend kill so no zombie double-runs
                    # the work when the gang relaunches
                    inst = self.store.instance(task_id)
                    if inst is not None:
                        self._cluster_kill(inst.compute_cluster, task_id)
            else:
                to_clear.append(task_id)
        # ONE transaction for every adopt/drop (a crash can leave
        # hundreds of intents; per-intent journaled txns would serialize
        # the new leader's startup)
        self.store.clear_launch_intents(to_clear)
        if swept:
            from ..utils.metrics import registry
            registry.counter_inc("cook_launch_intents_swept", float(swept))
            flight_recorder.note_fault("launch-intents-swept", swept)
        return swept

    def _on_status_update(self, task_id: str, status: InstanceStatus,
                          reason_code: Optional[int], exit_code=None,
                          preempted: bool = False, hostname=None) -> None:
        payload = (status, reason_code, exit_code, preempted, hostname)
        if self._status_queue is not None:
            self._status_queue.submit(task_id, payload)
        else:
            self._apply_status_payload(task_id, payload)

    def _apply_status_payload(self, task_id: str, payload) -> None:
        status, reason_code, exit_code, preempted, hostname = payload
        if status is InstanceStatus.RUNNING:
            self.heartbeats.beat(task_id, self.clock())
        self.store.update_instance_status(
            task_id, status, reason_code=reason_code, exit_code=exit_code,
            preempted=preempted, hostname=hostname)

    def heartbeat(self, task_id: str) -> None:
        """Explicit liveness signal from an executor/sidecar (progress
        frames route here too, matching the reference where any framework
        message resets the heartbeat timer, heartbeat.clj:100-123)."""
        from ..utils.faults import injector as _faults
        if _faults.should_fire("agent.heartbeat"):
            return  # injected delivery loss: the frame never arrives
        self.heartbeats.beat(task_id, self.clock())

    def flush_status_updates(self) -> None:
        if self._status_queue is not None:
            self._status_queue.flush()
        self.maintain_gc()

    def maintain_gc(self) -> None:
        """Proactive full collection at an idle point (see gc_discipline in
        __init__): freeze afterwards so the stable entity heap is never
        re-scanned — acyclic entities free by refcount anyway.  Called by
        the production cycle loop after each step_cycle and by
        flush_status_updates (tests/bench pacing)."""
        if self._gc_collect_due:
            self._gc_collect_due = False
            import gc
            gc.collect()
            gc.freeze()

    def _on_tx_events(self, tx_id: int, events) -> None:
        """Kill live instances of jobs that reached completed — covers user
        kills and retroactive cleanup (scheduler.clj:405-447)."""
        for e in events:
            if e.kind == "job-state" and e.data.get("new") == "completed":
                job = self.store.job(e.data["uuid"])
                if job is None:
                    continue
                for tid in job.instances:
                    inst = self.store.instance(tid)
                    if inst is None or inst.status not in (
                            InstanceStatus.UNKNOWN, InstanceStatus.RUNNING):
                        continue
                    # ensure the store converges even with a dead backend
                    self.store.update_instance_status(
                        tid, InstanceStatus.FAILED,
                        reason_code=Reasons.KILLED_BY_USER.code)
                    self._cluster_kill(inst.compute_cluster, tid)
                # a gang member that went terminal WITHOUT ever succeeding
                # (user kill while WAITING, say) breaks its gang for good.
                # Instance-failure events cover members that had
                # instances, but a WAITING kill emits none — take the
                # rest of the gang down here or the siblings would sit
                # gang-deferred forever.  (Members that completed after a
                # SUCCESS are normal staggered finishes, not a break; a
                # redundant call is a no-op once every member is
                # terminal.)
                if self.store.group_is_gang(job.group) and not any(
                        (i := self.store.instance(t)) is not None
                        and i.status is InstanceStatus.SUCCESS
                        for t in job.instances):
                    self._apply_gang_policy(job, None)
            if e.kind == "job-state" and e.data.get("new") in (
                    "running", "completed"):
                # consume rebalancer reservations once the job launches —
                # or release them if the job dies while still waiting
                self.reserved_hosts.pop(e.data.get("uuid"), None)
            if e.kind == "instance-created":
                # start the heartbeat clock at launch (heartbeat.clj:92)
                self.heartbeats.watch(e.data["task_id"], self.clock())
                # gang bookkeeping rides the event's gang tag so the
                # non-gang launch path fetches nothing extra
                guuid = e.data.get("gang")
                if guuid:
                    self._gang_of_task[e.data["task_id"]] = guuid
                    st = self._gang_barrier.setdefault(
                        guuid, {"first_live_ms": self.clock(),
                                "released": False})
                    if st.get("released"):
                        # a member launched AFTER the barrier released:
                        # a satisfied ELASTIC gang grew into capacity
                        # (docs/GANG.md elasticity).  Gated on
                        # elasticity: a rigid gang relaunching after a
                        # whole-gang requeue also lands here (the
                        # barrier entry persists released), and that is
                        # a retry, not a resize.
                        from ..state.schema import gang_is_elastic
                        group = self.store.group(guuid)
                        if group is not None and gang_is_elastic(group):
                            job = self.store.job(e.data.get("job", ""))
                            self.elastic.note_grow(
                                job.pool if job is not None else "")
            if e.kind == "instance-status" and e.data.get("new") == "running":
                guuid = self._gang_of_task.get(e.data["task_id"])
                if guuid:
                    self._maybe_release_gang_barrier(guuid)
            if e.kind == "instance-status" and e.data.get("new") in (
                    "success", "failed"):
                self.heartbeats.forget(e.data["task_id"])
                self._gang_of_task.pop(e.data["task_id"], None)
                # InstanceCompletionHandler plugins (plugins/definitions.clj)
                inst = self.store.instance(e.data["task_id"])
                job = self.store.job(e.data["job"]) if inst else None
                if inst is not None and job is not None:
                    self.plugins.on_instance_completion(job, inst)
                if (e.data.get("new") == "failed" and job is not None
                        and self.store.group_is_gang(job.group)):
                    self._apply_gang_policy(job, e.data.get("reason"))
                if (job is not None and job.group is not None
                        and job.group in self._gang_barrier
                        and job.state is JobState.COMPLETED):
                    # retire the barrier entry once the whole gang is
                    # terminal — it would otherwise leak one dict entry
                    # per finished gang for the leader's lifetime
                    group = self.store.group(job.group)
                    if group is not None and all(
                            (m := self.store.job(u)) is None
                            or m.state is JobState.COMPLETED
                            for u in group.jobs):
                        self._gang_barrier.pop(job.group, None)

    # ------------------------------------------------------------------ gangs
    def _apply_gang_policy(self, failed_job: Job,
                           reason_code: Optional[int]) -> None:
        """A gang member's instance failed: run the configured gang
        policy (state/machines.gang_failure_action, docs/GANG.md).
        ``requeue`` (default) kills every sibling's live instances with
        the mea-culpa ``gang-member-lost`` reason so the WHOLE gang
        returns to WAITING and relaunches atomically; ``kill`` — or a
        member whose job went terminal — takes the whole gang down.
        An ELASTIC gang still holding >= gang_min live members absorbs
        the loss as an implicit shrink instead (docs/GANG.md
        elasticity); the live count is only fetched for elastic groups
        so rigid gangs pay nothing new."""
        from ..state import machines
        from ..state.schema import gang_is_elastic
        group = self.store.group(failed_job.group)
        live = self.store.gang_live_members(group.uuid) \
            if group is not None and gang_is_elastic(group) else None
        action = machines.gang_failure_action(group, reason_code,
                                              failed_job.state,
                                              live_members=live)
        if action == "none":
            if live is not None \
                    and reason_code not in (Reasons.GANG_RESIZED.code,
                                            Reasons.GANG_MEMBER_LOST.code):
                # an elastic gang absorbed a member failure as a shrink
                from ..utils.metrics import registry
                registry.counter_inc("cook_gang_resize", labels={
                    "direction": "shrink", "reason": "member-lost"})
            return
        if action == "requeue" and any(
                u != failed_job.uuid
                and (m := self.store.job(u)) is not None
                and m.state is JobState.COMPLETED
                for u in group.jobs):
            # a sibling that already finished (a short member exiting
            # SUCCESS mid-gang is a normal staggered finish) can never
            # run again, so the gang can never re-admit whole —
            # requeueing would strand the live members in WAITING
            # forever behind a members-missing deferral
            action = "kill"
        if group.uuid in self._gang_policy_active:
            return
        self._gang_policy_active.add(group.uuid)
        try:
            self._run_gang_policy(group, action, failed_job)
        finally:
            self._gang_policy_active.discard(group.uuid)

    def _run_gang_policy(self, group, action: str, failed_job: Job) -> None:
        # collect what there actually is to do FIRST: a whole-gang
        # failure (e.g. rebalancer preemption of the full closure)
        # delivers one failure event per member, and only the first
        # should count as a policy reaction — the rest find nothing
        # left to kill and must not inflate the metric or re-loop
        if action == "kill":
            targets = [u for u in group.jobs
                       if (m := self.store.job(u)) is not None
                       and m.state is not JobState.COMPLETED]
            if not targets:
                return
            self._gang_barrier.pop(group.uuid, None)
            from ..utils.metrics import registry
            registry.counter_inc("cook_gang_policy_kills",
                                 labels={"action": action})
            for member_uuid in targets:
                try:
                    self.store.kill_job(member_uuid)
                except Exception:  # pragma: no cover - converges next sweep
                    pass
            return
        live: List[Tuple[str, str]] = []  # (task_id, cluster)
        for member_uuid in group.jobs:
            if member_uuid == failed_job.uuid:
                continue
            member = self.store.job(member_uuid)
            if member is None:
                continue
            for tid in member.instances:
                mi = self.store.instance(tid)
                if mi is not None and mi.status in (
                        InstanceStatus.UNKNOWN, InstanceStatus.RUNNING):
                    live.append((tid, mi.compute_cluster))
        if not live:
            return
        self._gang_barrier.pop(group.uuid, None)  # barrier re-arms
        from ..utils.metrics import registry
        registry.counter_inc("cook_gang_policy_kills",
                             labels={"action": action})
        for tid, cluster_name in live:
            # authoritative store transition first (single-writer
            # discipline, like _kill_instance), then the backend kill
            self.store.update_instance_status(
                tid, InstanceStatus.FAILED,
                reason_code=Reasons.GANG_MEMBER_LOST.code)
            self._cluster_kill(cluster_name, tid)

    def _maybe_release_gang_barrier(self, guuid: str) -> None:
        """Release the gang's barrier once every REQUIRED member has
        STARTED — currently RUNNING, or already finished a run (a short
        member can exit SUCCESS before the last member comes up;
        requiring all members to be simultaneously RUNNING would then
        block release forever).  Rigid gangs require every member;
        ELASTIC gangs make the barrier at ``gang_min`` started members
        (docs/GANG.md elasticity — the gang is legally whole there).
        The wait (first launch -> barrier) is observed on
        ``cook_gang_barrier_wait_ms``."""
        st = self._gang_barrier.get(guuid)
        if st is None or st.get("released"):
            return
        group = self.store.group(guuid)
        if group is None:
            return
        from ..state.schema import gang_bounds
        need = gang_bounds(group)[0] or len(group.jobs)
        started_n = 0
        for member_uuid in group.jobs:
            member = self.store.job(member_uuid)
            if member is None:
                continue
            started = any(
                (mi := self.store.instance(tid)) is not None
                and (mi.status is InstanceStatus.RUNNING
                     or (member.state is JobState.COMPLETED
                         and (mi.status is InstanceStatus.SUCCESS
                              or mi.mesos_start_time_ms)))
                for tid in member.instances)
            if started:
                started_n += 1
                if started_n >= need:
                    break
        if started_n < need:
            return
        st["released"] = True
        st["released_ms"] = self.clock()
        from ..utils.metrics import registry
        registry.observe(
            "cook_gang_barrier_wait_ms",
            float(max(self.clock() - st["first_live_ms"], 0)),
            buckets=(1.0, 10.0, 100.0, 1000.0, 10_000.0, 60_000.0,
                     600_000.0))

    # ---------------------------------------------------------------- cycles
    def step_rank(self) -> Dict[str, List[Job]]:
        """Rank cycle across all schedulable pools (reference: rank-jobs +
        reset! pool-name->pending-jobs-atom, scheduler.clj:2286-2296)."""
        queues: Dict[str, List[Job]] = {}
        with flight_recorder.cycle(kind="rank"), tracing.span("rank.cycle"):
            for pool in self.store.pools():
                if pool.state != "active":
                    continue
                with tracing.span("rank.pool", pool=pool.name) as sp:
                    ranked = self.ranker.rank_pool(pool.name, pool.dru_mode)
                    sp.set_tag("jobs", len(ranked))
                queues[pool.name] = self._filter_offensive_jobs(ranked)
        self.pending_queues = queues
        return queues

    def _filter_offensive_jobs(self, ranked: List[Job]) -> List[Job]:
        """Drop jobs whose mem/cpus exceed the configured limits and abort
        them off-cycle, returning the inoffensive rest immediately
        (reference: filter-offensive-jobs + make-offensive-job-stifler,
        scheduler.clj:2205-2257)."""
        limits = self.config.offensive_job_limits
        if limits is None:
            return ranked
        max_mem_mb = limits.memory_gb * 1024.0
        from .ranker import RankedQueue
        if isinstance(ranked, RankedQueue):
            # columnar path: vectorized over the resource columns, no
            # full-queue entity materialization
            import numpy as np
            bad = ((ranked.resources[:, 1] > max_mem_mb)
                   | (ranked.resources[:, 0] > limits.cpus))
            if not bad.any():
                return ranked
            self._stifle_offensive(
                [j for j in (self.store.job(u)
                             for u in ranked.uuids[bad]) if j is not None])
            return ranked.filtered(~bad)
        offensive = [j for j in ranked
                     if j.resources.mem > max_mem_mb
                     or j.resources.cpus > limits.cpus]
        if not offensive:
            return ranked
        offensive_uuids = {j.uuid for j in offensive}
        self._stifle_offensive(offensive)
        return [j for j in ranked if j.uuid not in offensive_uuids]

    def _stifle_offensive(self, offensive: List[Job]) -> None:
        """Abort offensive jobs off-cycle (the stifler thread)."""
        if not offensive:
            return

        def stifle():
            for job in offensive:
                try:
                    self.store.kill_job(job.uuid)
                except Exception:
                    pass
        threading.Thread(target=stifle, daemon=True,
                         name="offensive-job-stifler").start()

    def _ensure_fused(self):
        """The fused driver (and, at pipeline_depth > 0, the pipelined
        optimistic wrapper around it), created lazily."""
        if self._fused is None:
            from .fused import FusedCycleDriver
            self._fused = FusedCycleDriver(
                self.store, self.config, self.matcher, self.plugins,
                self.rate_limits, shard_id=self.shard_id)
            if self.config.pipeline.depth > 0:
                from .pipeline import PipelinedCycleDriver
                self._pipeline = PipelinedCycleDriver(
                    self._fused, self.config.pipeline)
            # gauge emitted for BOTH drivers: a depth-0 deployment must
            # read 0 on /metrics, not be indistinguishable from a broken
            # scrape (docs/OBSERVABILITY.md documents "0 = sync")
            from ..utils.metrics import registry
            registry.gauge_set("cook_pipeline_depth",
                               float(self.config.pipeline.depth))
        return self._pipeline or self._fused

    def warmup_kernels(self) -> int:
        """Boot-time pre-compile of the fused cycle at the configured
        (T, H) bucket grid (config.PipelineConfig; FusedCycleDriver.
        warmup): steady-state cycles then trace/compile nothing, so the
        first-call compile spike can never land inside a live cycle.
        Returns the number of warmup executions (0 when unconfigured or
        the device path is unavailable)."""
        pl = self.config.pipeline
        if not (pl.warmup_tasks and pl.warmup_hosts):
            return 0
        self._ensure_fused()
        try:
            with tracing.span("fused.warmup", tasks=pl.warmup_tasks,
                              hosts=pl.warmup_hosts, sweep=pl.warmup_sweep):
                return self._fused.warmup(
                    tasks=pl.warmup_tasks, hosts=pl.warmup_hosts,
                    users=pl.warmup_users, sweep=pl.warmup_sweep,
                    gpu=pl.warmup_gpu)
        except Exception:
            # a warmup failure is a cold start, not an outage: the live
            # path compiles on first use exactly as before
            import logging
            logging.getLogger(__name__).exception(
                "fused-cycle warmup failed; first cycles compile live")
            return 0

    def step_cycle(self) -> Dict[str, MatchCycleResult]:
        """PRODUCTION cycle: rank + admission + match for every active
        non-direct pool in ONE fused device dispatch
        (sched/fused.FusedCycleDriver over parallel/sharded.make_pool_cycle),
        then the transactional launch path on host.  Direct (Kenzo) pools
        keep the host path (there is no match kernel to fuse).

        With ``config.pipeline.depth > 0`` the dispatch is pipelined
        (sched/pipeline.py): while this cycle's launches are applied, the
        next cycle's kernel is already computing on device against an
        optimistically-stale snapshot, reconciled host-side before launch.

        Replaces the reference's per-pool handler round-robin
        (scheduler.clj:2398-2517) with a single dispatch; step_rank/
        step_match remain for the CPU fallback and deterministic tests.
        """
        driver = self._ensure_fused()
        with flight_recorder.cycle(kind="fused") as rec:
            import gc
            gc_paused = self.gc_discipline and gc.isenabled()
            if gc_paused:
                gc.disable()
            degraded = False
            try:
                with tracing.span("fused.cycle"):
                    queues, results = driver.step(self)
            except Exception:
                # device dispatch failed (XLA error, device loss, injected
                # fault): degrade to the split host path for this cycle
                # instead of skipping scheduling entirely
                import logging
                logging.getLogger(__name__).exception(
                    "fused cycle failed; degrading to host split path")
                from ..utils.metrics import registry
                registry.counter_inc("cook_kernel_fallback",
                                     labels={"kernel": "fused.pool_cycle"})
                flight_recorder.note_fault("fused.dispatch-fallback")
                if self._pipeline is not None:
                    # in-flight speculation may reference the failed
                    # device state; drop it (nothing was transacted)
                    self._pipeline.reset()
                # resident buffers may live on the failed device state
                # too: rebuild them from scratch next fused cycle — and
                # the split-path Ranker this very fallback runs has its
                # own device base mirror to shed
                self._fused.reset_resident()
                self.ranker.reset_device_state()
                degraded = True
            finally:
                if gc_paused:
                    gc.enable()
                    self._gc_cycles += 1
                    # collect after the FIRST cycle (freeze the heap the
                    # warm-up built) and then every 10th
                    if self._gc_cycles == 1 or self._gc_cycles % 10 == 0:
                        self._gc_collect_due = True
            if degraded:
                # split path: rank, then match (which owns direct pools,
                # per-pool autoscaling, and last_match_results updates)
                self.step_rank()
                results = self.step_match()
                if rec is not None:
                    rec.pools = len(results)
                    rec.jobs_considered = sum(r.considered
                                              for r in results.values())
                    rec.jobs_placed = sum(len(r.launched_task_ids)
                                          for r in results.values())
                return results
            # direct pools: host rank + backpressure submission
            for pool in self.store.pools():
                if pool.state != "active" \
                        or pool.scheduler is not SchedulerKind.DIRECT:
                    continue
                ranked = self._filter_offensive_jobs(
                    self.ranker.rank_pool(pool.name, pool.dru_mode))
                queues[pool.name] = ranked
                results[pool.name] = self._match_direct(pool.name, ranked)
            # queues were computed pre-launch; prune the jobs this cycle
            # launched so consumers (rebalancer, /queue, direct pools) see
            # current state.  Pools whose producer already dropped launches
            # by exact queue position (fused _apply_pool) are skipped — the
            # full-queue isin scan is O(T) string work at the 100k+ scale.
            launched_uuids = set()
            for pool_name, result in results.items():
                if result.queue_pruned:
                    continue
                launched_uuids.update(result.launched_job_uuids)
            if launched_uuids:
                from .ranker import RankedQueue

                def prune(q):
                    if isinstance(q, RankedQueue):
                        # columnar: vectorized, no full-queue
                        # materialization
                        import numpy as np
                        return q.filtered(~np.isin(q.uuids,
                                                   list(launched_uuids)))
                    return [j for j in q if j.uuid not in launched_uuids]
                queues = {p: (q if results.get(p) is not None
                              and results[p].queue_pruned else prune(q))
                          for p, q in queues.items()}
            self.pending_queues = queues
            for pool_name, result in results.items():
                self._autoscale(pool_name, result)
            self.last_match_results.update(results)
            if rec is not None:
                rec.pools = len(results)
                rec.jobs_considered = sum(r.considered
                                          for r in results.values())
                rec.jobs_placed = sum(len(r.launched_task_ids)
                                      for r in results.values())
        # once per cycle: journal the trail's pending advisory events so
        # decision context survives a leader failover (utils/audit.py;
        # a no-op without a journal or with nothing pending)
        self.store.flush_audit()
        return results

    def step_match(self, pool_name: Optional[str] = None
                   ) -> Dict[str, MatchCycleResult]:
        """Match cycle for one pool (or all), consuming the ranked queues."""
        results: Dict[str, MatchCycleResult] = {}
        pools = ([p for p in self.store.pools() if p.name == pool_name]
                 if pool_name else self.store.pools())
        with flight_recorder.cycle(kind="match") as rec:
            # per-stage XLA launches: the split path (also joined by a
            # degraded fused cycle, which then reads "mixed")
            flight_recorder.note_path("split")
            for pool in pools:
                if pool.state != "active":
                    continue
                ranked = self.pending_queues.get(pool.name, [])
                with tracing.span("scheduler.pool-handler", pool=pool.name):
                    if pool.scheduler is SchedulerKind.DIRECT:
                        results[pool.name] = self._match_direct(pool.name,
                                                                ranked)
                        continue
                    offers = []
                    for cluster in self.launchable_clusters(pool.name):
                        offers.extend(cluster.pending_offers(pool.name))
                    result = self.matcher.match_pool(
                        pool.name, ranked, offers, self.clusters,
                        reserved_hosts=self.reserved_hosts)
                    results[pool.name] = result
                    self._autoscale(pool.name, result)
            if rec is not None:
                rec.pools = len(results)
                rec.jobs_considered = sum(r.considered
                                          for r in results.values())
                rec.jobs_placed = sum(len(r.launched_task_ids)
                                      for r in results.values())
        self.last_match_results.update(results)
        self.store.flush_audit()
        return results

    def _autoscale(self, pool_name: str, result: MatchCycleResult) -> None:
        """Post-match autoscaling: surface unmatched demand as synthetic
        pods, reap placeholders for jobs that launched (reference:
        trigger-autoscaling! scheduler.clj:1178-1283).

        The demand is routed to ONE healthy (circuit-breaker-aware)
        autoscaling cluster: fanning it out verbatim to every accepting
        cluster double-provisioned — two clusters would both synthesize
        full-size placeholder pod sets for the same unmatched jobs.
        Placeholders are still reaped on EVERY cluster (the routing
        choice may move between cycles).  Gang demand is sized as
        whole-slice synthetic pod sets with co-location affinity
        (docs/GANG.md)."""
        if not self.config.autoscaling_enabled:
            return
        launched_jobs = list(result.launched_job_uuids)
        scalers = [c for c in self.clusters.values()
                   if getattr(c, "autoscale", None) is not None
                   and c.accepts_pool(pool_name)]
        if launched_jobs:
            for cluster in scalers:
                cluster.reap_synthetic_pods(launched_jobs)
        if not result.unmatched:
            return
        healthy = [c for c in scalers if self.breakers.get(c.name).allow()]
        if not healthy:
            return
        gangs: Dict[str, Dict] = {
            g.uuid: {"size": g.gang_size, "topology": g.gang_topology}
            for g in self.store.gang_groups_of(result.unmatched).values()}
        # deterministic routing: first healthy cluster in registration
        # order that can actually absorb the demand (a stable choice
        # keeps placeholder ownership from flapping).  A scaler at its
        # pod cap creates nothing WITHOUT raising, so its breaker never
        # opens — fall through to the next healthy scaler, but only
        # with the jobs the target does NOT already hold placeholders
        # for (re-surfacing covered jobs elsewhere would recreate the
        # double-provisioning this routing exists to prevent)
        remaining = list(result.unmatched)
        for target in healthy:
            # signature-probe once per backend class (catching TypeError
            # around the executed call would mask TypeErrors raised
            # INSIDE the backend and silently re-run it without gang
            # sizing)
            takes_gangs = self._autoscale_takes_gangs.get(type(target))
            if takes_gangs is None:
                import inspect
                try:
                    takes_gangs = "gangs" in inspect.signature(
                        target.autoscale).parameters
                except (TypeError, ValueError):
                    takes_gangs = False
                self._autoscale_takes_gangs[type(target)] = takes_gangs
            if takes_gangs:
                created = target.autoscale(pool_name, remaining,
                                           now_ms=now_ms(),
                                           gangs=gangs or None)
            else:
                created = target.autoscale(pool_name, remaining,
                                           now_ms=now_ms())
            if created:
                # budget permitting, autoscale covers every missing unit
                # it was handed; anything cut at the pod cap is caught
                # next cycle, when created drops to 0 and the coverage
                # probe routes the uncovered rest onward
                return
            probe = getattr(target, "synthetic_pods_for", None)
            if probe is None:
                # backend can't report placeholder ownership — assume
                # it absorbed the demand rather than fan out
                return
            covered = set(probe([j.uuid for j in remaining]))
            # a gang partially covered here (members reaped while the
            # cluster sits at its pod budget) stays routed here WHOLE:
            # forwarding just the uncovered members would have the next
            # cluster synthesize a partial gang pod set — the split-slice
            # provisioning the all-or-none pod-set logic exists to avoid
            held = {j.group for j in remaining
                    if j.group in gangs and j.uuid in covered}
            remaining = [j for j in remaining
                         if j.uuid not in covered and j.group not in held]
            if not remaining:
                return
            # at the pod cap with uncovered demand: fall through with
            # only the uncovered jobs

    def _match_direct(self, pool_name: str, ranked: List[Job]
                      ) -> MatchCycleResult:
        """Direct (Kenzo) mode: submit up to the backends' backpressure
        capacity and let the backend place (scheduler.clj:1728-1771)."""
        result = MatchCycleResult()
        clusters = self.launchable_clusters(pool_name)
        mc = self.config.matcher_for_pool(pool_name)
        # the fused path's head-of-queue scaleback + admission scaling
        # apply here too: an unmatchable head job shrinks the window,
        # and a brownout shrinks it further (scheduler.clj:1613-1651)
        backoff = self.matcher._backoff.setdefault(
            pool_name, _BackoffState(mc.max_jobs_considered))
        window = self.matcher.admission_limit(
            pool_name, ranked,
            min(backoff.num_considerable, mc.max_jobs_considered))
        if not clusters:
            # no launchable backend (none configured, or every breaker
            # open): the real demand must still be visible — a
            # capacity-of-zero truncation would report considered=0 /
            # unmatched=0 and hide the whole backlog for the outage
            considerable = self.matcher.considerable_jobs(
                pool_name, ranked, window)
            result.considered = len(considerable)
            result.unmatched = considerable
            # backend outage, not a head-of-queue problem: like the
            # fused path's no-offers cycle, backoff state is untouched
            result.head_matched = False
            from ..utils import audit as _audit
            _audit.note_skips(self.store.audit, {
                "unmatched": [j.uuid for j in result.unmatched]},
                pool=pool_name)
            return result
        capacity = sum(c.max_launchable(pool_name) for c in clusters)
        considerable = self.matcher.considerable_jobs(
            pool_name, ranked, min(capacity, window))
        result.considered = len(considerable)
        from ..policy import pool_user_key
        launch_rl = self.rate_limits.job_launch
        cluster_rl = self.rate_limits.cluster_launch
        cluster_budget = {c.name: cluster_rl.get_token_count(c.name)
                          for c in clusters} if cluster_rl.enforce else None
        i = 0
        gangs = self.store.gang_groups_of(considerable)
        for job in considerable:
            # direct (backend-places) mode has no all-or-nothing match
            # pass, so a gang member submitted here could come up partial
            # — gangs are BATCH-pool-only (docs/GANG.md) and wait instead
            if job.group in gangs:
                result.unmatched.append(job)
                continue
            cluster = clusters[i % len(clusters)]
            i += 1
            if cluster_budget is not None:
                if cluster_budget[cluster.name] < 1:
                    result.unmatched.append(job)
                    continue
                cluster_budget[cluster.name] -= 1
            task_id = new_uuid()
            try:
                self.store.launch_instance(job.uuid, task_id, hostname="",
                                           compute_cluster=cluster.name)
            except AbortTransaction as e:
                result.launch_failures.append((job.uuid, e.reason))
                continue
            launch_rl.spend(pool_user_key(pool_name, job.user))
            cluster_rl.spend(cluster.name)
            cluster.kill_lock.acquire_read()
            try:
                cluster.launch_tasks(pool_name, [LaunchSpec(
                    task_id=task_id, job_uuid=job.uuid, hostname="",
                    slave_id="", resources=job.resources, env=job.env,
                    port_count=job.ports, container=job.container)])
            finally:
                cluster.kill_lock.release_read()
            result.launched_task_ids.append(task_id)
            result.launched_job_uuids.append(job.uuid)
        # one batched intent-confirm for the cycle's direct launches (a
        # per-task clear would journal one transaction per job)
        self.store.clear_launch_intents(result.launched_task_ids)
        launched = set(result.launched_job_uuids)
        result.head_matched = bool(
            considerable and considerable[0].uuid in launched)
        if considerable:
            backoff.update(mc, result.head_matched)
        from ..utils import audit as _audit
        _audit.note_skips(self.store.audit, {
            "unmatched": [j.uuid for j in result.unmatched],
            "launch-failed": [(u, {"why": why})
                              for u, why in result.launch_failures],
        }, pool=pool_name)
        return result

    def step_rebalance(self) -> Dict[str, list]:
        """Preemption cycle (reference: start-rebalancer! rebalancer.clj:559)."""
        if not self.rebalancer.effective_params().enabled:
            return {}
        decisions: Dict[str, list] = {}
        with flight_recorder.cycle(kind="rebalance") as rec:
            for pool in self.store.pools():
                if pool.state != "active":
                    continue
                with tracing.span("rebalancer.pool", pool=pool.name):
                    pool_decisions = self.rebalancer.rebalance_pool(
                        pool.name, pool.dru_mode,
                        self.pending_queues.get(pool.name, []), self.clusters)
                if pool_decisions:
                    decisions[pool.name] = pool_decisions
                    victims = sum(len(d.victim_task_ids)
                                  for d in pool_decisions)
                    if victims:
                        from ..utils.metrics import registry
                        # preemption ATTRIBUTION (docs/OBSERVABILITY.md):
                        # direct fair-share victims vs gang-closure mates
                        # taken only because a sibling was chosen
                        closure = sum(len(d.gang_victim_ids)
                                      for d in pool_decisions)
                        if victims - closure:
                            registry.counter_inc(
                                "cook_preemptions",
                                float(victims - closure),
                                {"pool": pool.name,
                                 "reason": "fair-share"})
                        if closure:
                            registry.counter_inc(
                                "cook_preemptions", float(closure),
                                {"pool": pool.name,
                                 "reason": "gang-closure"})
                        flight_recorder.note_preemptions(victims)
                    for d in pool_decisions:
                        if len(d.victim_task_ids) > 1:
                            self.reserved_hosts[d.job_uuid] = d.hostname
            if rec is not None:
                rec.pools = len(decisions)
        self.store.flush_audit()
        return decisions

    # --------------------------------------------------------------- elastic
    def step_resize(self) -> Dict[str, int]:
        """Per-cycle elastic resize pass (docs/GANG.md elasticity):
        execute grace-expired shrinks, then shed standing optimizer
        shrink pressure per pool.  Growth needs no step of its own —
        satisfied elastic gangs grow through the ordinary match path,
        metered by the optimizer's per-pool grow budget.  Structural
        no-op (empty ledger, zero pressure) for rigid-only workloads."""
        if not self.config.elastic.enabled:
            return {}
        out: Dict[str, int] = {}
        swept = self.elastic.sweep(self.clusters)
        if swept:
            out["_grace_expired"] = len(swept)
        if any(self.elastic.shrink_pressure.values()):
            for pool in self.store.pools():
                if pool.state != "active":
                    continue
                shed = self.elastic.apply_pressure(pool.name, self.clusters)
                if shed:
                    out[pool.name] = shed
        return out

    # ------------------------------------------------------------- optimizer
    def _ensure_optimizer(self):
        """Build the optimizer cycler lazily from ``config.optimizer``
        (an OptimizerConfig the daemon boot-validated, or None = loop
        off)."""
        if self.optimizer_cycler is None and self.config.optimizer is not None:
            self.optimizer_cycler = self.config.optimizer.build()
        return self.optimizer_cycler

    def step_optimize(self) -> Dict:
        """One optimizer cycle (sched/optimizer.py GoodputOptimizer):
        sim-replay decision pass + legacy observational schedule, then
        APPLY the decisions — grow budgets and shrink pressure onto the
        elastic manager, the preemption budget onto the rebalancer's
        dynamic-config plane — and journal them durably onto every
        affected elastic gang member's audit timeline."""
        cyc = self._ensure_optimizer()
        if cyc is None:
            return {}
        decisions = cyc.run_scheduler_cycle(self)
        if decisions:
            self._apply_optimizer_decisions(decisions, cyc)
        return decisions

    def _apply_optimizer_decisions(self, decisions, cyc) -> None:
        from ..utils.metrics import registry
        # pool -> live elastic gang groups, for the audit journaling
        # (the decision lands on the GANG's timeline: its member jobs)
        gangs_by_pool: Dict[str, list] = {}
        for group in self.store.elastic_gang_groups():
            member = next((self.store.job(u) for u in group.jobs), None)
            if member is not None:
                gangs_by_pool.setdefault(member.pool, []).append(group)
        budgets = []
        for pool_name, d in decisions.items():
            if d.grow_budget is None:
                self.elastic.grow_budget.pop(pool_name, None)
            else:
                self.elastic.grow_budget[pool_name] = float(d.grow_budget)
            if d.shrink_pressure:
                self.elastic.shrink_pressure[pool_name] = \
                    int(d.shrink_pressure)
            else:
                # a no-shrink decision REVOKES any standing pressure a
                # previous cycle left unshed — step_resize would
                # otherwise keep executing a lever the optimizer
                # already withdrew
                self.elastic.shrink_pressure.pop(pool_name, None)
            if d.preemption_budget is not None:
                budgets.append(int(d.preemption_budget))
            registry.gauge_set("cook_pool_goodput", d.current_goodput,
                               {"pool": pool_name})
            facts = {"optimizer_cycle": cyc.cycles, **d.to_dict()}
            facts.pop("scores", None)  # debug detail, not timeline fact
            for group in gangs_by_pool.get(pool_name, ()):
                for member_uuid in group.jobs:
                    self.store.audit.record(
                        member_uuid, "optimizer-decision", facts,
                        durable=True)
        if budgets:
            # the rebalancer re-reads the dynamic document every cycle
            # (effective_params), so the budget takes effect next cycle
            # and remains operator-overridable through the same plane
            self.store.update_dynamic_config(
                "rebalancer", {"max_preemption": max(budgets)})
        for pool_name, d in decisions.items():
            if d.shrink_pressure:
                self.elastic.apply_pressure(
                    pool_name, self.clusters,
                    decision_facts={"optimizer_cycle": cyc.cycles})

    # --------------------------------------------------------------- reapers
    def step_reapers(self, current_ms: Optional[int] = None) -> List[str]:
        """Kill tasks over their max runtime (lingering-task killer,
        scheduler.clj:1888-1953) and straggler instances per group quantile
        rule (scheduler.clj:1955-1986)."""
        current = current_ms if current_ms is not None else self.clock()
        killed: List[str] = []
        # ONE materializing scan shared by every reaper: each
        # running_instances() call deep-clones the full live set under the
        # store lock, so repeating it per-reaper at the 100k design point
        # would stall concurrent transactions
        running = self.store.running_instances()
        for job, inst in running:
            if job.max_runtime_ms and inst.start_time_ms and \
                    current - inst.start_time_ms > job.max_runtime_ms:
                self._kill_instance(inst.task_id, Reasons.MAX_RUNTIME_EXCEEDED.code)
                killed.append(inst.task_id)
        # the snapshot is shared, so downstream reapers must skip tasks an
        # earlier reaper already killed this tick (a stale entry would get
        # a duplicate kill RPC and a duplicate task_id in the result)
        done = set(killed)
        killed.extend(self._reap_orphaned_cluster_instances(
            current, running, skip=done))
        done.update(killed)
        killed.extend(self._reap_stragglers(current, running, skip=done))
        if self.config.heartbeat_enabled:
            for task_id in self.heartbeats.expired(current):
                self._kill_instance(task_id, Reasons.HEARTBEAT_LOST.code)
                self.heartbeats.forget(task_id)
                killed.append(task_id)
        return killed

    def _reap_orphaned_cluster_instances(self, current_ms: int,
                                         running=None,
                                         skip=frozenset()) -> List[str]:
        """Fail (NODE_LOST, mea-culpa) running instances whose compute
        cluster this scheduler does not have — the previous leader's
        in-process backend after a failover, or a dynamically deleted
        cluster.  A grace window tolerates a cluster being re-added
        (reference contract: a new leader re-reads all state and
        reconciles what its backends can't account for,
        mesos.clj:296-313 + scheduler.clj:1828-1878)."""
        grace_ms = self.config.orphaned_cluster_grace_seconds * 1000.0
        missing = self._orphan_first_seen
        failed: List[str] = []
        live = set()
        if running is None:
            running = self.store.running_instances()
        for _job, inst in running:
            if inst.task_id in skip:
                continue
            if inst.compute_cluster and \
                    inst.compute_cluster not in self.clusters:
                live.add(inst.task_id)
                first = missing.setdefault(inst.task_id, current_ms)
                if current_ms - first >= grace_ms:
                    missing.pop(inst.task_id, None)
                    self.store.update_instance_status(
                        inst.task_id, InstanceStatus.FAILED,
                        reason_code=Reasons.NODE_LOST.code)
                    failed.append(inst.task_id)
        for tid in list(missing):
            if tid not in live:
                missing.pop(tid)  # cluster came back (or task finished)
        return failed

    def _reap_stragglers(self, current_ms: int,
                         running=None, skip=frozenset()) -> List[str]:
        killed: List[str] = []
        groups: Dict[str, List] = {}
        if running is None:
            running = self.store.running_instances()
        for job, inst in running:
            if inst.task_id in skip:
                continue
            if job.group:
                groups.setdefault(job.group, []).append((job, inst))
        for group_uuid, members in groups.items():
            group = self.store.group(group_uuid)
            if group is None or group.straggler_quantile is None \
                    or group.straggler_multiplier is None:
                continue
            runtimes = []
            for member_uuid in group.jobs:
                member = self.store.job(member_uuid)
                if member is None:
                    continue
                for tid in member.instances:
                    mi = self.store.instance(tid)
                    if mi is not None and mi.status is InstanceStatus.SUCCESS \
                            and mi.end_time_ms:
                        runtimes.append(mi.end_time_ms - mi.start_time_ms)
            if not runtimes:
                continue
            runtimes.sort()
            q_idx = min(len(runtimes) - 1,
                        int(group.straggler_quantile * len(runtimes)))
            threshold = runtimes[q_idx] * group.straggler_multiplier
            for job, inst in members:
                if current_ms - inst.start_time_ms > threshold:
                    self._kill_instance(inst.task_id, Reasons.STRAGGLER.code)
                    killed.append(inst.task_id)
        return killed

    def kill_instance(self, task_id: str, reason_code: int) -> None:
        """Public single-instance kill: authoritative store transition first,
        then the backend kill (used by reapers, the rebalancer, and the REST
        instance-kill endpoint)."""
        self._kill_instance(task_id, reason_code)

    def _kill_instance(self, task_id: str, reason_code: int) -> None:
        inst = self.store.instance(task_id)
        if inst is None:
            return
        # transact the authoritative reason first so the backend's own kill
        # status arrives stale and is dropped (single-writer discipline)
        self.store.update_instance_status(task_id, InstanceStatus.FAILED,
                                          reason_code=reason_code)
        self._cluster_kill(inst.compute_cluster, task_id)

    def _cluster_kill(self, cluster_name: str, task_id: str) -> None:
        """Kill on the backend; defers to the side-effect worker when the
        calling thread holds the cluster's kill-lock read side (a write
        acquire there would self-deadlock)."""
        cluster = self.clusters.get(cluster_name)
        if cluster is None:
            return
        if cluster.kill_lock.holds_read():
            self._ensure_side_effect_worker()
            self._side_effects.put((cluster, task_id))
        else:
            cluster.safe_kill_task(task_id)

    def _ensure_side_effect_worker(self) -> None:
        if self._side_effect_thread is not None \
                and self._side_effect_thread.is_alive():
            return

        def worker():
            while not self._stop.is_set():
                try:
                    cluster, task_id = self._side_effects.get(timeout=0.5)
                except queue.Empty:
                    continue
                try:
                    cluster.safe_kill_task(task_id)
                except Exception:  # pragma: no cover
                    import logging
                    logging.getLogger(__name__).exception("deferred kill failed")
                finally:
                    self._side_effects.task_done()

        self._side_effect_thread = threading.Thread(target=worker, daemon=True)
        self._side_effect_thread.start()

    def drain_side_effects(self, timeout_s: float = 5.0) -> bool:
        """Block until every queued deferred backend kill has been
        processed — determinism hook for tests and the chaos simulator
        (gang-policy sibling kills defer when the triggering event fires
        under a cluster's kill-lock read side).  Returns False on
        timeout."""
        if self._side_effect_thread is None:
            return True
        q = self._side_effects
        deadline = time.time() + timeout_s
        with q.all_tasks_done:
            while q.unfinished_tasks:
                remaining = deadline - time.time()
                if remaining <= 0:
                    return False
                q.all_tasks_done.wait(remaining)
        return True

    # ------------------------------------------------------------- wall clock
    def run(self) -> None:
        """Start background cycle threads (the chime equivalent)."""
        cfg = self.config

        def loop(interval, fn, immediate: bool = False) -> None:
            # interval may be a callable so dynamically-tunable cadences
            # (the rebalancer's no-restart interval-seconds) take effect on
            # the next tick instead of being frozen at startup
            if immediate and not self._stop.is_set():
                try:
                    fn()
                except Exception:  # pragma: no cover - cycle errors are logged
                    import logging
                    logging.getLogger(__name__).exception("cycle failed")
            while not self._stop.wait(interval() if callable(interval)
                                      else interval):
                try:
                    fn()
                except Exception:  # pragma: no cover - cycle errors are logged
                    import logging
                    logging.getLogger(__name__).exception("cycle failed")

        if cfg.cycle_mode == "fused" and self.ranker.backend != "cpu":
            # production path: one fused rank+match dispatch per cycle,
            # followed by the idle-point GC maintenance (gc_discipline)
            def fused_tick():
                self.step_cycle()
                self.maintain_gc()
            specs = [(cfg.match_interval_seconds, fused_tick)]
        else:
            specs = [(cfg.rank_interval_seconds, self.step_rank),
                     (cfg.match_interval_seconds, self.step_match)]
        specs += [
            (lambda: self.rebalancer.effective_params().interval_seconds,
             self.step_rebalance),
            (cfg.lingering_task_interval_seconds, self.step_reapers),
            (cfg.monitor_interval_seconds, self.monitor.sweep),
        ]
        if cfg.elastic.enabled:
            specs.append((cfg.elastic.resize_interval_seconds,
                          self.step_resize))
        for interval, fn in specs:
            t = threading.Thread(target=loop, args=(interval, fn), daemon=True)
            t.start()
            self._threads.append(t)
        if cfg.optimizer is not None:
            # immediate first cycle: the debug surface must not read
            # dead for a full interval after boot (the OptimizerCycler
            # fix, mirrored here for the scheduler-driven loop)
            t = threading.Thread(
                target=loop,
                args=(cfg.optimizer.interval_seconds, self.step_optimize),
                kwargs={"immediate": True}, daemon=True)
            t.start()
            self._threads.append(t)

    def shutdown(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5)
