"""Sharded controller processes: one partition = one process = one mesh
shard (ISSUE 19).

PR 12 partitioned the write plane (per-partition journal, fsync stream,
group-commit stage, lease, replication slot) and PR 14 made the cycle a
per-pool single-launch megakernel — but every partition still ran inside
ONE Python process.  This module is the scale-out step: a shard WORKER
process owns one contiguous partition block end-to-end —

- its pools' write plane: the partition Store (own journal + group
  commit), fenced by the partition lease it acquires at boot
  (:func:`~cook_tpu.sched.election.acquire_shard_lease` — process death
  releases the flock, which is what the PR 3 candidate-ranking failover
  keys on);
- its resident entity pack and fused/megakernel cycle launches: the
  scheduler it builds sees only its partition's pools (PR 14's cycle is
  per-pool by construction, so it shards for free), and the resident
  buffers it commits live in THIS process
  (``parallel.mesh.pool_sharding``'s owner-local contract, now across
  processes);
- its flight recorder and span ring, stamped with the shard identity
  (``flight.set_shard`` + ``tracing.set_process_identity``) so the
  supervisor stitches per-shard cycle traces into ONE Perfetto export
  with distinct process tracks (PR 16's ``export_fleet_trace``).

Cross-pool global state — per-user DRU, global quota/pending caps —
rides PR 12's bounded :class:`~cook_tpu.state.partition
.UserSummaryExchange` between the shard processes, never job state: the
``peer_fetch`` carrier here is a framed-JSON localhost socket (an
ICI/DCN collective when a real mesh is present), and the staleness
bound is ASSERTED (``assert_bound=True`` — a dead peer trips
:class:`~cook_tpu.state.partition.SummaryStalenessError` instead of
silently-stale enforcement).

The parent-side :class:`ShardSupervisor` spawns N workers, fans
commands out over the same socket protocol, and is what the
``sharded_cycle`` bench section, the cross-process decision-parity
tests, and the REAL-process-kill leg of ``sim --chaos-failover
--partitions N`` drive.

Wire protocol: 4-byte big-endian length + one JSON object per frame;
one request -> one response per frame, connections are serial per
client thread.  Deliberately not HTTP: the exchange sits on the quota
hot path and the chaos harness needs it working in a store-only worker
that never imports the REST stack.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import struct
import subprocess
import sys
import tempfile
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

_FRAME_MAX = 64 * 1024 * 1024


# --------------------------------------------------------------------------
# framed-JSON wire helpers (both sides)
# --------------------------------------------------------------------------

def send_msg(sock: socket.socket, obj: Dict[str, Any]) -> None:
    data = json.dumps(obj).encode("utf-8")
    sock.sendall(struct.pack(">I", len(data)) + data)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("shard peer closed mid-frame")
        buf += chunk
    return buf


def recv_msg(sock: socket.socket) -> Dict[str, Any]:
    (n,) = struct.unpack(">I", _recv_exact(sock, 4))
    if n > _FRAME_MAX:
        raise ValueError(f"shard frame of {n} bytes exceeds {_FRAME_MAX}")
    return json.loads(_recv_exact(sock, n).decode("utf-8"))


def rpc(port: int, obj: Dict[str, Any], timeout_s: float = 30.0,
        host: str = "127.0.0.1") -> Dict[str, Any]:
    """One request/response round to a shard worker's control socket.
    Raises on transport errors and re-raises worker-side errors as
    RuntimeError — callers decide whether a dead shard is fatal (parity
    runs) or expected (the chaos kill window)."""
    with socket.create_connection((host, port), timeout=timeout_s) as s:
        s.settimeout(timeout_s)
        send_msg(s, obj)
        resp = recv_msg(s)
    if not resp.get("ok"):
        raise RuntimeError(
            f"shard rpc {obj.get('cmd')!r} failed: {resp.get('error')}")
    return resp


def read_addr_file(path: str, timeout_s: float = 30.0) -> Dict[str, Any]:
    """Wait for a worker's atomically-written address announcement
    ({port, pid, repl_port?}) — the boot barrier the supervisor joins."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        try:
            with open(path, encoding="utf-8") as f:
                doc = json.load(f)
            if doc.get("port"):
                return doc
        except (OSError, json.JSONDecodeError):
            pass
        time.sleep(0.02)
    raise TimeoutError(f"shard address file {path} never appeared "
                       f"(worker failed to boot within {timeout_s}s)")


# --------------------------------------------------------------------------
# peer summary carrier (the UserSummaryExchange socket feed)
# --------------------------------------------------------------------------

class PeerSummaryFeed:
    """``peer_fetch`` carrier for :class:`UserSummaryExchange`: fetch
    every PEER shard's bounded per-user table over the control socket.
    A reachable peer contributes a fresh table (age 0 — the peer
    computes ``Store.user_summary()`` inside the request); an
    unreachable one contributes its last cached table aged by the time
    since that fetch, so the exchange's asserted staleness bound trips
    exactly when the fleet view genuinely decayed past the window."""

    def __init__(self, peer_addr_files: List[str], self_shard: int,
                 timeout_s: float = 5.0):
        self._addr_files = [
            (i, p) for i, p in enumerate(peer_addr_files)
            if i != self_shard]
        self._timeout_s = timeout_s
        self._ports: Dict[int, int] = {}
        # shard -> (table, monotonic fetch time) fallback cache
        self._cache: Dict[int, Tuple[Dict[str, Dict[str, float]], float]] = {}
        self.fetch_errors = 0

    def _port(self, shard: int, path: str) -> int:
        port = self._ports.get(shard)
        if port is None:
            port = int(read_addr_file(path, self._timeout_s)["port"])
            self._ports[shard] = port
        return port

    def __call__(self) -> List[Tuple[Dict[str, Dict[str, float]], float]]:
        out: List[Tuple[Dict[str, Dict[str, float]], float]] = []
        for shard, path in self._addr_files:
            try:
                resp = rpc(self._port(shard, path),
                           {"cmd": "summary"}, timeout_s=self._timeout_s)
                table = resp.get("users") or {}
                self._cache[shard] = (table, time.monotonic())
                out.append((table, 0.0))
            except Exception:
                self.fetch_errors += 1
                self._ports.pop(shard, None)  # re-resolve after failover
                cached = self._cache.get(shard)
                if cached is not None:
                    table, at = cached
                    out.append((table, time.monotonic() - at))
                else:
                    # never seen this peer: the fleet view is unbounded-
                    # stale by definition; inf backdates the sweep so an
                    # asserting consumer refuses instead of under-counting
                    out.append(({}, float("inf")))
        return out


# --------------------------------------------------------------------------
# worker side
# --------------------------------------------------------------------------

class _BaseWorker:
    """Control-socket serving shared by both worker roles: bind, announce
    the address atomically, then one handler thread per connection (the
    chaos harness drives concurrent writer threads from the parent)."""

    def __init__(self, spec: Dict[str, Any]):
        self.spec = spec
        self.shard = int(spec.get("shard", 0))
        self._stop = threading.Event()
        self._srv: Optional[socket.socket] = None

    # role hooks -----------------------------------------------------------
    def setup(self) -> Dict[str, Any]:
        """Open stores/schedulers; returns extra addr-file fields."""
        return {}

    def handle(self, req: Dict[str, Any]) -> Dict[str, Any]:
        raise NotImplementedError

    def teardown(self) -> None:
        pass

    # lifecycle ------------------------------------------------------------
    def serve_forever(self) -> int:
        from ..utils import tracing
        from ..utils.flight import set_shard
        set_shard(self.shard)
        tracing.set_process_identity(f"shard-{self.shard}")
        extra = self.setup()
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(("127.0.0.1", 0))
        srv.listen(64)
        self._srv = srv
        addr = {"port": srv.getsockname()[1], "pid": os.getpid(),
                "shard": self.shard, **extra}
        path = self.spec["addr_file"]
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(addr, f)
        os.replace(tmp, path)
        try:
            while not self._stop.is_set():
                try:
                    conn, _ = srv.accept()
                except OSError:
                    break
                t = threading.Thread(target=self._conn_loop, args=(conn,),
                                     daemon=True)
                t.start()
        finally:
            self.teardown()
        return 0

    def _conn_loop(self, conn: socket.socket) -> None:
        with conn:
            while not self._stop.is_set():
                try:
                    req = recv_msg(conn)
                except (ConnectionError, OSError, ValueError):
                    return
                try:
                    if req.get("cmd") == "ping":
                        resp = {"ok": True, "shard": self.shard,
                                "pid": os.getpid(),
                                "role": self.spec.get("role", "sched")}
                    elif req.get("cmd") == "shutdown":
                        resp = {"ok": True}
                        send_msg(conn, resp)
                        self._stop.set()
                        if self._srv is not None:
                            try:
                                # close() alone does not wake a thread
                                # blocked in accept() on Linux; shutdown
                                # does (accept fails with EINVAL)
                                self._srv.shutdown(socket.SHUT_RDWR)
                            except OSError:
                                pass
                            try:
                                self._srv.close()
                            except OSError:
                                pass
                        return
                    else:
                        resp = self.handle(req)
                except Exception as e:  # worker must answer, never wedge
                    resp = {"ok": False,
                            "error": f"{type(e).__name__}: {e}"}
                try:
                    send_msg(conn, resp)
                except OSError:
                    return


class _StoreWorker(_BaseWorker):
    """Chaos-harness role: one partition's WRITE PLANE only — fenced
    journal + group commit + sync socket replication — no scheduler, no
    jax.  ``sim --chaos-failover --partitions N`` SIGKILLs one of these
    for real and promotes its synced standby in the parent."""

    def setup(self) -> Dict[str, Any]:
        from ..state import replication as repl
        from ..state.store import Store
        spec = self.spec
        store = Store.open(spec["data_dir"], epoch=int(spec["epoch"]),
                           shared=False, partition=self.shard)
        store.attach_fence_authority(spec["authority"])
        self.store = store
        self.server = None
        extra: Dict[str, Any] = {}
        if spec.get("replicate", True):
            srv = repl.ReplicationServer(spec["data_dir"], 0)
            srv.epoch = int(spec["epoch"])
            srv.partition = self.shard
            store.attach_replication(
                srv, sync=True,
                timeout_s=float(spec.get("ack_timeout_s", 5.0)))
            self.server = srv
            extra["repl_port"] = srv.port
        if spec.get("group_commit", True):
            store.enable_group_commit(window_ms=2.0)
        return extra

    def handle(self, req: Dict[str, Any]) -> Dict[str, Any]:
        from ..state.store import ReplicationIndeterminate, _job_from_json
        cmd = req.get("cmd")
        if cmd == "put_pool":
            from ..state.schema import Pool
            self.store.put_pool(Pool(name=req["name"]))
            return {"ok": True}
        if cmd == "submit":
            jobs = [_job_from_json(d) for d in req["jobs"]]
            try:
                self.store.create_jobs(jobs)
                return {"ok": True, "outcome": "committed"}
            except ReplicationIndeterminate:
                return {"ok": True, "outcome": "indeterminate"}
        if cmd == "job":
            job = self.store.job(req["uuid"])
            return {"ok": True, "found": job is not None,
                    "state": job.state.value if job else None}
        if cmd == "arm_fault":
            from ..utils.faults import injector
            injector.arm(req["point"],
                         probability=float(req.get("probability", 1.0)),
                         max_fires=req.get("max_fires"))
            return {"ok": True}
        if cmd == "repl_status":
            journal = os.path.join(self.spec["data_dir"], "journal.jsonl")
            size = os.path.getsize(journal) if os.path.exists(journal) else 0
            synced = (self.server.synced_follower_count
                      if self.server is not None else 0)
            return {"ok": True, "synced_followers": synced,
                    "journal_bytes": size}
        return {"ok": False, "error": f"unknown cmd {cmd!r}"}

    def teardown(self) -> None:
        try:
            if self.server is not None:
                self.server.stop()
            self.store.close()
        except Exception:
            pass


class _SchedWorker(_BaseWorker):
    """Full controller shard: partition store + scheduler + fused cycle
    over ONLY this shard's pools, socket-fed summary exchange for the
    global view.  The parity tests, the exchange tests and the
    ``sharded_cycle`` bench drive this role."""

    def setup(self) -> Dict[str, Any]:
        from ..cluster import FakeCluster
        from ..sched.scheduler import Scheduler
        from ..state.partition import UserSummaryExchange
        from ..state.schema import Pool
        from ..state.store import Store
        spec = self.spec
        n_shards = int(spec.get("n_shards", 1))
        pools: List[str] = list(spec["pools"])
        my_pools = shard_pools(pools, self.shard, n_shards)
        if spec.get("election_dir"):
            from .election import acquire_shard_lease
            # one lease per shard: partition block p maps 1:1 here
            self.lease = acquire_shard_lease(
                spec["election_dir"], self.shard,
                f"shard://{self.shard}")
        store = Store(partition=self.shard if n_shards > 1 else None)
        for name in my_pools:
            store.put_pool(Pool(name=name))
        world = spec.get("world") or {}
        jobs = [j for j in build_world_jobs(world, pools)
                if j.pool in set(my_pools)]
        hosts = build_world_hosts(world, my_pools)
        cfg = config_from_spec(spec.get("cfg") or {})
        cluster = FakeCluster(f"fake-s{self.shard}", hosts)
        sched = Scheduler(store, cfg, [cluster],
                          rank_backend=(spec.get("cfg") or {}).get(
                              "rank_backend", "tpu"),
                          shard_id=self.shard if n_shards > 1 else None)
        for job in jobs:
            store.create_jobs([job])
        self.store, self.sched, self.jobs = store, sched, jobs
        self.my_pools = my_pools
        feed = None
        if n_shards > 1 and spec.get("peers"):
            feed = PeerSummaryFeed(list(spec["peers"]), self.shard)
        self.exchange = UserSummaryExchange(
            [store],
            max_age_s=float(spec.get("summary_max_age_s", 1.0)),
            peer_fetch=feed, assert_bound=True)
        return {"pools": my_pools}

    def handle(self, req: Dict[str, Any]) -> Dict[str, Any]:
        cmd = req.get("cmd")
        if cmd == "cycle":
            durations = []
            for _ in range(int(req.get("n", 1))):
                t0 = time.perf_counter()
                self.sched.step_cycle()
                durations.append((time.perf_counter() - t0) * 1000.0)
            return {"ok": True, "cycles": len(durations),
                    "durations_ms": [round(d, 3) for d in durations]}
        if cmd == "decisions":
            out = {}
            for j in self.jobs:
                job = self.store.job(j.uuid)
                hosts = sorted(
                    self.store.instance(t).hostname for t in job.instances
                    if self.store.instance(t) is not None)
                out[j.uuid] = [job.state.value, hosts]
            return {"ok": True, "decisions": out}
        if cmd == "submit":
            from ..state.store import _job_from_json
            jobs = [_job_from_json(d) for d in req["jobs"]]
            self.store.create_jobs(jobs)
            self.jobs.extend(jobs)
            return {"ok": True, "created": len(jobs)}
        if cmd == "summary":
            return {"ok": True, "users": self.store.user_summary()}
        if cmd == "user_totals":
            from ..state.partition import SummaryStalenessError
            try:
                totals = self.exchange.user_totals(req["user"])
            except SummaryStalenessError as e:
                return {"ok": True, "stale": str(e)}
            return {"ok": True, "totals": totals,
                    "staleness_s": self.exchange.staleness_s()}
        if cmd == "exchange_stats":
            return {"ok": True, "stats": self.exchange.stats()}
        if cmd == "flight_summary":
            from ..utils.flight import recorder
            return {"ok": True, "shard": self.shard,
                    "summary": recorder.summary(int(req.get("since_seq", 0)))}
        if cmd == "trace_spans":
            from ..utils import tracing
            if req.get("trace_id"):
                docs = tracing.tracer.traces(req["trace_id"])
            else:
                docs = tracing.tracer.recent(int(req.get("limit", 1000)))
            return {"ok": True, "spans": docs}
        return {"ok": False, "error": f"unknown cmd {cmd!r}"}


# --------------------------------------------------------------------------
# deterministic world construction (shared by every topology so 1-process
# and N-process runs see byte-identical jobs and hosts)
# --------------------------------------------------------------------------

def shard_pools(pools: List[str], shard: int, n_shards: int) -> List[str]:
    """The contiguous pool block shard ``shard`` owns: pool i lives on
    write-plane partition i, partitions block over shards — the same
    layout ``parallel.mesh.shard_of_partition`` validates at boot."""
    from ..parallel.mesh import shard_of_partition
    return [p for i, p in enumerate(pools)
            if shard_of_partition(i, len(pools), n_shards) == shard]


def build_world_jobs(world: Dict[str, Any], pools: List[str]) -> List:
    """Fixed-uuid world jobs over ALL pools.  Each job's attributes are
    derived from its INDEX alone (per-index rng stream), so a worker
    filtering to its own pools materializes exactly the same Job values
    the single-process topology does — the bit-identical-launches parity
    contract starts here."""
    import numpy as np

    from ..state.schema import Job, Resources
    n_jobs = int(world.get("n_jobs", 16))
    n_users = int(world.get("n_users", 3))
    seed = int(world.get("seed", 3))
    jobs = []
    for i in range(n_jobs):
        rng = np.random.default_rng(seed * 1_000_003 + i)
        jobs.append(Job(
            uuid=f"00000000-0000-4000-8000-{i:012d}",
            user=f"user{i % n_users}", command="true",
            pool=pools[i % len(pools)],
            priority=int(rng.integers(0, 100)),
            resources=Resources(cpus=float(rng.integers(1, 4)),
                                mem=float(rng.integers(128, 1024))),
            submit_time_ms=1000 + i))
    return jobs


def build_world_hosts(world: Dict[str, Any], pools: List[str]) -> List:
    """Pool-tagged FakeHosts for the given pools, deterministic names —
    offers are pool-filtered (cluster/fake.py), so each pool's matching
    surface is identical whichever process hosts it."""
    from ..cluster import FakeHost
    from ..state.schema import Resources
    hosts_per_pool = int(world.get("hosts_per_pool", 4))
    cpus = float(world.get("host_cpus", 16.0))
    mem = float(world.get("host_mem", 16384.0))
    return [FakeHost(hostname=f"{pool}-h{k}", pool=pool,
                     capacity=Resources(cpus=cpus, mem=mem))
            for pool in pools for k in range(hosts_per_pool)]


def config_from_spec(cfg_spec: Dict[str, Any]):
    from ..config import Config
    cfg = Config()
    cfg.cycle_mode = cfg_spec.get("cycle_mode", "fused")
    cfg.default_matcher.backend = cfg_spec.get("backend", "tpu")
    cfg.pipeline.depth = int(cfg_spec.get("depth", 0))
    cfg.resident_pack = bool(cfg_spec.get("resident", False))
    cfg.quantized_wire = bool(cfg_spec.get("quantized", False))
    return cfg


# --------------------------------------------------------------------------
# supervisor side
# --------------------------------------------------------------------------

class ShardProc:
    def __init__(self, shard: int, proc: subprocess.Popen,
                 addr_file: str, spec_file: str):
        self.shard = shard
        self.proc = proc
        self.addr_file = addr_file
        self.spec_file = spec_file
        self.addr: Dict[str, Any] = {}

    @property
    def pid(self) -> int:
        return self.proc.pid

    @property
    def port(self) -> int:
        return int(self.addr["port"])


class ShardSupervisor:
    """Spawn and drive N shard worker processes.

    Each worker gets ``base_spec`` + its shard identity + the shared
    peer address list (the summary-exchange carrier wiring); per-shard
    overrides come from ``per_shard`` (the chaos harness points each
    store-role worker at its own journal dir + fence authority).  The
    supervisor is deliberately thin: it never holds job state — its
    cross-shard reads are the same bounded summaries and telemetry
    documents any shard could serve."""

    def __init__(self, n_shards: int, base_spec: Dict[str, Any],
                 root: Optional[str] = None,
                 per_shard: Optional[List[Dict[str, Any]]] = None):
        self.n_shards = int(n_shards)
        self.root = root or tempfile.mkdtemp(prefix="cook-shards-")
        os.makedirs(self.root, exist_ok=True)
        self.base_spec = dict(base_spec)
        self.per_shard = list(per_shard or [{}] * self.n_shards)
        self.procs: List[ShardProc] = []

    # -------------------------------------------------------------- launch
    def start(self, boot_timeout_s: float = 60.0) -> "ShardSupervisor":
        addr_files = [os.path.join(self.root, f"shard-{i}.addr.json")
                      for i in range(self.n_shards)]
        pkg_parent = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env = dict(os.environ)
        env["PYTHONPATH"] = pkg_parent + (
            os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
        for i in range(self.n_shards):
            spec = dict(self.base_spec, shard=i, n_shards=self.n_shards,
                        addr_file=addr_files[i], peers=addr_files)
            spec.update(self.per_shard[i] if i < len(self.per_shard) else {})
            spec_file = os.path.join(self.root, f"shard-{i}.spec.json")
            with open(spec_file, "w", encoding="utf-8") as f:
                json.dump(spec, f)
            log = open(os.path.join(self.root, f"shard-{i}.log"), "wb")
            proc = subprocess.Popen(
                [sys.executable, "-m", "cook_tpu.sched.shard", spec_file],
                stdout=log, stderr=subprocess.STDOUT, env=env,
                cwd=pkg_parent)
            log.close()
            self.procs.append(ShardProc(i, proc, addr_files[i], spec_file))
        deadline = time.monotonic() + boot_timeout_s
        for sp in self.procs:
            remaining = max(0.5, deadline - time.monotonic())
            sp.addr = read_addr_file(sp.addr_file, remaining)
        return self

    # ----------------------------------------------------------------- rpc
    def rpc(self, shard: int, obj: Dict[str, Any],
            timeout_s: float = 60.0) -> Dict[str, Any]:
        return rpc(self.procs[shard].port, obj, timeout_s=timeout_s)

    def broadcast(self, obj: Dict[str, Any],
                  timeout_s: float = 60.0) -> List[Dict[str, Any]]:
        """Fan a command to every live shard CONCURRENTLY — on a
        multi-core host the shards' cycles overlap, which is the whole
        point of the scale-out; serializing here would serialize them."""
        out: List[Optional[Dict[str, Any]]] = [None] * len(self.procs)
        errs: List[Optional[Exception]] = [None] * len(self.procs)

        def _one(i: int) -> None:
            try:
                out[i] = self.rpc(i, dict(obj), timeout_s=timeout_s)
            except Exception as e:
                errs[i] = e

        threads = [threading.Thread(target=_one, args=(i,))
                   for i in range(len(self.procs))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=timeout_s + 5.0)
        for e in errs:
            if e is not None:
                raise e
        return [r for r in out if r is not None]

    # ------------------------------------------------------------ stitches
    def collect_decisions(self) -> Dict[str, Tuple[str, Tuple[str, ...]]]:
        """The union launched set across shards, in the parity-matrix
        shape of tests/test_megakernel.decisions()."""
        merged: Dict[str, Tuple[str, Tuple[str, ...]]] = {}
        for resp in self.broadcast({"cmd": "decisions"}):
            for uuid, (state, hosts) in resp["decisions"].items():
                merged[uuid] = (state, tuple(hosts))
        return merged

    def collect_flight(self, since_seq: int = 0) -> Dict[int, Dict[str, Any]]:
        """Per-shard flight-recorder summaries (each carries its own
        ``by_shard`` roll-up keyed by the worker's shard id)."""
        out: Dict[int, Dict[str, Any]] = {}
        for resp in self.broadcast({"cmd": "flight_summary",
                                    "since_seq": since_seq}):
            out[int(resp["shard"])] = resp["summary"]
        return out

    def collect_trace(self, trace_label: str = "sharded-cycle"
                      ) -> Dict[str, Any]:
        """ONE stitched Perfetto export across every shard's span ring:
        each worker's spans carry its ``shard-<i>`` process identity, so
        ``export_fleet_trace`` renders them as distinct process tracks
        (PR 16), with per-shard provenance in ``otherData``."""
        from ..utils.tracing import export_fleet_trace
        spans: List[Dict[str, Any]] = []
        members: List[Dict[str, Any]] = []
        for sp in self.procs:
            entry: Dict[str, Any] = {"instance": f"shard-{sp.shard}"}
            try:
                resp = self.rpc(sp.shard, {"cmd": "trace_spans"})
                remote = resp.get("spans") or []
                spans.extend(remote)
                entry.update(ok=True, spans=len(remote))
            except Exception as e:
                entry.update(ok=False, error=f"{type(e).__name__}: {e}")
            members.append(entry)
        seen = set()
        deduped = []
        for d in spans:
            key = (d.get("proc"), d.get("span_id"))
            if key not in seen:
                seen.add(key)
                deduped.append(d)
        return export_fleet_trace(deduped, trace_label, members=members)

    # ------------------------------------------------------------ lifecycle
    def kill(self, shard: int, sig: int = signal.SIGKILL) -> None:
        """REAL process kill — the chaos leg's victim loss.  SIGKILL by
        default: no handlers, no cleanup, the journal stops mid-write
        exactly as a host loss would leave it."""
        os.kill(self.procs[shard].pid, sig)
        self.procs[shard].proc.wait(timeout=30.0)

    def stop(self) -> None:
        for sp in self.procs:
            if sp.proc.poll() is not None:
                continue
            try:
                rpc(sp.port, {"cmd": "shutdown"}, timeout_s=5.0)
            except Exception:
                pass
        deadline = time.monotonic() + 5.0
        for sp in self.procs:
            while sp.proc.poll() is None and time.monotonic() < deadline:
                time.sleep(0.05)
            if sp.proc.poll() is None:
                try:
                    sp.proc.kill()
                    sp.proc.wait(timeout=10.0)
                except OSError:
                    pass


def sched_topology(n_shards: int, pools: List[str],
                   world: Dict[str, Any],
                   cfg: Optional[Dict[str, Any]] = None,
                   summary_max_age_s: float = 1.0,
                   root: Optional[str] = None) -> ShardSupervisor:
    """Convenience: an N-process scheduler topology over ``pools`` with
    a deterministic world — the parity tests' and bench's entry point."""
    base = {"role": "sched", "pools": list(pools), "world": dict(world),
            "cfg": dict(cfg or {}), "summary_max_age_s": summary_max_age_s}
    return ShardSupervisor(n_shards, base, root=root).start()


# --------------------------------------------------------------------------
# worker entry point
# --------------------------------------------------------------------------

def run_worker(spec: Dict[str, Any]) -> int:
    role = spec.get("role", "sched")
    worker = _StoreWorker(spec) if role == "store" else _SchedWorker(spec)
    return worker.serve_forever()


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if len(argv) != 1:
        print("usage: python -m cook_tpu.sched.shard <spec.json>",
              file=sys.stderr)
        return 2
    with open(argv[0], encoding="utf-8") as f:
        spec = json.load(f)
    rc = run_worker(spec)
    # Hard exit: the scheduler's pump/pipeline threads are non-daemon and
    # would hold the interpreter open past the supervisor's stop deadline.
    # Worker state is crash-safe by contract (the chaos leg SIGKILLs these
    # processes), so a clean shutdown owes nothing to interpreter teardown.
    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(rc)


if __name__ == "__main__":
    sys.exit(main())
