"""Pluggable scheduling optimizer (forecaster).

Parity with the reference's optimizer subsystem (reference:
scheduler/src/cook/scheduler/optimizer.clj): ``HostFeed``/``Optimizer``
protocols, dummy implementations, a validated ``Schedule`` shape, and a
cycle driver. Like the reference (TODO at mesos.clj:258-267), the produced
schedule is observational — it is validated and surfaced but not wired to
launch actions.

Factories are config-driven dotted paths, mirroring the reference's
``lazy-load-var`` create-fn loading (optimizer.clj:115-124).
"""

from __future__ import annotations

import importlib
import logging
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

log = logging.getLogger(__name__)


@dataclass(frozen=True)
class HostInfo:
    """A purchasable host class (reference: optimizer.clj HostInfo schema)."""
    count: int
    instance_type: str
    cpus: float
    mem: float
    gpus: Optional[float] = None

    def validate(self) -> None:
        if self.count < 0:
            raise ValueError(f"HostInfo.count must be >= 0, got {self.count}")
        if self.cpus <= 0 or self.mem <= 0:
            raise ValueError("HostInfo cpus/mem must be positive")
        if self.gpus is not None and self.gpus <= 0:
            raise ValueError("HostInfo gpus, when present, must be positive")


class HostFeed:
    """Service producing info on hosts that can be purchased
    (reference: optimizer.clj:33 defprotocol HostFeed)."""

    def get_available_host_info(self) -> List[HostInfo]:
        raise NotImplementedError


class Optimizer:
    """Tool producing a schedule to execute
    (reference: optimizer.clj:57 defprotocol Optimizer).

    ``produce_schedule(queue, running, available, host_infos)`` returns
    ``{millis_in_future: {"suggested-matches": {HostInfo: [job uuids]}}}``.
    """

    def produce_schedule(self, queue: List[Any], running: List[Any],
                         available: List[Any],
                         host_infos: List[HostInfo]) -> Dict:
        raise NotImplementedError


class DummyHostFeed(HostFeed):
    """Returns no purchasable hosts (reference: create-dummy-host-feed)."""

    def __init__(self, config: Optional[Dict] = None):
        self.config = config or {}

    def get_available_host_info(self) -> List[HostInfo]:
        return []


class DummyOptimizer(Optimizer):
    """Returns an empty schedule (reference: create-dummy-optimizer)."""

    def __init__(self, config: Optional[Dict] = None):
        self.config = config or {}

    def produce_schedule(self, queue, running, available, host_infos):
        return {0: {"suggested-matches": {}}}


def validate_schedule(schedule: Dict) -> None:
    """Structural validation of a Schedule (reference: optimizer.clj Schedule
    schema + s/validate at :111)."""
    if not isinstance(schedule, dict):
        raise ValueError("schedule must be a dict of time-period -> step")
    for period_ms, step in schedule.items():
        if not isinstance(period_ms, int) or period_ms < 0:
            raise ValueError(f"schedule key {period_ms!r} is not a "
                             "non-negative integer of millis-in-future")
        if not isinstance(step, dict) or "suggested-matches" not in step:
            raise ValueError(f"schedule step at {period_ms} is missing "
                             "'suggested-matches'")
        matches = step["suggested-matches"]
        if not isinstance(matches, dict):
            raise ValueError("suggested-matches must map HostInfo -> [uuid]")
        for host_info, uuids in matches.items():
            if not isinstance(host_info, HostInfo):
                raise ValueError(f"suggested-matches key {host_info!r} is "
                                 "not a HostInfo")
            host_info.validate()
            if not isinstance(uuids, (list, tuple)):
                raise ValueError("suggested-matches values must be lists of "
                                 "job uuids")


def optimizer_cycle(get_queue: Callable[[], List[Any]],
                    get_running: Callable[[], List[Any]],
                    get_offers: Callable[[], List[Any]],
                    host_feed: HostFeed,
                    optimizer: Optimizer) -> Dict:
    """One optimizer cycle (reference: optimizer-cycle! optimizer.clj:90-113):
    gather queue/running/host info, produce a schedule, validate it."""
    queue = get_queue()
    running = get_running()
    # Offer integration with pools is not implemented in the reference
    # either (optimizer.clj:106); pass the empty set for parity.
    available: List[Any] = []
    host_infos = host_feed.get_available_host_info()
    for info in host_infos:
        if not isinstance(info, HostInfo):
            raise ValueError(f"host feed produced non-HostInfo {info!r}")
        info.validate()
    schedule = optimizer.produce_schedule(queue, running, available,
                                          host_infos)
    validate_schedule(schedule)
    return schedule


def _load_factory(dotted: str) -> Callable:
    """Resolve 'pkg.module.fn' (reference: lazy-load-var)."""
    module_name, _, attr = dotted.rpartition(".")
    if not module_name:
        raise ValueError(f"factory path {dotted!r} must be module.attr")
    return getattr(importlib.import_module(module_name), attr)


@dataclass
class OptimizerConfig:
    """Config-driven construction (reference: start-optimizer-cycles!
    construct, optimizer.clj:118-123)."""
    host_feed_create_fn: str = "cook_tpu.sched.optimizer.DummyHostFeed"
    host_feed_config: Dict = field(default_factory=dict)
    optimizer_create_fn: str = "cook_tpu.sched.optimizer.DummyOptimizer"
    optimizer_config: Dict = field(default_factory=dict)
    interval_seconds: float = 30.0

    def build(self) -> "OptimizerCycler":
        host_feed = _load_factory(self.host_feed_create_fn)(
            self.host_feed_config)
        optimizer = _load_factory(self.optimizer_create_fn)(
            self.optimizer_config)
        return OptimizerCycler(host_feed, optimizer, self.interval_seconds)


class OptimizerCycler:
    """Periodic driver (reference: start-optimizer-cycles! optimizer.clj:115).
    Errors are logged-and-swallowed per cycle, matching the reference's
    error-handler."""

    def __init__(self, host_feed: HostFeed, optimizer: Optimizer,
                 interval_seconds: float = 30.0):
        self.host_feed = host_feed
        self.optimizer = optimizer
        self.interval_seconds = interval_seconds
        self.last_schedule: Optional[Dict] = None
        self.last_error: Optional[Exception] = None
        self.cycles = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def run_cycle(self, get_queue, get_running,
                  get_offers=lambda: []) -> Optional[Dict]:
        try:
            self.last_schedule = optimizer_cycle(
                get_queue, get_running, get_offers,
                self.host_feed, self.optimizer)
            self.last_error = None
        except Exception as e:
            log.warning("Error running optimizer cycle", exc_info=e)
            self.last_error = e
            return None
        finally:
            self.cycles += 1
        return self.last_schedule

    def start(self, get_queue, get_running, get_offers=lambda: []) -> None:
        def loop():
            while not self._stop.wait(self.interval_seconds):
                self.run_cycle(get_queue, get_running, get_offers)
        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="optimizer-cycler")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
