"""Scheduling optimizer: protocols + the real goodput loop.

Parity with the reference's optimizer subsystem (reference:
scheduler/src/cook/scheduler/optimizer.clj): ``HostFeed``/``Optimizer``
protocols, dummy implementations, a validated ``Schedule`` shape, and a
cycle driver.  The reference left the loop observational (TODO at
mesos.clj:258-267: schedule validated then dropped); this module closes
that gap with :class:`GoodputOptimizer` — the decision plane above the
elastic-gang resize machinery (sched/elastic.py, docs/GANG.md
elasticity):

1. **capture** recent traffic per pool from the live store (waiting +
   recently-submitted jobs, measured durations, elastic gang groups)
   and the pool's real host inventory;
2. **replay** it through ``sim/`` faster than real time, once per
   candidate lever setting (per-pool grow budget x shrink pressure),
   with metric writes suppressed (``registry.suppressed()``) so the
   simulated schedulers never pollute the production exposition;
3. **score** each replay on goodput (busy-capacity fraction + placed
   gang-member fraction) minus an unfairness penalty weighted by the
   LIVE fairness plane (per-user DRU table + wait-phase split,
   docs/OBSERVABILITY.md);
4. **decide** per-pool grow budgets, shrink pressure, a preemption
   budget, and an autoscale target — applied to the scheduler by
   ``Scheduler.step_optimize`` and journaled durably onto every
   affected elastic gang member's audit timeline
   (``optimizer-decision`` events, ``cs why`` renders them).

Factories are config-driven dotted paths, mirroring the reference's
``lazy-load-var`` create-fn loading (optimizer.clj:115-124).
"""

from __future__ import annotations

import importlib
import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

log = logging.getLogger(__name__)


@dataclass(frozen=True)
class HostInfo:
    """A purchasable host class (reference: optimizer.clj HostInfo schema)."""
    count: int
    instance_type: str
    cpus: float
    mem: float
    gpus: Optional[float] = None

    def validate(self) -> None:
        if self.count < 0:
            raise ValueError(f"HostInfo.count must be >= 0, got {self.count}")
        if self.cpus <= 0 or self.mem <= 0:
            raise ValueError("HostInfo cpus/mem must be positive")
        if self.gpus is not None and self.gpus <= 0:
            raise ValueError("HostInfo gpus, when present, must be positive")


class HostFeed:
    """Service producing info on hosts that can be purchased
    (reference: optimizer.clj:33 defprotocol HostFeed)."""

    def get_available_host_info(self) -> List[HostInfo]:
        raise NotImplementedError


class Optimizer:
    """Tool producing a schedule to execute
    (reference: optimizer.clj:57 defprotocol Optimizer).

    ``produce_schedule(queue, running, available, host_infos)`` returns
    ``{millis_in_future: {"suggested-matches": {HostInfo: [job uuids]}}}``.
    """

    def produce_schedule(self, queue: List[Any], running: List[Any],
                         available: List[Any],
                         host_infos: List[HostInfo]) -> Dict:
        raise NotImplementedError


class DummyHostFeed(HostFeed):
    """Returns no purchasable hosts (reference: create-dummy-host-feed)."""

    def __init__(self, config: Optional[Dict] = None):
        self.config = config or {}

    def get_available_host_info(self) -> List[HostInfo]:
        return []


class DummyOptimizer(Optimizer):
    """Returns an empty schedule (reference: create-dummy-optimizer)."""

    def __init__(self, config: Optional[Dict] = None):
        self.config = config or {}

    def produce_schedule(self, queue, running, available, host_infos):
        return {0: {"suggested-matches": {}}}


# ------------------------------------------------------------------ goodput

@dataclass
class PoolDecision:
    """One optimizer cycle's levers for one pool (docs/GANG.md
    elasticity; surfaced on ``GET /debug/optimizer`` and journaled as
    ``optimizer-decision`` audit events on affected gang members)."""

    pool: str
    #: per-cycle grow slots for satisfied elastic gangs; None = unmetered
    grow_budget: Optional[int]
    #: surplus members to shed via the grace protocol this interval
    shrink_pressure: int
    #: dynamic rebalancer ``max_preemption`` suggestion; None = leave the
    #: operator's setting alone
    preemption_budget: Optional[int]
    #: suggested TOTAL host count for the pool (autoscale target; the
    #: legacy Schedule shape carries the delta as a HostInfo suggestion)
    autoscale_hosts: int
    #: winning replay's predicted goodput in [0, ~2] (utilization +
    #: placed-gang-member fraction)
    predicted_goodput: float
    #: the pool's goodput right now (busy capacity fraction)
    current_goodput: float
    objective: float
    replayed_jobs: int
    candidates: int
    #: per-candidate replay scores, for the debug surface
    scores: Dict[str, float] = field(default_factory=dict)

    def to_dict(self) -> Dict:
        return {
            "pool": self.pool, "grow_budget": self.grow_budget,
            "shrink_pressure": self.shrink_pressure,
            "preemption_budget": self.preemption_budget,
            "autoscale_hosts": self.autoscale_hosts,
            "predicted_goodput": round(self.predicted_goodput, 4),
            "current_goodput": round(self.current_goodput, 4),
            "objective": round(self.objective, 4),
            "replayed_jobs": self.replayed_jobs,
            "candidates": self.candidates,
            "scores": {k: round(v, 4) for k, v in self.scores.items()},
        }


_GOODPUT_KEYS = {
    "lookback_seconds", "max_replay_jobs", "max_replay_hosts",
    "replay_horizon_seconds", "grow_budgets", "shrink_pressures",
    "fairness_weight", "preemption_budget_cap", "set_preemption_budget",
    "default_duration_ms",
}


class GoodputOptimizer(Optimizer):
    """The real optimizer loop (module docstring): sim-replay candidate
    grow/shrink lever settings per pool and pick the argmax of
    goodput - fairness penalty.  Config keys are boot-validated
    (unknown keys fail construction, i.e. daemon boot)."""

    def __init__(self, config: Optional[Dict] = None):
        conf = dict(config or {})
        unknown = set(conf) - _GOODPUT_KEYS
        if unknown:
            raise ValueError(
                f"unknown goodput optimizer key(s): {sorted(unknown)}")
        self.config = conf
        self.lookback_seconds = float(conf.get("lookback_seconds", 900.0))
        self.max_replay_jobs = int(conf.get("max_replay_jobs", 200))
        self.max_replay_hosts = int(conf.get("max_replay_hosts", 64))
        self.replay_horizon_seconds = float(
            conf.get("replay_horizon_seconds", 600.0))
        #: candidate per-cycle grow budgets; None = unmetered growth
        self.grow_budgets: List[Optional[int]] = [
            (None if g is None else int(g))
            for g in conf.get("grow_budgets", [0, 2, None])]
        self.shrink_pressures: List[int] = [
            int(s) for s in conf.get("shrink_pressures", [0, 2])]
        self.fairness_weight = float(conf.get("fairness_weight", 0.25))
        self.preemption_budget_cap = int(
            conf.get("preemption_budget_cap", 128))
        self.set_preemption_budget = bool(
            conf.get("set_preemption_budget", True))
        self.default_duration_ms = int(
            conf.get("default_duration_ms", 60_000))
        self.last_decisions: Dict[str, PoolDecision] = {}

    # ------------------------------------------------------- legacy protocol
    def produce_schedule(self, queue, running, available, host_infos):
        """The reference Schedule shape, carrying this loop's autoscale
        suggestions: one HostInfo per pool that wants more hosts, mapped
        to (up to 32 of) the jobs still waiting there."""
        matches: Dict[HostInfo, List] = {}
        for pool, d in self.last_decisions.items():
            extra = d.autoscale_hosts - d.scores.get("_current_hosts", 0)
            if extra <= 0:
                continue
            uuids = [getattr(j, "uuid", j) for j in queue
                     if getattr(j, "pool", pool) == pool][:32]
            matches[HostInfo(count=int(extra),
                             instance_type=f"{pool}-class",
                             cpus=max(d.scores.get("_host_cpus", 8.0), 1.0),
                             mem=max(d.scores.get("_host_mem", 8192.0),
                                     1.0))] = uuids
        return {0: {"suggested-matches": matches}}

    # ----------------------------------------------------------- world build
    def _pool_world(self, scheduler, pool_name: str, now_ms: int):
        """Capture the pool's recent traffic + host inventory as a
        replayable world: plain job entries (rebased submit times,
        measured-or-estimated durations), elastic/gang group specs, and
        FakeHost inventory.  Everything is plain data so each candidate
        replay builds FRESH Job/Group objects."""
        from ..state.schema import InstanceStatus, JobState
        store = scheduler.store
        horizon_ms = self.lookback_seconds * 1000.0
        cutoff = now_ms - horizon_ms

        def keep(j):
            if j.pool != pool_name:
                return False
            if j.state is not JobState.COMPLETED:
                return True
            return (j.submit_time_ms or 0) >= cutoff

        jobs = store.jobs_where(keep)
        jobs.sort(key=lambda j: j.submit_time_ms or 0)
        # gang groups whose members ride the replay (cohort semantics
        # must replay too, or the elastic levers meter nothing)
        group_uuids = {j.group for j in jobs if j.group}
        groups: Dict[str, Dict] = {}
        for guuid in group_uuids:
            g = store.group(guuid)
            if g is not None and getattr(g, "gang", False):
                groups[guuid] = {
                    "gang_size": g.gang_size, "gang_min": g.gang_min,
                    "gang_max": g.gang_max,
                    "gang_topology": g.gang_topology,
                    "gang_policy": g.gang_policy}
        if len(jobs) > self.max_replay_jobs:
            # keep newest, but never split a gang's cohort
            kept = {j.uuid for j in jobs[-self.max_replay_jobs:]}
            kept_groups = {j.group for j in jobs
                           if j.group and j.uuid in kept}
            jobs = [j for j in jobs
                    if j.uuid in kept
                    or (j.group and j.group in kept_groups)]
        t0 = min((j.submit_time_ms or 0) for j in jobs) if jobs else 0
        entries = []
        for j in jobs:
            duration = self._estimate_duration(store, j, now_ms)
            entries.append({
                "uuid": j.uuid, "user": j.user,
                "submit_ms": max(int((j.submit_time_ms or 0) - t0), 0),
                "duration_ms": duration, "group": j.group,
                "cpus": j.resources.cpus, "mem": j.resources.mem,
                "gpus": j.resources.gpus, "priority": j.priority})
        hosts = []
        for cluster in scheduler.clusters.values():
            if not cluster.accepts_pool(pool_name):
                continue
            for offer in cluster.hosts(pool_name):
                hosts.append({
                    "hostname": offer.hostname,
                    "cpus": offer.capacity.cpus,
                    "mem": offer.capacity.mem,
                    "gpus": offer.capacity.gpus,
                    "attributes": dict(offer.attributes)})
                if len(hosts) >= self.max_replay_hosts:
                    break
            if len(hosts) >= self.max_replay_hosts:
                break
        return entries, groups, hosts

    def _estimate_duration(self, store, job, now_ms: int) -> int:
        """Measured duration when the job ran; elapsed-so-far for
        running jobs (a lower bound is honest enough for replay);
        config default otherwise."""
        best = None
        for tid in job.instances:
            inst = store.instance(tid)
            if inst is None or not inst.start_time_ms:
                continue
            if inst.end_time_ms:
                best = max(best or 0, inst.end_time_ms - inst.start_time_ms)
            else:
                best = max(best or 0, now_ms - inst.start_time_ms)
        d = int(best) if best else self.default_duration_ms
        return max(d, 100)

    # --------------------------------------------------------------- replay
    def _replay(self, entries: List[Dict], groups: Dict[str, Dict],
                hosts: List[Dict], grow: Optional[int],
                shrink: int) -> Dict[str, float]:
        """One candidate replay: fresh world, levers applied, metrics
        suppressed, scored.  Returns the replay measurements."""
        from ..config import Config
        from ..sim.simulator import Simulator, load_hosts
        from ..state.schema import Group, Job, Resources
        from ..utils.metrics import registry

        jobs = [Job(uuid=e["uuid"], user=e["user"], command="replay",
                    resources=Resources(cpus=e["cpus"], mem=e["mem"],
                                        gpus=e["gpus"]),
                    priority=e["priority"], group=e["group"],
                    submit_time_ms=e["submit_ms"],
                    labels={"sim/duration_ms": str(e["duration_ms"])})
                for e in entries]
        jobs.sort(key=lambda j: j.submit_time_ms)
        members: Dict[str, List[str]] = {}
        for j in jobs:
            if j.group in groups:
                members.setdefault(j.group, []).append(j.uuid)
        gang_groups = {
            guuid: Group(uuid=guuid, gang=True,
                         gang_size=g["gang_size"] or len(members[guuid]),
                         gang_min=g["gang_min"], gang_max=g["gang_max"],
                         gang_topology=g["gang_topology"],
                         gang_policy=g["gang_policy"],
                         jobs=list(members[guuid]))
            for guuid, g in groups.items() if guuid in members}
        cfg = Config()
        cfg.elastic.shrink_grace_seconds = 0.0  # replay sheds immediately
        sim = Simulator(jobs, load_hosts(hosts), config=cfg,
                        backend="cpu", groups=gang_groups)
        if grow is not None:
            sim.scheduler.elastic.grow_budget["default"] = float(grow)
        if shrink:
            sim.scheduler.elastic.shrink_pressure["default"] = int(shrink)
        with registry.suppressed():
            res = sim.run(max_virtual_ms=int(
                self.replay_horizon_seconds * 1000))
        m = dict(res.goodput)
        m["wait_unfairness"] = self._wait_unfairness(res)
        m["completed"] = res.completed
        return m

    @staticmethod
    def _wait_unfairness(res) -> float:
        """Spread of per-user mean wait, normalized by the overall mean
        — the replay-side fairness term the live DRU bias weights."""
        import numpy as np
        by_user: Dict[str, List[float]] = {}
        for r in res.task_records:
            if r.get("wait_ms") is not None:
                by_user.setdefault(r["user"], []).append(r["wait_ms"])
        if len(by_user) < 2:
            return 0.0
        means = np.array([float(np.mean(v)) for v in by_user.values()])
        overall = float(np.mean(means))
        if overall <= 0:
            return 0.0
        return float(np.std(means)) / overall

    # --------------------------------------------------------------- decide
    def optimize(self, scheduler) -> Dict[str, PoolDecision]:
        """One full decision cycle over every active pool (module
        docstring steps 1-4; application/journaling is the scheduler's
        ``step_optimize``)."""
        store = scheduler.store
        now_ms = store.clock()
        decisions: Dict[str, PoolDecision] = {}
        for pool in store.pools():
            if pool.state != "active":
                continue
            d = self._optimize_pool(scheduler, pool.name, now_ms)
            if d is not None:
                decisions[pool.name] = d
        self.last_decisions = decisions
        return decisions

    def _optimize_pool(self, scheduler, pool_name: str,
                       now_ms: int) -> Optional[PoolDecision]:
        entries, groups, hosts = self._pool_world(
            scheduler, pool_name, now_ms)
        if not entries or not hosts:
            return None
        # POOL-LOCAL elastic presence: only pools whose own replay world
        # carries an elastic gang pay the candidate sweep — the levers
        # meter nothing anywhere else
        elastic_present = any(
            not ((g["gang_min"] or g["gang_size"])
                 == (g["gang_max"] or g["gang_size"])
                 == g["gang_size"])
            for g in groups.values())
        # the LIVE fairness plane biases the penalty: users over share
        # (DRU >= 1) mean unfair replays should hurt more
        dru = scheduler.store.audit.user_dru_table(pool_name)
        over_share = sum(1 for v in dru.values() if v >= 1.0)
        fairness_bias = 1.0 + (over_share / len(dru) if dru else 0.0)
        if elastic_present:
            candidates: List[Tuple[Optional[int], int]] = [
                (g, s) for g in self.grow_budgets
                for s in self.shrink_pressures]
            # evaluation order doubles as the tie-break: strict > below
            # keeps the FIRST of equal scores, and equal goodput should
            # keep the least-restrictive levers (unmetered growth, no
            # pressure), not freeze growth for nothing
            candidates.sort(key=lambda c: (
                0 if c[0] is None else 1, -(c[0] or 0), c[1]))
        else:
            # nothing to meter: a single baseline replay still yields
            # the autoscale/preemption decision
            candidates = [(None, 0)]
        best = None
        scores: Dict[str, float] = {}
        for grow, shrink in candidates:
            try:
                m = self._replay(entries, groups, hosts, grow, shrink)
            except Exception:
                log.exception("optimizer replay failed (pool=%s grow=%s "
                              "shrink=%s)", pool_name, grow, shrink)
                continue
            goodput = m.get("util", 0.0) + m.get("gang_goodput", 0.0)
            obj = goodput - self.fairness_weight * fairness_bias \
                * m.get("wait_unfairness", 0.0)
            scores[f"grow={grow},shrink={shrink}"] = obj
            if best is None or obj > best[0]:
                best = (obj, grow, shrink, m)
        if best is None:
            return None
        obj, grow, shrink, m = best
        current = self._current_goodput(scheduler, pool_name)
        n_hosts = len(hosts)
        host_cpus = (sum(h["cpus"] for h in hosts) / n_hosts) or 1.0
        # autoscale: capacity to absorb the replay's never-placed demand
        unplaced = m.get("unplaced_cpus", 0.0)
        extra_hosts = int(unplaced // host_cpus) if unplaced > 0 else 0
        # preemption budget: only when the live plane shows users over
        # share AND the winning replay still preempted under pressure
        budget = None
        if self.set_preemption_budget and over_share \
                and m.get("preemptions", 0) > 0:
            budget = min(int(m["preemptions"]) * 2,
                         self.preemption_budget_cap)
        scores["_current_hosts"] = float(n_hosts)
        scores["_host_cpus"] = host_cpus
        scores["_host_mem"] = (sum(h["mem"] for h in hosts) / n_hosts) or 1.0
        return PoolDecision(
            pool=pool_name, grow_budget=grow, shrink_pressure=shrink,
            preemption_budget=budget,
            autoscale_hosts=n_hosts + extra_hosts,
            predicted_goodput=m.get("util", 0.0) + m.get("gang_goodput", 0.0),
            current_goodput=current, objective=obj,
            replayed_jobs=len(entries), candidates=len(candidates),
            scores=scores)

    @staticmethod
    def _current_goodput(scheduler, pool_name: str) -> float:
        """Busy-capacity fraction right now, from the pool's offers."""
        cap = busy = 0.0
        for cluster in scheduler.clusters.values():
            if not cluster.accepts_pool(pool_name):
                continue
            for offer in cluster.hosts(pool_name):
                cap += offer.capacity.cpus
                busy += max(offer.capacity.cpus - offer.available.cpus, 0.0)
        return busy / cap if cap > 0 else 0.0


def validate_schedule(schedule: Dict) -> None:
    """Structural validation of a Schedule (reference: optimizer.clj Schedule
    schema + s/validate at :111)."""
    if not isinstance(schedule, dict):
        raise ValueError("schedule must be a dict of time-period -> step")
    for period_ms, step in schedule.items():
        if not isinstance(period_ms, int) or period_ms < 0:
            raise ValueError(f"schedule key {period_ms!r} is not a "
                             "non-negative integer of millis-in-future")
        if not isinstance(step, dict) or "suggested-matches" not in step:
            raise ValueError(f"schedule step at {period_ms} is missing "
                             "'suggested-matches'")
        matches = step["suggested-matches"]
        if not isinstance(matches, dict):
            raise ValueError("suggested-matches must map HostInfo -> [uuid]")
        for host_info, uuids in matches.items():
            if not isinstance(host_info, HostInfo):
                raise ValueError(f"suggested-matches key {host_info!r} is "
                                 "not a HostInfo")
            host_info.validate()
            if not isinstance(uuids, (list, tuple)):
                raise ValueError("suggested-matches values must be lists of "
                                 "job uuids")


def optimizer_cycle(get_queue: Callable[[], List[Any]],
                    get_running: Callable[[], List[Any]],
                    get_offers: Callable[[], List[Any]],
                    host_feed: HostFeed,
                    optimizer: Optimizer) -> Dict:
    """One optimizer cycle (reference: optimizer-cycle! optimizer.clj:90-113):
    gather queue/running/host info, produce a schedule, validate it."""
    queue = get_queue()
    running = get_running()
    # Offer integration with pools is not implemented in the reference
    # either (optimizer.clj:106); pass the empty set for parity.
    available: List[Any] = []
    host_infos = host_feed.get_available_host_info()
    for info in host_infos:
        if not isinstance(info, HostInfo):
            raise ValueError(f"host feed produced non-HostInfo {info!r}")
        info.validate()
    schedule = optimizer.produce_schedule(queue, running, available,
                                          host_infos)
    validate_schedule(schedule)
    return schedule


def _load_factory(dotted: str) -> Callable:
    """Resolve 'pkg.module.fn' (reference: lazy-load-var)."""
    module_name, _, attr = dotted.rpartition(".")
    if not module_name:
        raise ValueError(f"factory path {dotted!r} must be module.attr")
    return getattr(importlib.import_module(module_name), attr)


@dataclass
class OptimizerConfig:
    """Config-driven construction (reference: start-optimizer-cycles!
    construct, optimizer.clj:118-123).  The default optimizer is the
    REAL :class:`GoodputOptimizer` loop; the dummies remain for parity
    tests and as explicit opt-outs.  ``interval_seconds`` is validated
    at build time: the cycler's wait loop divides work by it, and a
    non-positive interval would spin or never fire."""
    host_feed_create_fn: str = "cook_tpu.sched.optimizer.DummyHostFeed"
    host_feed_config: Dict = field(default_factory=dict)
    optimizer_create_fn: str = "cook_tpu.sched.optimizer.GoodputOptimizer"
    optimizer_config: Dict = field(default_factory=dict)
    interval_seconds: float = 30.0

    def __post_init__(self):
        if float(self.interval_seconds) <= 0:
            raise ValueError("optimizer interval_seconds must be > 0, "
                             f"got {self.interval_seconds!r}")

    @classmethod
    def from_conf(cls, conf: Dict) -> "OptimizerConfig":
        """Boot-validated daemon conf section (daemon.py "optimizer"):
        unknown keys and a non-positive interval fail the boot, like the
        replication/pipeline/serving/partitions sections around it."""
        cfg = cls()
        for k, v in (conf or {}).items():
            if not hasattr(cfg, k):
                raise ValueError(f"unknown optimizer key {k!r}")
            setattr(cfg, k, type(getattr(cfg, k))(v)
                    if not isinstance(getattr(cfg, k), dict) else dict(v))
        cfg.__post_init__()
        # factory construction validates the nested optimizer_config
        # (GoodputOptimizer rejects unknown keys) at boot, not first use
        cfg.build()
        return cfg

    def build(self) -> "OptimizerCycler":
        self.__post_init__()
        host_feed = _load_factory(self.host_feed_create_fn)(
            self.host_feed_config)
        optimizer = _load_factory(self.optimizer_create_fn)(
            self.optimizer_config)
        return OptimizerCycler(host_feed, optimizer, self.interval_seconds)


class OptimizerCycler:
    """Periodic driver (reference: start-optimizer-cycles! optimizer.clj:115).
    Errors are logged-and-swallowed per cycle, matching the reference's
    error-handler."""

    def __init__(self, host_feed: HostFeed, optimizer: Optimizer,
                 interval_seconds: float = 30.0):
        self.host_feed = host_feed
        self.optimizer = optimizer
        self.interval_seconds = interval_seconds
        self.last_schedule: Optional[Dict] = None
        self.last_error: Optional[Exception] = None
        self.cycles = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def run_cycle(self, get_queue, get_running,
                  get_offers=lambda: [], _observe: bool = True
                  ) -> Optional[Dict]:
        from ..utils.metrics import registry
        t0 = time.perf_counter()
        try:
            self.last_schedule = optimizer_cycle(
                get_queue, get_running, get_offers,
                self.host_feed, self.optimizer)
            self.last_error = None
        except Exception as e:
            log.warning("Error running optimizer cycle", exc_info=e)
            self.last_error = e
            return None
        finally:
            self.cycles += 1
            if _observe:
                registry.observe("cook_optimizer_cycle_seconds",
                                 time.perf_counter() - t0)
        return self.last_schedule

    def run_scheduler_cycle(self, scheduler) -> Dict[str, "PoolDecision"]:
        """One full cycle against a live scheduler: the goodput decision
        pass first (when the optimizer implements ``optimize``), then
        the legacy observational schedule — which for
        :class:`GoodputOptimizer` renders the fresh decisions' autoscale
        suggestions.  Decision application/journaling stays with the
        caller (``Scheduler.step_optimize``)."""
        from ..utils.metrics import registry
        t0 = time.perf_counter()
        decisions: Dict[str, PoolDecision] = {}
        if hasattr(self.optimizer, "optimize"):
            try:
                decisions = self.optimizer.optimize(scheduler) or {}
            except Exception as e:
                log.warning("Error running goodput decision pass",
                            exc_info=e)
                self.last_error = e
                self.cycles += 1
                registry.observe("cook_optimizer_cycle_seconds",
                                 time.perf_counter() - t0)
                return {}

        def get_queue():
            return [j for q in scheduler.pending_queues.values()
                    for j in q]

        def get_running():
            return scheduler.store.running_instances()

        self.run_cycle(get_queue, get_running, _observe=False)
        registry.observe("cook_optimizer_cycle_seconds",
                         time.perf_counter() - t0)
        return decisions

    def start(self, get_queue, get_running, get_offers=lambda: []) -> None:
        def loop():
            # first cycle IMMEDIATELY: waiting a full interval before
            # cycle 1 left last_schedule None for interval_seconds after
            # every boot (the /debug/optimizer surface read as dead)
            self.run_cycle(get_queue, get_running, get_offers)
            while not self._stop.wait(self.interval_seconds):
                self.run_cycle(get_queue, get_running, get_offers)
        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="optimizer-cycler")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
