"""Match cycle: ranked queue x cluster offers -> launched tasks.

Host half of the reference's match path (reference: handle-fenzo-pool
scheduler.clj:1554, handle-resource-offers! :1339, launch-matched-tasks!
:1028) around the batched match kernels:

  considerable selection (quota filter + cap)  -> constraint mask compile
  -> kernel dispatch (greedy / auction / cpu)  -> within-batch group check
  -> transactional launch guard                -> cluster launch under
                                                  kill-lock read side

Head-of-queue fairness backoff is preserved host-side
(scheduler.clj:1613-1651): while the head of the queue can't match, the
number of considerable jobs shrinks so the cheap tail can't starve it.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..cluster.base import ComputeCluster, LaunchSpec, Offer
from ..config import Config, MatcherConfig
from ..ops import host_prep, reference_impl
from ..ops import telemetry
from ..state.schema import InstanceStatus, Job, Reasons, new_uuid
from ..state.store import Store
from ..utils import audit, tracing
from ..utils.flight import recorder as flight_recorder
from ..utils.metrics import LATENCY_BUCKETS, registry
from .constraints import (
    LOCATION_ATTRIBUTE,
    ConstraintContext,
    build_constraint_mask,
    validate_group_placement,
)

F32 = np.float32


@dataclass
class MatchCycleResult:
    considered: int = 0
    matched: List[Tuple[Job, Offer]] = field(default_factory=list)
    launched_task_ids: List[str] = field(default_factory=list)
    launched_job_uuids: List[str] = field(default_factory=list)
    unmatched: List[Job] = field(default_factory=list)
    head_matched: bool = True
    launch_failures: List[Tuple[str, str]] = field(default_factory=list)
    # True when the producer already removed this cycle's launches from the
    # pool's pending queue (the fused driver prunes by exact queue position;
    # the scheduler's generic isin-based prune then skips the pool)
    queue_pruned: bool = False
    # gang group uuid -> {"size", "matched", "missing",
    # "topology_blocked"} for gangs that could not place whole this
    # cycle (ops/gang.py; feeds the unscheduled explainer's
    # "waiting on N gang members" reason, docs/GANG.md)
    gang_partial: Dict[str, Dict] = field(default_factory=dict)


class _BackoffState:
    """Per-pool num-considerable backoff (scheduler.clj:1613-1651)."""

    def __init__(self, cap: int):
        self.num_considerable = cap
        self.floor_iterations = 0

    def update(self, mc: MatcherConfig, head_matched: bool) -> None:
        if head_matched:
            self.num_considerable = mc.max_jobs_considered
            self.floor_iterations = 0
        else:
            shrunk = int(self.num_considerable * mc.scaleback)
            self.num_considerable = max(1, shrunk)
            if self.num_considerable == 1:
                self.floor_iterations += 1
                if self.floor_iterations >= mc.floor_iterations_before_reset:
                    self.num_considerable = mc.max_jobs_considered
                    self.floor_iterations = 0


class Matcher:
    def __init__(self, store: Store, config: Config, plugins=None,
                 rate_limits=None):
        from ..policy import PluginRegistry, RateLimits
        self.store = store
        self.config = config
        self.plugins = plugins or PluginRegistry()
        self.rate_limits = rate_limits or RateLimits()
        self._backoff: Dict[str, _BackoffState] = {}
        # pool -> {group uuid -> {"size", "reason"}} for gangs deferred at
        # ADMISSION (before any match ran): the unscheduled-jobs explainer
        # reads this — such gangs never reach the match pass, so they have
        # no gang_partial entry to explain them
        self.last_admission_deferred: Dict[str, Dict[str, Dict]] = {}
        # elastic resize plane (sched/elastic.ElasticManager, set by the
        # scheduler): meters grow admissions of satisfied elastic gangs
        # by the optimizer's per-pool budget.  None = unmetered growth.
        self.elastic = None
        # adaptive-admission controller (sched/admission.py, set by the
        # scheduler): its 0-1 level also scales the considerable window,
        # so a browned-out cell stops paying full-queue match work.
        # None = no admission throttle.
        self.admission = None

    def admission_limit(self, pool_name: str, ranked: List[Job],
                        limit: int) -> int:
        """Scale the considerable window by the admission level and
        attribute the cut (bounded by the window, never [T]-sized) as
        ``admission-throttled`` skips — `cs why` answers "why is my job
        waiting" during brownout from the same audit lane as every
        other throttle."""
        ctrl = self.admission
        if ctrl is None or ctrl.level >= 1.0 or limit <= 1:
            return limit
        scaled = max(1, int(limit * ctrl.level))
        if scaled < limit:
            cut = ranked[scaled:limit]
            if cut:
                from ..utils import audit as _audit
                _audit.note_skips(self.store.audit, {
                    "admission-throttled": [
                        (j.uuid, {"level": round(ctrl.level, 3),
                                  "stage": ctrl.stage})
                        for j in cut]}, pool=pool_name)
        return scaled

    # ------------------------------------------------------------ selection
    def considerable_jobs(self, pool_name: str, ranked: List[Job],
                          limit: int) -> List[Job]:
        """Quota-filtered prefix of the ranked queue (reference:
        pending-jobs->considerable-jobs scheduler.clj:729: usage of running
        jobs + jobs earlier in the queue must stay below the user's quota;
        the accumulator includes skipped jobs, tools.clj:899-915)."""
        if limit <= 0:
            return []
        from ..policy import pool_user_key
        launch_rl = self.rate_limits.job_launch
        usage: Dict[str, np.ndarray] = {}
        for job, _inst in self.store.running_instances(pool_name):
            u = usage.setdefault(job.user, np.zeros(4, dtype=F32))
            u += [job.resources.cpus, job.resources.mem, job.resources.gpus, 1.0]
        out: List[Job] = []
        user_tokens: Dict[str, float] = {}
        user_seen: Dict[str, int] = {}
        # gang-cohort admission (docs/GANG.md): an all-or-nothing gang
        # whose members cannot ALL clear this cycle's throttles would
        # otherwise admit a partial cohort every cycle — matched, then
        # reset by the reduction, forever.  A gang's FIRST member decides
        # for the whole cohort: enough rate-limit tokens for the cohort
        # size and enough room under the considerable cap, or every
        # member waits this cycle (tokens refill; the cap resets).
        # ELASTIC gangs (docs/GANG.md elasticity) reserve only gang_min
        # — members beyond the cohort admit as surplus SINGLES, and a
        # gang already running at >= min (admission size 0) routes its
        # waiting members straight to the grow path below.
        gang_size_of: Dict[str, int] = {}
        gang_deferred: set = set()
        gang_reserved: set = set()
        # groups whose cohort reservation was fully consumed: later
        # members of the same (elastic) gang are surplus singles
        gang_cohort_done: set = set()
        if self.elastic is not None:
            self.elastic.start_pool_cycle(pool_name)
        # outstanding considerable-cap slots held for admitted gangs whose
        # later members have not been reached yet (group -> remaining);
        # singles must not eat a sibling's slot mid-cohort
        slots_reserved: Dict[str, int] = {}
        # a gang whose full cohort is not even in this cycle's ranked
        # queue (a member completed, or was ranked out) can never fully
        # admit — defer it outright instead of reserving slots it will
        # strand for the rest of the scan
        ranked_members: Dict[str, int] = {}
        for job in ranked:
            if job.group is not None:
                ranked_members[job.group] = \
                    ranked_members.get(job.group, 0) + 1
        # head-of-line skip reasons for the cycle's flight record AND the
        # per-job audit lanes: reason -> [uuid | (uuid, extra)], so the
        # aggregate histogram and the per-job attribution come from ONE
        # structure (utils/audit.note_skips; attribution parity)
        skips: Dict[str, List] = {}

        def _skip(reason: str, job, **extra) -> None:
            skips.setdefault(reason, []).append(
                (job.uuid, extra) if extra else job.uuid)
        # group uuid -> why the cohort was withheld, for the explainer
        deferred_why: Dict[str, Dict] = {}

        def _defer(group: str, reason: str) -> None:
            gang_deferred.add(group)
            deferred_why.setdefault(group, {
                "size": gang_size_of.get(group, 0), "reason": reason})

        def _sink_cohort(job, cohort: int, reason: str) -> None:
            """A member denial sinks its whole cohort: defer the gang,
            release its token/slot reservation (nothing from it launches,
            so a later same-user single may use them), and strip
            already-admitted siblings."""
            _defer(job.group, reason)
            slots_reserved.pop(job.group, None)
            if launch_rl.enforce and job.group in gang_reserved:
                user_seen[job.user] = max(
                    user_seen.get(job.user, 0) - cohort, 0)
            stripped = [j for j in out if j.group == job.group]
            if stripped:
                out[:] = [j for j in out if j.group != job.group]
                for j in stripped:
                    _skip("gang-deferred", j, why=reason)

        # group uuid -> is-a-gang, for the grow path (admission size 0
        # covers both plain groups and SATISFIED elastic gangs; only the
        # latter are metered by the optimizer's grow budget)
        gang_flag: Dict[str, bool] = {}
        # growth headroom left per elastic gang this cycle (gang_max -
        # live - the cohort reserved here): surplus singles and grow
        # members consume it so a gang never admits past its declared
        # maximum (docs/GANG.md elasticity)
        gang_headroom: Dict[str, float] = {}

        def _growth_headroom(group: str) -> float:
            h = gang_headroom.get(group)
            if h is None:
                h = self.store.gang_growth_headroom(group) \
                    - gang_size_of.get(group, 0)
                gang_headroom[group] = h = max(h, 0.0)
            return h

        for job in ranked:
            cohort = 1
            if job.group is not None:
                size = gang_size_of.get(job.group)
                if size is None:
                    # cohort size the admission must reserve: gang_size
                    # for rigid gangs, gang_min for unsatisfied elastic
                    # ones, 0 once an elastic gang runs satisfied (its
                    # members grow like singles, docs/GANG.md)
                    size = self.store.gang_admission_size(job.group)
                    gang_size_of[job.group] = size
                if size:
                    if job.group not in gang_deferred \
                            and ranked_members.get(job.group, 0) < size:
                        _defer(job.group, "members-missing")
                    if job.group in gang_deferred:
                        _skip("gang-deferred", job)
                        continue
                    if job.group in gang_cohort_done:
                        # elastic surplus single beyond the cohort:
                        # capped by the gang's growth headroom
                        if _growth_headroom(job.group) < 1:
                            _skip("gang-at-max", job)
                            continue
                        gang_headroom[job.group] -= 1
                        cohort = 1
                    else:
                        cohort = size
                else:
                    is_gang = gang_flag.get(job.group)
                    if is_gang is None:
                        is_gang = self.store.group_is_gang(job.group)
                        gang_flag[job.group] = is_gang
                    if is_gang:
                        # satisfied elastic gang: the member grows like
                        # a single — capped at gang_max, then metered
                        # by the optimizer's per-pool grow budget
                        if _growth_headroom(job.group) < 1:
                            _skip("gang-at-max", job)
                            continue
                        if self.elastic is not None \
                                and not self.elastic.admit_grow(pool_name):
                            _skip("gang-grow-deferred", job)
                            continue
                        gang_headroom[job.group] -= 1
            quota = self.store.get_quota(job.user, pool_name)
            qvec = np.array([quota.get("cpus", np.inf), quota.get("mem", np.inf),
                             quota.get("gpus", np.inf), quota.get("count", np.inf)],
                            dtype=F32)
            u = usage.setdefault(job.user, np.zeros(4, dtype=F32))
            u += [job.resources.cpus, job.resources.mem, job.resources.gpus, 1.0]
            if not np.all(u <= qvec):
                _skip("over-quota", job)
                if cohort > 1:
                    _sink_cohort(job, cohort, "member-denied")
                continue
            # gang cohort reservation: the FIRST member clears both the
            # considerable cap and the per-user launch-rate tokens for the
            # WHOLE cohort and reserves them (reference:
            # filter-pending-jobs-for-ratelimit tools.clj:940-970, extended
            # to cohorts); siblings ride the reservation with no per-member
            # check.  A gang straddling either budget defers whole —
            # admitting partial would match, then burn on the reduction
            # every cycle.
            if cohort > 1 and job.group not in gang_reserved:
                if len(out) + sum(slots_reserved.values()) + cohort > limit:
                    _defer(job.group, "considerable-cap")
                    _skip("gang-deferred", job, why="considerable-cap")
                    continue
                if launch_rl.enforce:
                    tokens = user_tokens.setdefault(
                        job.user,
                        launch_rl.get_token_count(
                            pool_user_key(pool_name, job.user)))
                    seen = user_seen.get(job.user, 0)
                    if seen + cohort > int(tokens):
                        _defer(job.group, "rate-limited")
                        _skip("gang-deferred", job, why="rate-limited")
                        continue
                    user_seen[job.user] = seen + cohort
                gang_reserved.add(job.group)
                slots_reserved[job.group] = cohort
            elif cohort == 1:
                # per-user-per-pool launch rate limit: each user passes at
                # most token-count jobs per cycle; the accumulator includes
                # skipped jobs
                if launch_rl.enforce:
                    tokens = user_tokens.setdefault(
                        job.user,
                        launch_rl.get_token_count(
                            pool_user_key(pool_name, job.user)))
                    seen = user_seen.get(job.user, 0)
                    user_seen[job.user] = seen + 1
                    if seen >= int(tokens):
                        # a fractional token is not a launch
                        _skip("rate-limited", job)
                        continue
                # singles fill remaining slots but never the ones held
                # for a reserved gang's unseen members
                if slots_reserved and \
                        len(out) + sum(slots_reserved.values()) >= limit:
                    _skip("cap-reserved", job)
                    continue
            # launch-filter plugin with cached accept/defer verdicts
            if not self.plugins.launch_allowed(job):
                _skip("launch-filtered", job)
                if cohort > 1:
                    _sink_cohort(job, cohort, "member-denied")
                continue
            out.append(job)
            if cohort > 1:
                rem = slots_reserved.get(job.group, 0) - 1
                if rem > 0:
                    slots_reserved[job.group] = rem
                else:
                    slots_reserved.pop(job.group, None)
                    # an elastic gang's members past the reserved
                    # cohort admit as surplus singles (rigid gangs
                    # never have extra ranked members to reach this)
                    gang_cohort_done.add(job.group)
            if len(out) >= limit:
                break
        # hard cohort guarantee: a gang that did not FULLY admit (a
        # launch filter denied one member, or the cap's break landed
        # mid-cohort behind same-rank fillers) is withheld whole — a
        # partial cohort would match and then be reset by the reduction
        # every cycle, burning capacity forever
        if gang_size_of and any(gang_size_of.values()):
            admitted: Dict[str, int] = {}
            for j in out:
                if j.group is not None and gang_size_of.get(j.group):
                    admitted[j.group] = admitted.get(j.group, 0) + 1
            short = {g for g, n in admitted.items()
                     if n < gang_size_of[g]}
            if short:
                for j in out:
                    if j.group in short:
                        _skip("gang-deferred", j, why="partial-admission")
                out = [j for j in out if j.group not in short]
                for g in short:
                    deferred_why.setdefault(g, {
                        "size": gang_size_of.get(g, 0),
                        "reason": "partial-admission"})
        self.last_admission_deferred[pool_name] = deferred_why
        if skips:
            audit.note_skips(self.store.audit, skips, pool=pool_name)
        return out

    # -------------------------------------------------------------- context
    def _constraint_context(self, jobs: List[Job],
                            reserved_hosts: Optional[Dict[str, str]] = None
                            ) -> ConstraintContext:
        ec = self.config.estimated_completion
        ec_on = (ec.expected_runtime_multiplier is not None
                 and ec.host_lifetime_mins is not None)
        ctx = ConstraintContext(
            reserved_hosts=dict(reserved_hosts or {}),
            max_tasks_per_host=self.config.max_tasks_per_host,
            host_lifetime_mins=ec.host_lifetime_mins if ec_on else None)
        for job in jobs:
            full = self.store.job(job.uuid)
            if full is None:
                continue
            failed = set()
            node_lost_runtimes = [0.0]
            for tid in full.instances:
                inst = self.store.instance(tid)
                if inst is not None and inst.status is InstanceStatus.FAILED:
                    # a launch cancelled before the backend ever saw it
                    # (crash-window refund, reconcile sweep) proves nothing
                    # about the host; novel-host-excluding it would livelock
                    # single-host relaunches after a leader crash.  Same
                    # for a gang-policy sibling kill (gang-member-lost):
                    # the host did nothing wrong and the gang NEEDS it to
                    # relaunch whole (docs/GANG.md)
                    if inst.reason_code not in (
                            Reasons.CANCELLED_DURING_LAUNCH.code,
                            Reasons.GANG_MEMBER_LOST.code):
                        failed.add(inst.hostname)
                    if (inst.reason_code == Reasons.NODE_LOST.code
                            and inst.end_time_ms and inst.start_time_ms):
                        node_lost_runtimes.append(
                            inst.end_time_ms - inst.start_time_ms)
            if failed:
                ctx.failed_hosts[job.uuid] = failed
            # checkpoint locality: a restarted checkpointed job is pinned to
            # the location its previous instance ran in (reference:
            # constraints.clj:218-240); the location was snapshotted from the
            # offer at launch time (Instance.node_location)
            if full.checkpoint is not None:
                for tid in reversed(full.instances):
                    prior = self.store.instance(tid)
                    if prior is not None and prior.node_location:
                        ctx.checkpoint_locations[full.uuid] = \
                            prior.node_location
                        break
            # estimated-completion end time: max of scaled expected runtime
            # and prior node-lost runtimes, capped so a job that nearly fills
            # a host lifetime still accepts young hosts
            # (build-estimated-completion-constraint, constraints.clj:408)
            if ec_on:
                expected = (full.expected_runtime_ms or 0) \
                    * ec.expected_runtime_multiplier
                max_expected = max([expected] + node_lost_runtimes)
                if max_expected > 0:
                    longest = (ec.host_lifetime_mins
                               - ec.agent_start_grace_period_mins) * 60_000
                    ctx.estimated_end_ms[job.uuid] = int(
                        self.store.clock() + min(max_expected, longest))
            if job.group:
                group = self.store.group(job.group)
                if group is not None and job.group not in ctx.groups:
                    ctx.groups[job.group] = group
                    # list, not set: BALANCED frequencies count cotasks per
                    # host with multiplicity
                    hosts = []
                    for member_uuid in group.jobs:
                        member = self.store.job(member_uuid)
                        if member is None:
                            continue
                        for tid in member.instances:
                            inst = self.store.instance(tid)
                            if inst is not None and inst.status in (
                                    InstanceStatus.UNKNOWN, InstanceStatus.RUNNING):
                                hosts.append(inst.hostname)
                    if hosts:
                        ctx.group_running_hosts[job.group] = hosts
        return ctx

    def _fill_cotask_host_attributes(self, ctx: ConstraintContext,
                                     pool_name: str, offers: List[Offer],
                                     clusters: Dict[str, ComputeCluster]
                                     ) -> None:
        """Attribute maps for running-cotask hosts that are NOT in the offer
        set (fully-packed hosts emit no offer): without them, balanced /
        attribute-equals groups would silently ignore those cotasks."""
        needed = {hn for hosts in ctx.group_running_hosts.values()
                  for hn in hosts}
        needed -= {o.hostname for o in offers}
        if not needed:
            return
        for cluster in clusters.values():
            try:
                all_hosts = cluster.hosts(pool_name)
            except Exception:
                continue
            for h in all_hosts:
                if h.hostname in needed:
                    ctx.host_attributes[h.hostname] = h.attributes

    # ----------------------------------------------------------------- match
    def match_pool(self, pool_name: str, ranked: List[Job],
                   offers: List[Offer],
                   clusters: Dict[str, ComputeCluster],
                   reserved_hosts: Optional[Dict[str, str]] = None
                   ) -> MatchCycleResult:
        mc = self.config.matcher_for_pool(pool_name)
        backoff = self._backoff.setdefault(
            pool_name, _BackoffState(mc.max_jobs_considered))
        result = MatchCycleResult()
        limit = self.admission_limit(
            pool_name, ranked, min(backoff.num_considerable,
                                   mc.max_jobs_considered))
        considerable = self.considerable_jobs(pool_name, ranked, limit)
        result.considered = len(considerable)
        # per-job rank attribution for the admitted candidates (bounded
        # by the considerable cap): queue position this cycle + the
        # user's cached DRU (utils/audit.py)
        self.store.audit.ranked(
            [j.uuid for j in considerable], range(len(considerable)),
            pool_name, users=[j.user for j in considerable])
        if not considerable or not offers:
            result.unmatched = considerable
            # an empty cycle leaves the backoff state untouched
            return result

        ctx = self._constraint_context(considerable, reserved_hosts)
        self._fill_cotask_host_attributes(ctx, pool_name, offers, clusters)
        cmask = build_constraint_mask(considerable, offers, ctx)
        job_res = [[j.resources.cpus, j.resources.mem, j.resources.gpus,
                    j.resources.disk] for j in considerable]
        avail = [[o.available.cpus, o.available.mem, o.available.gpus,
                  o.available.disk] for o in offers]
        cap = [[o.capacity.cpus, o.capacity.mem, o.capacity.gpus,
                o.capacity.disk] for o in offers]

        with tracing.span("match.schedule-once", pool=pool_name,
                          backend=self.resolve_backend(mc, len(considerable)),
                          jobs=len(considerable), offers=len(offers)):
            assign = self._dispatch(mc, job_res, cmask, avail, cap)
            assign = validate_group_placement(considerable, assign, offers, ctx)
            # gang all-or-nothing reduction + same-cycle refill of the
            # freed capacity (structural no-op without gang members);
            # satisfied elastic gangs' waiting members bypass the
            # reduction — they are the grow path (docs/GANG.md)
            from ..ops.gang import apply_gang_cycle
            from .elastic import satisfied_gangs
            assign, gstats = apply_gang_cycle(
                considerable, assign, offers, ctx.groups,
                job_res=np.asarray(job_res, dtype=F32),
                cmask_fn=lambda: cmask,
                avail=np.asarray(avail, dtype=F32),
                capacity=np.asarray(cap, dtype=F32),
                device=mc.backend != "cpu",
                audit_trail=self.store.audit, audit_pool=pool_name,
                satisfied=satisfied_gangs(self.store, ctx.groups))
            if gstats is not None:
                result.gang_partial = gstats.partial
        self.record_placement_failures(considerable, assign, offers, ctx)

        # head-of-queue backoff bookkeeping
        result.head_matched = bool(assign[0] >= 0)
        backoff.update(mc, result.head_matched)

        for j, job in enumerate(considerable):
            h = int(assign[j])
            if h < 0:
                result.unmatched.append(job)
            else:
                result.matched.append((job, offers[h]))
        self._launch(pool_name, result, clusters)
        audit.note_skips(self.store.audit, {
            "unmatched": [j.uuid for j in result.unmatched],
            "launch-failed": [(u, {"why": why})
                              for u, why in result.launch_failures],
        }, pool=pool_name)
        return result

    def record_placement_failures(self, jobs: List[Job], assign: np.ndarray,
                                  offers: List[Offer],
                                  ctx: ConstraintContext) -> None:
        """Persist per-host failure summaries for unmatched jobs the
        explainer put under investigation (reference:
        record-placement-failures! fenzo_utils.clj:75-99)."""
        from .constraints import explain_placement_failure
        for j, job in enumerate(jobs):
            if int(assign[j]) >= 0:
                continue
            fresh = self.store.job(job.uuid)
            if fresh is None or not fresh.under_investigation:
                continue
            summary = explain_placement_failure(job, offers, ctx)
            self.store.set_placement_investigation(
                job.uuid, under_investigation=False, failure=summary)

    @staticmethod
    def resolve_backend(mc: MatcherConfig, num_jobs: int) -> str:
        """Concrete kernel for ``auto``: bit-exact greedy while the scan
        length is affordable; beyond the threshold, the choice follows
        ``auto_packing`` (policy table: docs/PLACEMENT_QUALITY.md) —
        "throughput" keeps the no-JxH waterfill kernel (lowest latency,
        full placement, looser packing), "tight" selects the adaptive
        auction + waterfill tail (full placement at near-greedy
        tightness for ~2.5x the kernel latency; the reference's default
        fitness IS bin-packing, config.clj:108 cpuMemBinPacker)."""
        # names are validated/migrated at CONFIG time
        # (MatcherConfig.__post_init__); this stays a pure lookup
        if mc.backend == "tpu-auction-pallas":  # mutated post-init
            return "tpu-auction"
        if mc.backend == "tpu-megakernel":
            # the megakernel is a CYCLE backend (sched/fused.py routes
            # dispatch_group through ops/pallas_cycle); when the SPLIT
            # path runs (degraded cycle, step_match tests) the match
            # stage falls back to the bit-exact greedy scan — the same
            # assignment math the megakernel fuses
            return "tpu-greedy"
        if mc.backend != "auto":
            return mc.backend
        if num_jobs <= mc.auto_large_j_threshold:
            return "tpu-greedy"
        return ("tpu-auction" if mc.auto_packing == "tight"
                else "tpu-waterfill")

    def _dispatch(self, mc: MatcherConfig, job_res, cmask, avail, cap
                  ) -> np.ndarray:
        # callers pass plain lists; everything below (including the
        # sparse/dense fancy-indexed split) needs arrays
        job_res = np.asarray(job_res, dtype=F32).reshape(-1, 4)
        avail = np.asarray(avail, dtype=F32).reshape(-1, 4)
        cap = np.asarray(cap, dtype=F32).reshape(-1, 4)
        cmask = np.asarray(cmask, dtype=bool)
        if mc.backend == "cpu":
            return reference_impl.greedy_match(job_res, cmask, avail, cap)
        try:
            return self._dispatch_device(mc, job_res, cmask, avail, cap)
        except Exception:
            # a kernel dispatch failure (XLA error, device loss, injected
            # fault) degrades to the host reference path instead of
            # killing the whole match cycle (docs/ROBUSTNESS.md)
            import logging
            logging.getLogger(__name__).exception(
                "kernel dispatch failed; falling back to host match")
            registry.counter_inc("cook_kernel_fallback",
                                 labels={"kernel": "match"})
            flight_recorder.note_fault("kernel.dispatch-fallback")
            return reference_impl.greedy_match(job_res, cmask, avail, cap)

    def _dispatch_device(self, mc: MatcherConfig, job_res, cmask, avail,
                         cap) -> np.ndarray:
        backend = self.resolve_backend(mc, len(job_res))
        if backend == "tpu-waterfill" and mc.backend == "auto" \
                and len(job_res):
            # The prefix-packing kernel's constraint-mask support is
            # safety-only (ops/match.py): a sparse row's few allowed hosts
            # can be probed over.  Bulk dense-mask jobs go through
            # waterfill; the constrained minority is matched exactly by the
            # greedy scan against the remaining availability.
            sparse = cmask.mean(axis=1) < mc.sparse_cmask_density
            if sparse.any():
                J = len(job_res)
                assign = np.full(J, -1, dtype=np.int32)
                avail_left = avail
                didx = np.flatnonzero(~sparse)
                if didx.size:
                    a, avail_left = self._run_kernel(
                        "tpu-waterfill", mc, job_res[didx], cmask[didx],
                        avail_left, cap)
                    assign[didx] = a
                sidx = np.flatnonzero(sparse)
                a, _ = self._run_kernel(
                    "tpu-greedy", mc, job_res[sidx], cmask[sidx],
                    avail_left, cap)
                assign[sidx] = a
                return assign
        return self._run_kernel(backend, mc, job_res, cmask, avail, cap)[0]

    def _run_kernel(self, backend: str, mc: MatcherConfig, job_res, cmask,
                    avail, cap):
        """One kernel call; returns (assign over real jobs, remaining
        host availability over real hosts)."""
        from ..utils.faults import injector as _faults
        _faults.fire("kernel.dispatch")
        import jax.numpy as jnp
        from ..ops import MatchInputs, auction_match_kernel, greedy_match_kernel
        from ..ops.match import waterfill_match_kernel
        arrays = host_prep.pack_match_inputs(job_res, cmask, avail, cap)
        telemetry.count_transfer("h2d", sum(
            getattr(a, "nbytes", 0) for a in arrays.values()))
        inp = MatchInputs(
            job_res=jnp.asarray(arrays["job_res"]),
            constraint_mask=jnp.asarray(arrays["constraint_mask"]),
            avail=jnp.asarray(arrays["avail"]),
            capacity=jnp.asarray(arrays["capacity"]),
            valid=jnp.asarray(arrays["valid"]))
        if backend == "tpu-auction":
            assign, left = auction_match_kernel(
                inp, num_prefs=mc.auction_num_prefs,
                num_rounds=mc.auction_num_rounds,
                num_refresh=mc.auction_num_refresh,
                min_refresh_gain=mc.auction_min_refresh_gain)
        elif backend == "tpu-waterfill":
            assign, left = waterfill_match_kernel(
                inp, num_rounds=mc.waterfill_num_rounds,
                num_compaction=mc.waterfill_num_compaction)
        else:
            assign, left = greedy_match_kernel(inp)
        if backend == "tpu-auction":
            # finish leftovers with the waterfill formulation: the
            # auction's residual under contention is preference-structure
            # exhaustion (every job's K tightest hosts taken in rank
            # order, docs/PLACEMENT_QUALITY.md), which the prefix mapping
            # doesn't suffer; placements strictly increase (jobs already
            # assigned keep their host, waterfill only sees the rest)
            leftover_valid = inp.valid & (assign < 0)
            tail_inp = MatchInputs(
                job_res=inp.job_res, constraint_mask=inp.constraint_mask,
                avail=left, capacity=inp.capacity, valid=leftover_valid)
            # compaction is safe here: settled auction placements are
            # baked into the availability the tail sees, and only tail
            # jobs can move
            tail_assign, left = waterfill_match_kernel(
                tail_inp, num_rounds=mc.waterfill_num_rounds,
                num_compaction=mc.waterfill_num_compaction)
            assign = jnp.where(assign < 0, tail_assign, assign)
        n_hosts = len(avail)
        with telemetry.sync_wait("match.fetch"):
            assign_np = np.asarray(assign)
            left_np = np.asarray(left)
        telemetry.count_transfer("d2h", assign_np.nbytes + left_np.nbytes)
        return assign_np[:arrays["num_jobs"]], left_np[:n_hosts]

    # ---------------------------------------------------------------- launch
    def _launch(self, pool_name: str, result: MatchCycleResult,
                clusters: Dict[str, ComputeCluster]) -> None:
        """Transactional guard then cluster launch (reference:
        launch-matched-tasks! scheduler.clj:1028: the store transaction
        failing MUST block the backend launch)."""
        from ..policy import pool_user_key
        cluster_rl = self.rate_limits.cluster_launch
        launch_rl = self.rate_limits.job_launch
        cluster_budget: Dict[str, float] = {}
        by_cluster: Dict[str, List[LaunchSpec]] = {}
        entries: List[Dict] = []
        by_task: Dict[str, Tuple[Job, Offer]] = {}
        # gang cohorts launch atomically: every member clears the
        # per-cluster rate limit together or the whole gang waits, and
        # the entries carry the gang uuid so the guard transaction (and
        # the crash-recovery intent sweep) treats them as one unit
        gangs = self.store.gang_groups_of(j for j, _o in result.matched)
        # units preserve match order: singles as-is, gang cohorts whole
        units: List[List[Tuple[Job, Offer]]] = []
        cohort_by_gang: Dict[str, List[Tuple[Job, Offer]]] = {}
        for job, offer in result.matched:
            guuid = job.group if job.group in gangs else None
            if guuid is None:
                units.append([(job, offer)])
            else:
                cohort = cohort_by_gang.get(guuid)
                if cohort is None:
                    cohort = cohort_by_gang[guuid] = []
                    units.append(cohort)
                cohort.append((job, offer))
        for unit in units:
            # per-compute-cluster launch rate limit (reference:
            # filter-matches-for-ratelimit scheduler.clj:887) — applied
            # to the whole unit: a gang partially over the limit would
            # otherwise launch partial
            if cluster_rl.enforce:
                need: Dict[str, int] = {}
                for _job, offer in unit:
                    need[offer.cluster] = need.get(offer.cluster, 0) + 1
                ok = True
                for cname, n in need.items():
                    budget = cluster_budget.setdefault(
                        cname, cluster_rl.get_token_count(cname))
                    if budget < n:
                        ok = False
                if not ok:
                    result.unmatched.extend(job for job, _o in unit)
                    guuid = unit[0][0].group \
                        if unit[0][0].group in gangs else None
                    if guuid:
                        # surface the wait to the unscheduled explainer:
                        # the gang MATCHED but the cluster launch budget
                        # cannot cover the whole cohort yet (tokens
                        # refill; permanent only if the bucket is
                        # smaller than the gang)
                        result.gang_partial.setdefault(guuid, {
                            "size": len(unit), "matched": len(unit),
                            "missing": 0, "topology_blocked": False,
                            "rate_limited": True})
                    continue
                for cname, n in need.items():
                    cluster_budget[cname] -= n
            guuid = unit[0][0].group if unit[0][0].group in gangs else None
            for job, offer in unit:
                task_id = new_uuid()
                entries.append(dict(
                    job_uuid=job.uuid, task_id=task_id,
                    hostname=offer.hostname,
                    slave_id=offer.slave_id, compute_cluster=offer.cluster,
                    node_location=offer.attributes.get(
                        LOCATION_ATTRIBUTE, ""),
                    **({"gang": guuid} if guuid else {})))
                by_task[task_id] = (job, offer)
        # ONE guard transaction for the whole cycle's launches (reference:
        # launch-matched-tasks! transacts all task txns at once,
        # scheduler.clj:810-1009); per-job guard failures are reported and
        # those jobs never reach a backend
        insts, failures = self.store.launch_instances(entries)
        result.launch_failures.extend(failures)
        for inst in insts:
            job, offer = by_task[inst.task_id]
            # launch-time wait histogram: the queue-latency SLO's
            # companion (monitor samples pending ages; this records the
            # realized wait of every job that actually launched)
            registry.observe("cook_queue_latency_seconds",
                             inst.queue_time_ms / 1000.0,
                             labels={"pool": pool_name},
                             buckets=LATENCY_BUCKETS)
            launch_rl.spend(pool_user_key(pool_name, job.user))
            cluster_rl.spend(offer.cluster)
            env = job.env
            if job.trace_id:
                # propagate the submission's trace context to the agent
                # executor (W3C traceparent in the task env): the exec
                # span the wrapper opens joins the job's client-minted
                # trace, so the fleet trace collector can stitch client
                # submit -> leader txn -> agent exec onto one timeline
                # (docs/OBSERVABILITY.md)
                env = {**env, "COOK_TRACEPARENT":
                       tracing.make_traceparent(job.trace_id)}
            guuid = job.group if job.group in gangs else None
            if guuid:
                # executors gate on the gang barrier via the task env
                # (docs/GANG.md); the scheduler's barrier state is the
                # authoritative mirror on /group.  Elastic gangs also
                # see their legal size range so the workload can adapt
                # to resize events (COOK_GANG_RESIZE_* protocol,
                # agent/executor.py).
                from ..state.schema import gang_bounds, gang_is_elastic
                g = gangs.get(guuid)
                env = {**env, "COOK_GANG_UUID": guuid,
                       "COOK_GANG_SIZE":
                           str(getattr(g, "gang_size", 0) or 0)}
                if gang_is_elastic(g):
                    lo, hi = gang_bounds(g)
                    env["COOK_GANG_MIN"] = str(lo)
                    env["COOK_GANG_MAX"] = str(hi)
                    # sandbox-relative advisory file the agent executor
                    # appends resize events to (SIGUSR1 says "look",
                    # the file says what; agent/executor.py)
                    env["COOK_GANG_RESIZE_FILE"] = \
                        ".cook-gang-resize.jsonl"
            by_cluster.setdefault(offer.cluster, []).append(LaunchSpec(
                task_id=inst.task_id, job_uuid=job.uuid,
                hostname=offer.hostname, slave_id=offer.slave_id,
                resources=job.resources, env=env, port_count=job.ports,
                container=job.container))
            result.launched_task_ids.append(inst.task_id)
            result.launched_job_uuids.append(job.uuid)
        # per-cluster launches fan out in parallel (reference: future per
        # cluster, scheduler.clj:1034-1048) — one slow backend must not
        # serialize the others
        def launch_on(cluster, specs):
            from ..utils.retry import breakers
            cluster.kill_lock.acquire_read()
            try:
                with tracing.span("cluster.launch-tasks", pool=pool_name,
                                  cluster=cluster.name, tasks=len(specs)):
                    cluster.launch_tasks(pool_name, specs)
            except Exception:
                # a whole-batch dispatch failure counts against the
                # cluster's breaker; the intents stay open so a crash or
                # restart reconciles them (refund, never duplicate)
                breakers.get(cluster.name).record_failure()
                raise
            finally:
                cluster.kill_lock.release_read()
            # dispatch acked by the backend: confirm the launch intents
            # (tasks whose status already arrived were cleared in-line)
            self.store.clear_launch_intents([s.task_id for s in specs])

        targets = [(clusters[name], specs)
                   for name, specs in by_cluster.items() if name in clusters]
        if len(targets) == 1:
            launch_on(*targets[0])
        elif targets:
            import contextvars
            import threading
            errors: List[BaseException] = []

            def launch_guarded(cluster, specs):
                try:
                    launch_on(cluster, specs)
                except BaseException as e:  # propagate after join
                    errors.append(e)

            # copy_context: the per-cluster launch spans (and their
            # flight-record attribution) stay nested under the calling
            # cycle's trace instead of starting orphan root traces
            threads = [threading.Thread(
                target=contextvars.copy_context().run,
                args=(launch_guarded,) + t,
                name=f"launch-{t[0].name}")
                for t in targets]
            for th in threads:
                th.start()
            for th in threads:
                th.join()
            if errors:
                # surface like the sequential path would: first failure wins
                raise errors[0]
