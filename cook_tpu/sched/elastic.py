"""Elastic gangs: the resize plane between placement and cluster goodput.

The gang subsystem (docs/GANG.md) placed a gang at exactly ``gang_size``
or not at all.  Long-running elastic training jobs (Pollux, OSDI'21;
Gandiva, OSDI'18) want the shape this module adds: a gang declares
``gang_min <= size <= gang_max`` and

- **places** whole at any member count in ``[min, max]`` (the segment
  reduction in ``ops/gang.py`` gates on min; surplus members keep their
  placements);
- **grows** into spare capacity: once a gang runs at >= min live
  members ("satisfied"), its remaining waiting members admit like
  group-less singles — the ordinary match path IS the grow mechanism,
  metered by the per-pool grow budget the optimizer loop sets;
- **shrinks** under pressure instead of dying: the rebalancer prices an
  elastic gang's surplus members individually (post-shrink size) and
  sheds them through the checkpoint/grace protocol below rather than
  killing the whole gang.

Checkpoint/grace shrink protocol (the agent side lives in
``agent/executor.py``):

1. the scheduler picks a surplus member and calls
   :meth:`ElasticManager.request_shrink`;
2. the member's cluster gets a best-effort ``notify_task`` (the agent
   delivers SIGUSR1 to the task's process group and appends a
   ``shrink`` event to the ``COOK_GANG_RESIZE_FILE`` advertised in the
   task environment) so the workload can checkpoint;
3. after ``elastic.shrink_grace_seconds`` the member's instance is
   transacted FAILED with the mea-culpa ``gang-resized`` reason and
   backend-killed.  The gang policy never reacts to ``gang-resized``
   (the gang stays whole at its post-shrink size), and the member —
   back in WAITING — is the first candidate to grow the gang again
   when capacity frees.

A leader crash between (2) and (3) loses only the in-memory deadline:
the victim keeps running, and the successor's rebalancer/optimizer
re-decides — a shrink can be delayed by failover, never half-applied
(the ``sim --chaos --elastic`` leg asserts exactly this).

The per-pool **grow budgets** and **shrink pressure** are the levers
the real optimizer loop (``sched/optimizer.py`` GoodputOptimizer)
actually pulls; both default to "unbounded grow, no pressure" so a
deployment without the optimizer behaves like plain elastic matching.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from ..state.schema import (
    InstanceStatus,
    Reasons,
    gang_bounds,
    gang_is_elastic,
)
from ..utils.metrics import registry

#: lock rank 18 (utils/locks.py contract table): below ``store`` (20)
#: so an accidental store call under the ledger lock still acquires in
#: ascending rank — by design the ledger sections hold no other lock.
_LOCK_NAME = "elastic"

INF = float("inf")


def satisfied_gangs(store, groups: Dict[str, object]) -> Optional[set]:
    """Group uuids of ELASTIC gangs in ``groups`` currently running at
    >= gang_min live members — their waiting members are the grow path
    (docs/GANG.md elasticity).  None when no group is elastic, so the
    rigid-only workload pays one generator scan and no store reads
    (decision-parity guard: rigid packs are built identically)."""
    elastic = [g for g in groups.values() if gang_is_elastic(g)]
    if not elastic:
        return None
    out = set()
    for g in elastic:
        lo, _hi = gang_bounds(g)
        if store.gang_live_members(g.uuid) >= lo:
            out.add(g.uuid)
    return out or None


class ElasticManager:
    """Resize ledger + budgets: pending grace shrinks, per-pool grow
    budgets / shrink pressure (set by the optimizer), and the
    ``cook_gang_resize_total`` accounting.  Owned by the scheduler;
    shared with the rebalancer (shrink-instead-of-kill) and the match
    paths (grow metering)."""

    def __init__(self, store, elastic_config=None):
        from ..utils.locks import named_lock
        self.store = store
        self.config = elastic_config
        self._mu = named_lock(_LOCK_NAME)
        # task_id -> {"deadline_ms", "gang", "cluster", "reason"}
        self._pending: Dict[str, Dict] = {}
        # optimizer-set levers (pool -> value); absent = default
        self.grow_budget: Dict[str, float] = {}
        self.shrink_pressure: Dict[str, int] = {}
        # per-cycle grow slots left (reset by start_pool_cycle)
        self._grow_left: Dict[str, float] = {}
        self.grows = 0
        self.shrinks = 0
        self.grace_expiries = 0

    # ------------------------------------------------------------- config
    @property
    def enabled(self) -> bool:
        return self.config is None or getattr(self.config, "enabled", True)

    def _grace_ms(self) -> float:
        if self.config is None:
            return 0.0
        return float(getattr(self.config, "shrink_grace_seconds", 0.0)) \
            * 1000.0

    # ---------------------------------------------------------- grow plane
    def start_pool_cycle(self, pool: str) -> None:
        """Reset the pool's per-cycle grow meter to the optimizer's
        budget (unbounded when the optimizer set none)."""
        self._grow_left[pool] = self.grow_budget.get(pool, INF)

    def admit_grow(self, pool: str) -> bool:
        """Consume one grow slot for ``pool`` this cycle; False when the
        optimizer's budget is exhausted (the member waits a cycle with
        the ``gang-grow-deferred`` skip reason)."""
        left = self._grow_left.get(pool, INF)
        if left <= 0:
            return False
        self._grow_left[pool] = left - 1
        return True

    def note_grow(self, pool: str, n: int = 1,
                  reason: str = "capacity") -> None:
        """A satisfied gang gained ``n`` launched members (observed off
        the launch tx events, so the rigid path pays nothing)."""
        self.grows += n
        registry.counter_inc("cook_gang_resize", float(n),
                             labels={"direction": "grow",
                                     "reason": reason})

    # -------------------------------------------------------- shrink plane
    def request_shrink(self, task_id: str, job_uuid: str, gang_uuid: str,
                       cluster_name: str, clusters: Dict,
                       reason: str = "pressure",
                       facts: Optional[Dict] = None) -> bool:
        """Begin the checkpoint/grace shrink of one surplus member: the
        agent is notified (SIGUSR1 + resize-file event, best-effort),
        the decision lands on the member's audit timeline, and the kill
        executes after the grace deadline (immediately at grace 0).
        Idempotent per task; False when the task is already shrinking."""
        now = self.store.clock()
        grace_ms = self._grace_ms()
        with self._mu:
            if task_id in self._pending:
                return False
            self._pending[task_id] = {
                "deadline_ms": now + grace_ms, "gang": gang_uuid,
                "cluster": cluster_name, "reason": reason,
                "job": job_uuid}
        self.shrinks += 1
        registry.counter_inc("cook_gang_resize",
                             labels={"direction": "shrink",
                                     "reason": reason})
        self.store.audit.record(job_uuid, "gang-resize", {
            "direction": "shrink", "task": task_id, "gang": gang_uuid,
            "reason": reason, "grace_ms": grace_ms,
            **(facts or {})}, durable=True)
        cluster = clusters.get(cluster_name)
        if cluster is not None:
            try:
                cluster.notify_task(task_id, {
                    "kind": "gang-resize", "direction": "shrink",
                    "gang": gang_uuid, "grace_ms": grace_ms,
                    "reason": reason})
            except Exception:  # pragma: no cover - notify is best-effort
                pass
        if grace_ms <= 0:
            self._execute_shrink(task_id, clusters)
        return True

    def _execute_shrink(self, task_id: str, clusters: Dict) -> None:
        with self._mu:
            entry = self._pending.pop(task_id, None)
        if entry is None:
            return
        inst = self.store.instance(task_id)
        if inst is None or inst.status not in (InstanceStatus.UNKNOWN,
                                               InstanceStatus.RUNNING):
            return  # completed/killed during the grace window: no-op
        # authoritative store transition first (single-writer
        # discipline), then the backend kill — exactly _kill_instance's
        # order, with the resize-specific mea-culpa reason
        self.store.update_instance_status(
            task_id, InstanceStatus.FAILED,
            reason_code=Reasons.GANG_RESIZED.code, preempted=True)
        cluster = clusters.get(entry["cluster"])
        if cluster is not None:
            try:
                cluster.safe_kill_task(task_id)
            except Exception:  # pragma: no cover - reapers converge it
                pass

    def sweep(self, clusters: Dict,
              now_ms: Optional[int] = None) -> List[str]:
        """Execute every pending shrink whose grace deadline passed
        (docs/ROBUSTNESS.md "checkpoint-grace expiry").  Returns the
        task ids shed this sweep."""
        now = now_ms if now_ms is not None else self.store.clock()
        with self._mu:
            due = [tid for tid, e in self._pending.items()
                   if e["deadline_ms"] <= now]
        for tid in due:
            self.grace_expiries += 1
            self._execute_shrink(tid, clusters)
        return due

    def pending_shrinks(self) -> Dict[str, Dict]:
        with self._mu:
            return {tid: dict(e) for tid, e in self._pending.items()}

    def shrinking(self, task_id: str) -> bool:
        with self._mu:
            return task_id in self._pending

    # ------------------------------------------------- optimizer pressure
    def apply_pressure(self, pool: str, clusters: Dict,
                       decision_facts: Optional[Dict] = None) -> int:
        """Shed up to ``shrink_pressure[pool]`` surplus members of the
        pool's elastic gangs — the optimizer's shrink lever.  Surplus =
        live members above gang_min; the newest-launched members go
        first (they hold the least progress).  Returns the number of
        shrinks requested; the pressure is consumed by what it sheds."""
        budget = int(self.shrink_pressure.get(pool, 0))
        if budget <= 0:
            return 0
        # members already pending a grace shrink are NOT surplus twice:
        # their kills are committed, and shedding "surplus" that is
        # mid-shrink would take the gang below gang_min once every
        # pending kill executes (same netting the rebalancer does)
        with self._mu:
            pending_by_gang: Dict[str, int] = {}
            for e in self._pending.values():
                g = e.get("gang")
                pending_by_gang[g] = pending_by_gang.get(g, 0) + 1
        shed = 0
        for group in self.store.elastic_gang_groups():
            if shed >= budget:
                break
            lo, _hi = gang_bounds(group)
            live: List[Tuple[int, str, str, str, str]] = []
            for member_uuid in group.jobs:
                job = self.store.job(member_uuid)
                if job is None or job.pool != pool:
                    continue
                for tid in job.instances:
                    inst = self.store.instance(tid)
                    if inst is not None and inst.status in (
                            InstanceStatus.UNKNOWN,
                            InstanceStatus.RUNNING):
                        live.append((inst.start_time_ms or 0, tid,
                                     member_uuid, inst.compute_cluster,
                                     group.uuid))
            surplus = len(live) - lo - pending_by_gang.get(group.uuid, 0)
            if surplus <= 0:
                continue
            live.sort(reverse=True)  # newest first: least progress lost
            for start_ms, tid, member_uuid, cluster_name, guuid \
                    in live[:min(surplus, budget - shed)]:
                if self.shrinking(tid):
                    continue
                if self.request_shrink(
                        tid, member_uuid, guuid, cluster_name, clusters,
                        reason="optimizer", facts=decision_facts):
                    shed += 1
        if shed:
            self.shrink_pressure[pool] = max(budget - shed, 0)
        return shed

    # ------------------------------------------------------------ surfaces
    def debug(self) -> Dict:
        with self._mu:
            pending = {tid: {k: v for k, v in e.items()}
                       for tid, e in self._pending.items()}
        return {
            "enabled": self.enabled,
            "pending_shrinks": pending,
            "grow_budget": {p: (None if b == INF else b)
                            for p, b in self.grow_budget.items()},
            "shrink_pressure": dict(self.shrink_pressure),
            "grows": self.grows,
            "shrinks": self.shrinks,
            "grace_expiries": self.grace_expiries,
        }
