"""Rebalancer cycle: periodic preemption to restore fair share.

Host half of the reference's rebalancer (reference: rebalancer.clj
rebalance :434, init-state :222-266, next-state :270-309): build the DRU
state of all running tasks, walk the top pending jobs, and for each ask the
preemption kernel for the host whose minimum-DRU victim set is maximal;
apply decisions by transacting preempted-by-rebalancer failures (mea-culpa)
and kill the tasks under the cluster write lock.
"""

from __future__ import annotations

import bisect
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..cluster.base import ComputeCluster, Offer
from ..config import Config
from ..ops import host_prep
from ..state.schema import (
    DruMode,
    Instance,
    InstanceStatus,
    Job,
    Reasons,
    Resources,
    job_usage,
    add_usage,
    below_quota,
)
from ..state.store import Store
from .constraints import ConstraintContext, build_constraint_mask
from .ranker import _job_feature_key

F32 = np.float32


def effective_rebalancer_params(config: Config, store: Store):
    """Static file config overlaid with the store's dynamic document
    (reference: rebalancer params re-read from the DB every cycle,
    rebalancer.clj:539-544).  Module-level so API nodes without a
    scheduler report the same truth they accept updates against."""
    import dataclasses
    params = config.rebalancer
    override = store.dynamic_config("rebalancer")
    if not override:
        return params
    known = {f.name for f in dataclasses.fields(params)}
    return dataclasses.replace(
        params, **{k: v for k, v in override.items() if k in known})


@dataclass
class PreemptionDecision:
    job_uuid: str
    hostname: str
    victim_task_ids: List[str]
    dru: float
    spare_only: bool = False
    # fairness observability (docs/OBSERVABILITY.md): the DRU facts that
    # justified the decision — per-victim DRU at decision time, the
    # beneficiary's pending DRU, and which victims were only taken by a
    # whole-gang closure (they label cook_preemptions_total{reason} and
    # land on both sides' audit timelines)
    victim_drus: Dict[str, float] = field(default_factory=dict)
    pending_dru: float = 0.0
    gang_victim_ids: List[str] = field(default_factory=list)
    # ELASTIC shrink victims (docs/GANG.md elasticity): surplus members
    # of elastic gangs shed through the checkpoint/grace protocol
    # instead of the immediate preempt kill — their gangs keep running
    # at >= gang_min, no whole-gang closure
    shrink_task_ids: List[str] = field(default_factory=list)


@dataclass
class _Task:
    task_id: str
    job: Job
    inst: Instance
    dru: float = 0.0


class _State:
    """Mutable cycle state (reference: rebalancer State record)."""

    def __init__(self, store: Store, pool_name: str, dru_mode: DruMode,
                 running: List[Tuple[Job, Instance]],
                 spare: Dict[str, Resources]):
        self.pool_name = pool_name
        self.gpu_mode = dru_mode is DruMode.GPU
        self.store = store
        # user -> tasks in comparator order (running only)
        self.user_tasks: Dict[str, List[_Task]] = {}
        # gang bookkeeping (docs/GANG.md): task -> gang group uuid and
        # gang -> member tasks, so victims are priced and expanded at
        # whole-gang granularity — preemption must never strand a
        # partial gang
        self.gang_of_task: Dict[str, str] = {}
        self.gang_tasks: Dict[str, List[str]] = {}
        # elastic gangs (docs/GANG.md elasticity): effective minimum per
        # gang, so the decision loop can shed SURPLUS members (live -
        # min, net of shrinks already pending a grace deadline) instead
        # of closing the whole gang
        self.gang_lo: Dict[str, int] = {}
        self.gang_elastic: set = set()
        gang_groups: Dict[str, bool] = {}
        for job, inst in running:
            self.user_tasks.setdefault(job.user, []).append(
                _Task(inst.task_id, job, inst))
            if job.group is not None:
                is_gang = gang_groups.get(job.group)
                if is_gang is None:
                    g = store.group(job.group)
                    is_gang = bool(g is not None
                                   and getattr(g, "gang", False))
                    gang_groups[job.group] = is_gang
                    if is_gang:
                        from ..state.schema import (gang_bounds,
                                                    gang_is_elastic)
                        self.gang_lo[job.group] = gang_bounds(g)[0]
                        if gang_is_elastic(g):
                            self.gang_elastic.add(job.group)
                if is_gang:
                    self.gang_of_task[inst.task_id] = job.group
                    self.gang_tasks.setdefault(
                        job.group, []).append(inst.task_id)
        # surplus shrink budget per elastic gang, consumed as decisions
        # shed members this cycle
        self.gang_surplus: Dict[str, int] = {
            g: max(len(self.gang_tasks.get(g, ())) - self.gang_lo[g], 0)
            for g in self.gang_elastic}
        for user, tasks in self.user_tasks.items():
            tasks.sort(key=lambda t: _job_feature_key(t.job, t.inst))
        self.shares: Dict[str, Tuple[float, float, float]] = {}
        for user in self.user_tasks:
            s = store.get_share(user, pool_name)
            self.shares[user] = (s["cpus"], s["mem"], s["gpus"])
        for user in self.user_tasks:
            self._recompute_user(user)
        self.spare: Dict[str, Resources] = dict(spare)
        self.preempted_ids: set = set()

    def _share(self, user: str) -> Tuple[float, float, float]:
        if user not in self.shares:
            s = self.store.get_share(user, self.pool_name)
            self.shares[user] = (s["cpus"], s["mem"], s["gpus"])
        return self.shares[user]

    def _recompute_user(self, user: str) -> None:
        """Per-user cumulative DRU (reference: dru.clj:50-80)."""
        share = np.asarray(self._share(user), dtype=F32)
        cum = np.zeros(3, dtype=F32)
        for t in self.user_tasks.get(user, []):
            cum = cum + np.array([t.job.resources.cpus, t.job.resources.mem,
                                  t.job.resources.gpus], dtype=F32)
            if self.gpu_mode:
                t.dru = float(cum[2] / share[2])
            else:
                t.dru = float(max(cum[1] / share[1], cum[0] / share[0]))

    def pending_job_dru(self, job: Job) -> float:
        """Nearest-task dru + the job's own increment (reference:
        compute-pending-default-job-dru rebalancer.clj:182-209)."""
        user = job.user
        tasks = self.user_tasks.get(user, [])
        key = _job_feature_key(job, None)
        keys = [_job_feature_key(t.job, t.inst) for t in tasks]
        i = bisect.bisect_right(keys, key)
        nearest = tasks[i - 1].dru if i > 0 else 0.0
        share = self._share(user)
        if self.gpu_mode:
            return nearest + job.resources.gpus / share[2]
        return max(nearest + job.resources.mem / share[1],
                   nearest + job.resources.cpus / share[0])

    def job_below_quota(self, job: Job) -> bool:
        """Would the job fit its user's quota if launched (rebalancer.clj
        job-below-quota :212-221)."""
        usage = job_usage(job)
        for t in self.user_tasks.get(job.user, []):
            usage = add_usage(usage, job_usage(t.job))
        quota = self.store.get_quota(job.user, self.pool_name)
        return below_quota(quota, usage)

    def all_tasks(self) -> List[_Task]:
        return [t for tasks in self.user_tasks.values() for t in tasks]

    def apply_decision(self, job: Job, hostname: str,
                       victims: List[_Task]) -> None:
        """next-state (rebalancer.clj:270-309): remove victims, add the
        pending job as a virtual running task, update spare, rescore."""
        changed = {job.user}
        for v in victims:
            changed.add(v.job.user)
            self.user_tasks[v.job.user].remove(v)
            self.preempted_ids.add(v.task_id)
        virtual = _Task(
            task_id=f"virtual-{job.uuid}", job=job,
            inst=Instance(task_id=f"virtual-{job.uuid}", job_uuid=job.uuid,
                          hostname=hostname,
                          status=InstanceStatus.RUNNING,
                          start_time_ms=2**62))
        lst = self.user_tasks.setdefault(job.user, [])
        lst.append(virtual)
        lst.sort(key=lambda t: _job_feature_key(t.job, t.inst))
        for user in changed:
            self._recompute_user(user)
        # each victim's capacity frees on ITS OWN host (identical to the
        # old single-host sum when all victims share the target host —
        # always true for non-gang decisions — but a whole-gang closure
        # spans hosts and must not credit them all to the target)
        for v in victims:
            self.spare[v.inst.hostname] = \
                self.spare.get(v.inst.hostname, Resources()) \
                + v.job.resources
        self.spare[hostname] = \
            self.spare.get(hostname, Resources()) - job.resources


class Rebalancer:
    def __init__(self, store: Store, config: Config, backend: str = "tpu"):
        self.store = store
        self.config = config
        self.backend = backend
        # elastic resize plane (sched/elastic.ElasticManager, attached
        # by the scheduler): surplus members of elastic gangs shrink
        # through the checkpoint/grace protocol instead of dying with a
        # whole-gang closure.  None = pre-elastic behavior.
        self.elastic = None

    def effective_params(self):
        """Per-cycle parameter resolution: the store's dynamic config
        document overrides the static file config, exactly the reference's
        read-params-from-the-DB-every-cycle (rebalancer.clj:539-544) — a
        no-restart tuning plane."""
        return effective_rebalancer_params(self.config, self.store)

    def rebalance_pool(self, pool_name: str, dru_mode: DruMode,
                       pending_ranked: List[Job],
                       clusters: Dict[str, ComputeCluster]
                       ) -> List[PreemptionDecision]:
        params = self.effective_params()
        if not pending_ranked:
            return []
        running = self.store.running_instances(pool_name)
        spare: Dict[str, Resources] = {}
        offers_by_host: Dict[str, Offer] = {}
        for cluster in clusters.values():
            if not cluster.accepts_pool(pool_name):
                continue
            # hosts() covers fully-utilized hosts with their true
            # capacity/attributes so constraints evaluate correctly there
            for offer in cluster.hosts(pool_name):
                offers_by_host[offer.hostname] = offer
            for offer in cluster.pending_offers(pool_name):
                spare[offer.hostname] = offer.available
                offers_by_host[offer.hostname] = offer
        state = _State(self.store, pool_name, dru_mode, running, spare)
        if self.elastic is not None and state.gang_elastic:
            # members already pending a grace shrink are not surplus
            # twice: shedding "surplus" that is mid-shrink would take
            # the gang below gang_min once both kills execute
            pending = self.elastic.pending_shrinks()
            for tid, entry in pending.items():
                g = entry.get("gang")
                if g in state.gang_surplus:
                    state.gang_surplus[g] = max(
                        state.gang_surplus[g] - 1, 0)

        decisions: List[PreemptionDecision] = []
        budget = params.max_preemption
        task_by_id = {t.task_id: t for t in state.all_tasks()}
        for job in pending_ranked:
            if budget <= 0:
                break
            decision = self._decide(state, job, params, offers_by_host)
            if decision is None:
                continue
            victims = decision[1]
            hostname = decision[0]
            # the beneficiary's DRU BEFORE the decision mutates state:
            # the victim/beneficiary delta is the fairness justification
            pending_dru = state.pending_job_dru(job)
            direct = {v.task_id for v in victims}
            # SHRINK instead of closure (docs/GANG.md elasticity): an
            # elastic gang whose chosen victims fit inside its surplus
            # budget sheds exactly those members through the grace
            # protocol and keeps running at >= gang_min — no closure.
            # Victims beyond the surplus close the whole gang as before.
            shrink_ids: List[str] = []
            if victims and state.gang_elastic:
                per_gang: Dict[str, int] = {}
                for v in victims:
                    g = state.gang_of_task.get(v.task_id)
                    if g in state.gang_elastic:
                        per_gang[g] = per_gang.get(g, 0) + 1
                shrink_gangs = {
                    g for g, n in per_gang.items()
                    if n <= state.gang_surplus.get(g, 0)}
                for g in shrink_gangs:
                    state.gang_surplus[g] -= per_gang[g]
                shrink_ids = [v.task_id for v in victims
                              if state.gang_of_task.get(v.task_id)
                              in shrink_gangs]
            else:
                shrink_gangs = set()
            # whole-gang closure (docs/GANG.md): preempting any member
            # kills its entire gang — across hosts — so the decision can
            # never strand a partial gang holding fragmented capacity
            if victims and state.gang_of_task:
                seen = {v.task_id for v in victims}
                for v in list(victims):
                    g = state.gang_of_task.get(v.task_id)
                    if g is None or g in shrink_gangs:
                        continue
                    for tid in state.gang_tasks.get(g, ()):
                        if tid in seen or tid in state.preempted_ids:
                            continue
                        mate = task_by_id.get(tid)
                        if mate is not None:
                            victims.append(mate)
                            seen.add(tid)
            victim_drus = {v.task_id: round(float(v.dru), 4)
                           for v in victims}
            state.apply_decision(job, hostname, victims)
            decisions.append(PreemptionDecision(
                job_uuid=job.uuid, hostname=hostname,
                victim_task_ids=[v.task_id for v in victims],
                dru=decision[2], spare_only=not victims,
                victim_drus=victim_drus,
                pending_dru=round(float(pending_dru), 4),
                gang_victim_ids=[v.task_id for v in victims
                                 if v.task_id not in direct],
                shrink_task_ids=shrink_ids))
            if victims:
                budget -= 1
        self._execute(decisions, clusters)
        return [d for d in decisions if d.victim_task_ids]

    # ----------------------------------------------------------------- core
    def _decide(self, state: _State, job: Job, params,
                offers_by_host: Dict[str, Offer]
                ) -> Optional[Tuple[str, List["_Task"], float]]:
        pending_dru = state.pending_job_dru(job)
        job_ok_quota = state.job_below_quota(job)

        tasks = state.all_tasks()
        # whole-gang pricing (docs/GANG.md): preempting any member kills
        # the whole gang, so a member's effective DRU for eligibility,
        # scan order, and the decision score is its gang's MINIMUM — the
        # gang is never cheaper than its most-protected member
        gang_min: Dict[str, float] = {}
        if state.gang_of_task:  # gang-free clusters skip the O(tasks) pass
            for t in tasks:
                g = state.gang_of_task.get(t.task_id)
                if g is not None:
                    cur = gang_min.get(g)
                    gang_min[g] = t.dru if cur is None else min(cur, t.dru)

        def edru(t: "_Task") -> float:
            g = state.gang_of_task.get(t.task_id)
            if g is None:
                return t.dru
            # elastic gangs with shrink surplus price members at their
            # OWN dru — the post-shrink cost of the decision is one
            # member, not the whole gang (docs/GANG.md elasticity);
            # once the surplus is consumed the gang-min floor returns
            if state.gang_surplus.get(g, 0) > 0:
                return t.dru
            return gang_min[g]
        # only hosts with a backend inventory entry are preemption targets:
        # a host known solely from a running task has no attribute/capacity
        # facts, so constraint evaluation there would be guesswork
        hostnames = sorted(set(offers_by_host.keys()))
        if not hostnames:
            return None
        host_index = {h: i for i, h in enumerate(hostnames)}

        # eligibility (rebalancer.clj:340-348)
        def ok(t: _Task) -> bool:
            if t.task_id in state.preempted_ids or t.task_id.startswith("virtual-"):
                return False
            if self.elastic is not None \
                    and self.elastic.shrinking(t.task_id):
                return False  # already mid-grace: its capacity is spoken for
            if t.inst.hostname not in host_index:
                return False  # no backend inventory for this host
            if not (job_ok_quota or t.job.user == job.user):
                return False
            d = edru(t)
            if d < params.safe_dru_threshold:
                return False
            return (d - pending_dru) > params.min_dru_diff

        # host constraint check with the match-side compiler
        offers = [offers_by_host[h] for h in hostnames]
        ctx = ConstraintContext(
            max_tasks_per_host=None)  # preemption frees slots; skip count cap
        host_ok = build_constraint_mask([job], offers, ctx)[0]

        order = sorted(range(len(tasks)),
                       key=lambda i: (host_index.get(tasks[i].inst.hostname, 0),
                                      -edru(tasks[i]), i))
        demand = np.array([job.resources.cpus, job.resources.mem,
                           job.resources.gpus, 0.0], dtype=F32)
        spare_arr = np.zeros((len(hostnames), 4), dtype=F32)
        for h, name in enumerate(hostnames):
            s = state.spare.get(name, Resources())
            spare_arr[h] = [s.cpus, s.mem, s.gpus, 0.0]

        # gpu feasibility only matters when requested; padding col 3 unused
        task_dru = np.array([edru(tasks[i]) for i in order], dtype=F32)
        task_res = np.array(
            [[tasks[i].job.resources.cpus, tasks[i].job.resources.mem,
              tasks[i].job.resources.gpus, 0.0] for i in order], dtype=F32) \
            if order else np.zeros((0, 4), dtype=F32)
        task_host = np.array(
            [host_index.get(tasks[i].inst.hostname, 0) for i in order],
            dtype=np.int32)
        eligible = np.array([ok(tasks[i]) for i in order], dtype=bool)

        if self.backend == "cpu" or len(order) == 0:
            from ..ops.reference_impl import preemption_decision
            res = preemption_decision(task_dru, task_res, task_host, eligible,
                                      spare_arr, host_ok, demand)
            if res is None:
                return None
            host, victim_pos, dru = res
            victims = [tasks[order[p]] for p in victim_pos]
            return hostnames[host], victims, float(dru)

        import jax.numpy as jnp
        from ..ops.padding import bucket, pad_to
        from ..ops.rebalance import RebalanceInputs, preemption_kernel
        T = bucket(len(order))
        host_start = np.ones(len(order), dtype=bool)
        host_start[1:] = task_host[1:] != task_host[:-1]
        inp = RebalanceInputs(
            task_dru=jnp.asarray(pad_to(task_dru, T)),
            task_res=jnp.asarray(pad_to(task_res, T)),
            task_host=jnp.asarray(pad_to(task_host, T)),
            host_start=jnp.asarray(pad_to(host_start, T, fill=True)),
            eligible=jnp.asarray(pad_to(eligible, T, fill=False)),
            spare=jnp.asarray(spare_arr),
            host_ok=jnp.asarray(host_ok),
            demand=jnp.asarray(demand))
        out = preemption_kernel(inp)
        if not bool(out.found):
            return None
        host = int(out.host)
        if bool(out.spare_only):
            return hostnames[host], [], float("inf")
        mask = np.asarray(out.victim_mask)[:len(order)]
        victims = [tasks[order[p]] for p in np.nonzero(mask)[0]]
        return hostnames[host], victims, float(out.decision_dru)

    # -------------------------------------------------------------- execute
    def _execute(self, decisions: List[PreemptionDecision],
                 clusters: Dict[str, ComputeCluster]) -> None:
        """Transact preemptions then kill under the write lock (reference:
        rebalancer.clj:482-533).  Both sides of every decision land on
        the audit trail with the DRU delta that justified it: the victim
        records who preempted it and at what DRU, the beneficiary
        records whose capacity it received (docs/OBSERVABILITY.md)."""
        audit = self.store.audit
        for d in decisions:
            gang_mates = set(d.gang_victim_ids)
            shrinks = set(d.shrink_task_ids)
            for tid in d.victim_task_ids:
                inst = self.store.instance(tid)
                if inst is None:
                    continue
                if tid in shrinks and self.elastic is not None \
                        and self.elastic.enabled:
                    # elastic surplus member: checkpoint/grace shrink
                    # instead of the immediate kill — the gang runs on
                    # at its post-shrink size (docs/GANG.md elasticity)
                    job = self.store.job(inst.job_uuid)
                    self.elastic.request_shrink(
                        tid, inst.job_uuid,
                        job.group if job is not None else "",
                        inst.compute_cluster, clusters,
                        reason="pressure",
                        facts={"by": d.job_uuid,
                               "dru": d.victim_drus.get(tid),
                               "beneficiary_dru": d.pending_dru})
                    continue
                self.store.update_instance_status(
                    tid, InstanceStatus.FAILED,
                    reason_code=Reasons.PREEMPTED_BY_REBALANCER.code,
                    preempted=True)
                audit.record(inst.job_uuid, "preempted", {
                    "task": tid, "by": d.job_uuid,
                    "host": inst.hostname,
                    "dru": d.victim_drus.get(tid),
                    "beneficiary_dru": d.pending_dru,
                    **({"gang_closure": True} if tid in gang_mates
                       else {})}, durable=True)
                cluster = clusters.get(inst.compute_cluster)
                if cluster is not None:
                    cluster.safe_kill_task(tid)
            if d.victim_task_ids:
                audit.record(d.job_uuid, "preemption-benefit", {
                    "victims": len(d.victim_task_ids),
                    "host": d.hostname, "dru": d.pending_dru,
                    "victim_dru_min": min(d.victim_drus.values())
                    if d.victim_drus else None}, durable=True)
