"""Why-is-my-job-pending explainer.

Mirrors the reference's unscheduled-jobs reasons (reference:
scheduler/src/cook/unscheduled.clj reasons :172 — exhausted retries,
quota/share limits, queue position with jobs-ahead, launch rate limit,
plugin filter, placement failure; fenzo_utils.clj:21-99 for the
placement-failure summary).  Each reason is {reason, data}; several can
apply at once.

Placement failures use the reference's two-step "under investigation"
workflow: the first ask flags the job (:job/under-investigation), the next
match cycle records a per-host failure census for it
(Matcher.record_placement_failures), and subsequent asks present the
detailed host counts per cause.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ..state.schema import (
    InstanceStatus,
    Job,
    JobState,
    add_usage,
    below_quota,
    job_usage,
)
from ..state.store import Store

# constraint name -> human message (reference: unscheduled.clj
# constraint-name->message)
CONSTRAINT_MESSAGES = {
    "novel_host_constraint": "Job already ran on this host.",
    "gpu_host_constraint": "Host has no GPU support.",
    "non_gpu_host_constraint":
        "Host is reserved for jobs that need GPU support.",
    "attribute-equals-host-placement-group-constraint":
        "Host had a different attribute than other jobs in the group.",
    "unique_host_constraint": "Group cotask already runs on this host.",
    "balanced-host-placement-group-constraint":
        "Placing here would unbalance the group's spread.",
    "rebalancer_reservation_constraint":
        "Host is reserved for a preempting job.",
    "checkpoint_locality_constraint":
        "Host is outside the job's prior checkpoint location.",
    "max_tasks_per_host_constraint": "Host is at its task-count limit.",
    "disk_type_constraint": "Host has a different disk type.",
    "gpu_model_constraint": "Host has a different GPU model.",
    "gang_topology_constraint":
        "Host is outside every topology domain (slice) large enough "
        "for the whole gang.",
}


def placement_failure_for_user(summary: Dict) -> List[Dict]:
    """Serialized failure census -> presentation rows (reference:
    fenzo-failures-for-user, unscheduled.clj)."""
    rows: List[Dict] = []
    for dim, n in (summary.get("resources") or {}).items():
        rows.append({"reason": f"Not enough {dim} available.",
                     "host_count": n})
    for name, n in (summary.get("constraints") or {}).items():
        rows.append({"reason": CONSTRAINT_MESSAGES.get(name, name),
                     "host_count": n})
    return rows


def _limit_excess(limits: Dict[str, float], usage: Dict[str, float]) -> Dict:
    """How usage would exceed limits (reference:
    how-job-would-exceed-resource-limits, unscheduled.clj): returns
    {dim: {"limit": l, "usage": u}} for each exceeded dimension."""
    out = {}
    for dim, lim in limits.items():
        if lim != float("inf") and usage.get(dim, 0.0) > lim:
            out[dim] = {"limit": lim, "usage": usage.get(dim, 0.0)}
    return out


def job_reasons(store: Store, job: Job,
                scheduler=None,
                queue_limits=None) -> List[Dict]:
    """Compute unscheduled reasons for a waiting job."""
    reasons: List[Dict] = []
    if job.state is not JobState.WAITING:
        reasons.append({"reason": f"The job is {job.state.value}.", "data": {}})
        return reasons
    if not job.committed:
        reasons.append({
            "reason": "The job is not yet committed (its submission batch "
                      "has not completed).",
            "data": {}})
        return reasons

    # exhausted retries (reference: check-exhausted-retries)
    instances = {t: store.instance(t) for t in job.instances}
    instances = {t: i for t, i in instances.items() if i is not None}
    attempts = job.attempts_used(instances)
    if attempts >= job.max_retries:
        reasons.append({
            "reason": "Job has exhausted its maximum number of retries.",
            "data": {"max_retries": job.max_retries,
                     "instance_count": attempts}})
    else:
        failures = sum(1 for i in instances.values()
                       if i.status is InstanceStatus.FAILED)
        if failures:
            reasons.append({
                "reason": "The job has failed instances and is waiting to "
                          "retry.",
                "data": {"failures": failures,
                         "max_retries": job.max_retries}})

    # user quota and share limits (reference: check-exceeds-limit applied to
    # both quota and share read-fns)
    usage = job_usage(job)
    for other, _inst in store.running_instances(job.pool):
        if other.user == job.user:
            usage = add_usage(usage, job_usage(other))
    quota = store.get_quota(job.user, job.pool)
    if not below_quota(quota, usage):
        reasons.append({
            "reason": "The job would cause you to exceed resource quotas.",
            "data": _limit_excess(quota, usage)})
    share = store.get_share(job.user, job.pool)
    share_excess = _limit_excess(share, usage)
    if share_excess:
        reasons.append({
            "reason": "The job would cause you to exceed resource shares.",
            "data": share_excess})

    # queue limits
    if queue_limits is not None:
        # probe with one hypothetical job: at exactly the limit, n=0 would
        # pass and the reason would never surface
        msg = queue_limits.check_submission(job.pool, job.user, 1)
        if msg:
            reasons.append({"reason": "You have reached the limit of jobs "
                                      "you can have in the queue.",
                            "data": {"detail": msg}})

    if scheduler is not None:
        # admission brownout (sched/admission.py): under saturation the
        # matcher's considerable window is scaled down by the admission
        # level, so a job can be at the FRONT of its share and still wait
        # — "cs why" must say so instead of "just waiting for its turn"
        ctrl = getattr(scheduler, "admission", None)
        if ctrl is not None and ctrl.level < 1.0:
            reasons.append({
                "reason": "The scheduler is throttling admissions while "
                          "the cluster recovers from overload; fewer "
                          "jobs are considered each cycle.",
                "data": {"kind": "admission-throttled",
                         "level": round(ctrl.level, 3),
                         "stage": ctrl.stage,
                         "stage_name": ctrl.state().get("stage_name"),
                         "worst_resource": ctrl.worst_resource}})
        # launch rate limit
        rl = scheduler.rate_limits.job_launch
        if rl.enforce:
            from ..policy import pool_user_key
            key = pool_user_key(job.pool, job.user)
            if rl.get_token_count(key) <= 0:
                reasons.append({
                    "reason": "You are currently rate limited on how many "
                              "jobs you launch per minute.",
                    "data": {"seconds_until_out_of_debt":
                             rl.time_until_out_of_debt_s(key)}})
        # queue position + the jobs ahead (reference: check-queue-position
        # returns up to 10 uuids of the USER'S OWN jobs ahead in line —
        # never another user's uuids)
        queue = scheduler.pending_queues.get(job.pool, [])
        from .ranker import RankedQueue
        if isinstance(queue, RankedQueue):
            # columnar queue: pure numpy scans — no entity materialization
            # regardless of queue depth or position
            import numpy as np
            hits = np.flatnonzero(queue.uuids == job.uuid)
            position = int(hits[0]) if hits.size else None
            own_ahead = (list(queue.uuids[:position][
                queue.users[:position] == job.user])
                if position is not None and position > 0 else [])
        else:
            position = next((i for i, j in enumerate(queue)
                             if j.uuid == job.uuid), None)
            own_ahead = ([j.uuid for j in queue[:position]
                          if j.user == job.user]
                         if position is not None and position > 0 else [])
        if position is not None and position > 0:
            if own_ahead:
                reasons.append({
                    "reason": f"You have {len(own_ahead)} other jobs ahead "
                              "in the queue.",
                    "data": {"queue_position": position,
                             "queue_length": len(queue),
                             "jobs": own_ahead[:10]}})
        # launch-filter plugin verdict (reference: check-plugin-filter)
        plugins = getattr(scheduler, "plugins", None)
        if plugins is not None and plugins.launch_filters \
                and not plugins.launch_allowed(job):
            reasons.append({
                "reason": "The launch filter plugin is blocking the job "
                          "launch.",
                "data": {"plugins": [type(f).__name__
                                     for f in plugins.launch_filters]}})
        # gang reasons (docs/GANG.md): all-or-nothing placement means a
        # member can be individually placeable yet waiting on its gang
        last = getattr(scheduler, "last_match_results", {}).get(job.pool)
        if job.group is not None:
            group = store.group(job.group)
            if group is not None and getattr(group, "gang", False):
                gp = (getattr(last, "gang_partial", None) or {}).get(
                    job.group) if last is not None else None
                if gp and gp.get("rate_limited"):
                    reasons.append({
                        "reason": "The gang matched but is waiting for "
                                  "enough cluster launch-rate budget to "
                                  "launch all members together.",
                        "data": dict(gp)})
                elif gp and gp.get("topology_blocked"):
                    reasons.append({
                        "reason": "No slice of size "
                                  f"{gp['size']} satisfies the gang's "
                                  "topology request "
                                  f"({group.gang_topology}).",
                        "data": dict(gp)})
                elif gp:
                    reasons.append({
                        "reason": f"Waiting on {gp['missing']} of "
                                  f"{gp['size']} gang members to be "
                                  "placeable in the same cycle.",
                        "data": dict(gp)})
                else:
                    # deferred at ADMISSION (tokens/cap/denied member):
                    # the gang never reached the match pass, so there is
                    # no gang_partial entry to explain it
                    matcher = getattr(scheduler, "matcher", None)
                    adm = (getattr(matcher, "last_admission_deferred", {})
                           .get(job.pool, {}).get(job.group)
                           if matcher is not None else None)
                    if adm:
                        texts = {
                            "rate-limited":
                                "The gang is waiting for enough "
                                "launch-rate tokens to admit all "
                                f"{adm['size']} members together.",
                            "considerable-cap":
                                "The gang is waiting for enough room in "
                                "the scheduling cycle to consider all "
                                f"{adm['size']} members together.",
                            "members-missing":
                                "A gang member is no longer in the "
                                "pending queue, so the gang cannot be "
                                "admitted whole.",
                            "member-denied":
                                "A gang member is blocked from launching "
                                "(launch filter or quota), holding the "
                                "whole gang.",
                            "partial-admission":
                                "The gang could not be admitted whole "
                                "this cycle.",
                        }
                        reasons.append({
                            "reason": texts.get(
                                adm["reason"],
                                "The gang was deferred at admission."),
                            "data": dict(adm)})
        # placement failure: the two-step under-investigation workflow
        # (reference: check-fenzo-placement unscheduled.clj)
        unmatched_last_cycle = last is not None and any(
            j.uuid == job.uuid for j in last.unmatched)
        if job.last_placement_failure:
            reasons.append({
                "reason": "The job couldn't be placed on any available "
                          "hosts.",
                "data": {"reasons": placement_failure_for_user(
                    job.last_placement_failure)}})
        elif unmatched_last_cycle:
            if not job.under_investigation:
                store.set_placement_investigation(
                    job.uuid, under_investigation=True)
            reasons.append({
                "reason": "The job is now under investigation. Check back "
                          "in a minute for more details!",
                "data": {}})
    if not reasons:
        reasons.append({
            "reason": "The job is just waiting for its turn. "
                      "Check back soon!",
            "data": {}})
    return reasons
