"""Why-is-my-job-pending explainer.

Mirrors the reference's unscheduled-jobs reasons (reference:
scheduler/src/cook/rest/unscheduled.clj:172 reasons; fenzo_utils.clj:21-99
for placement-failure conversion): each reason is {reason, data} and several
can apply at once.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ..state.schema import InstanceStatus, Job, JobState, below_quota, job_usage, add_usage
from ..state.store import Store


def job_reasons(store: Store, job: Job,
                scheduler=None,
                queue_limits=None) -> List[Dict]:
    """Compute unscheduled reasons for a waiting job."""
    reasons: List[Dict] = []
    if job.state is not JobState.WAITING:
        reasons.append({"reason": f"The job is {job.state.value}.", "data": {}})
        return reasons
    if not job.committed:
        reasons.append({
            "reason": "The job is not yet committed (its submission batch "
                      "has not completed).",
            "data": {}})
        return reasons

    # attempts so far
    failures = 0
    for tid in job.instances:
        inst = store.instance(tid)
        if inst is not None and inst.status is InstanceStatus.FAILED:
            failures += 1
    if failures:
        reasons.append({
            "reason": "The job has failed instances and is waiting to retry.",
            "data": {"failures": failures,
                     "max_retries": job.max_retries}})

    # user quota
    usage = job_usage(job)
    for other, _inst in store.running_instances(job.pool):
        if other.user == job.user:
            usage = add_usage(usage, job_usage(other))
    quota = store.get_quota(job.user, job.pool)
    if not below_quota(quota, usage):
        reasons.append({
            "reason": "The job would cause you to exceed resource quotas.",
            "data": {"quota": {k: v for k, v in quota.items()
                               if v != float("inf")},
                     "usage": usage}})

    # queue limits
    if queue_limits is not None:
        # probe with one hypothetical job: at exactly the limit, n=0 would
        # pass and the reason would never surface
        msg = queue_limits.check_submission(job.pool, job.user, 1)
        if msg:
            reasons.append({"reason": "You have reached the limit of jobs "
                                      "you can have in the queue.",
                            "data": {"detail": msg}})

    if scheduler is not None:
        # launch rate limit
        rl = scheduler.rate_limits.job_launch
        if rl.enforce:
            from ..policy import pool_user_key
            key = pool_user_key(job.pool, job.user)
            if rl.get_token_count(key) <= 0:
                reasons.append({
                    "reason": "You are currently rate limited on how many "
                              "jobs you launch per minute.",
                    "data": {"seconds_until_out_of_debt":
                             rl.time_until_out_of_debt_s(key)}})
        # queue position
        queue = scheduler.pending_queues.get(job.pool, [])
        position = next((i for i, j in enumerate(queue)
                         if j.uuid == job.uuid), None)
        if position is not None:
            reasons.append({
                "reason": "The job is waiting for its turn in the queue.",
                "data": {"queue_position": position,
                         "queue_length": len(queue)}})
        # placement failure from the last match cycle
        last = getattr(scheduler, "last_match_results", {}).get(job.pool)
        if last is not None and any(j.uuid == job.uuid for j in last.unmatched):
            reasons.append({
                "reason": "The job couldn't be placed on any available hosts.",
                "data": {"considered": last.considered,
                         "offers_were_available": bool(last.matched
                                                       or last.considered)}})
    if not reasons:
        reasons.append({
            "reason": "The job is just waiting for its turn. "
                      "Check back soon!",
            "data": {}})
    return reasons
