from .constraints import (  # noqa: F401
    ConstraintContext,
    build_constraint_mask,
    validate_group_placement,
)
from .matcher import MatchCycleResult, Matcher  # noqa: F401
from .ranker import Ranker, build_user_tasks  # noqa: F401
from .rebalancer import PreemptionDecision, Rebalancer  # noqa: F401
from .scheduler import Scheduler  # noqa: F401
