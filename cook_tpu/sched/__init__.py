from .constraints import (  # noqa: F401
    ConstraintContext,
    build_constraint_mask,
    validate_group_placement,
)
from .matcher import MatchCycleResult, Matcher  # noqa: F401
from .monitor import Monitor  # noqa: F401
from .election import FileLeaderElector, LeaseLeaderElector  # noqa: F401
from .ranker import RankedQueue, Ranker, build_user_tasks  # noqa: F401
from .optimizer import (  # noqa: F401
    DummyHostFeed,
    DummyOptimizer,
    HostFeed,
    HostInfo,
    Optimizer,
    OptimizerConfig,
    OptimizerCycler,
    optimizer_cycle,
)
from .rebalancer import PreemptionDecision, Rebalancer  # noqa: F401
from .scheduler import Scheduler  # noqa: F401
