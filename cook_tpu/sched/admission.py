"""Monitor-driven adaptive admission + saturation-driven brownout.

The serving plane's overload ladder (docs/DEPLOY.md "overload runbook").
Each monitor sweep feeds the six ``cook_saturation{resource=}`` gauges
(sched/fleet.py — the input contract PR 16 shipped) into
:class:`AdmissionController.decide`, which maintains:

* a **0-1 admission level** with hysteresis: the worst gauge past
  ``engage_saturation`` walks the level down (faster the deeper the
  overload — DAGOR-style feedback admission, Zhou et al., SoCC'18);
  below ``release_saturation`` it recovers by ``recover_step`` per
  sweep; the band between is a dead zone so the level never flaps at
  the threshold.  The level directly scales the front-door token-bucket
  refill rates (policy/rate_limit.py ``set_refill_scale``), so admitted
  load tracks what the control plane can actually digest.
* a **brownout stage ladder**, strictly ordered so the shed order is
  provably monotone (the metastable-failure guard of Bronson et al.,
  HotOS'21 — sustained retries against a saturated core are what turn
  overload into outage):

  ====  ===================  ==========================================
  stage name                 what sheds
  ====  ===================  ==========================================
  0     none                 nothing
  1     shed-observability   advisory audit flush folds, slow-ring
                             request capture off (PR 7 cardinality-
                             guard idiom: detail first, signal last)
  2     stale-reads          follower min-offset wait gate relaxed —
                             reads serve bounded-stale with honest
                             ``X-Cook-Replication-Age-Ms``
  3     shed-writes          low-priority submissions 429 at the front
                             door
  ====  ===================  ==========================================

  Committed writes and scheduling decisions degrade last or NEVER:
  no stage touches the journal, group commit, or the match cycle.

Escalation is immediate (a jump past two thresholds engages both
stages — actions are nested ``stage >= k`` checks, so order holds);
de-escalation steps down ONE stage per ``stage_hold_seconds`` of
sustained recovery.  Every stage flip is journaled through the store's
dynamic-config plane (``configs/admission`` rides ordinary ``"w"``
journal records, replicates to standbys, and replays at promotion), so
a leader killed mid-brownout comes back AT ITS STAGE instead of
naively re-admitting the overload that killed it.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from ..utils import tracing
from ..utils.metrics import MetricsRegistry
from ..utils.metrics import registry as default_registry

#: stage index -> wire name (journal doc, /debug/health, gauges docs)
STAGE_NAMES = ("none", "shed-observability", "stale-reads", "shed-writes")

#: the dynamic-config document key stage flips are journaled under
CONFIG_KEY = "admission"


class AdmissionController:
    """One per leader scheduler (the monitor sweep drives
    :meth:`decide`); followers never run one — they read the journaled
    stage off their replicated ``configs`` table (rest/api.py)."""

    def __init__(self, store, config,
                 rate_limits=None,
                 ip_limiter=None,
                 registry: Optional[MetricsRegistry] = None,
                 request_obs=None):
        self.store = store
        self.config = config
        self.ac = config.admission
        self.registry = registry if registry is not None else \
            default_registry
        self.rate_limits = rate_limits
        self.ip_limiter = ip_limiter
        # serving-plane capture rings (rest/instrument.py); default to
        # the module singleton the API serves /debug/requests from
        if request_obs is None:
            from ..rest.instrument import request_log
            request_obs = request_log
        self.request_obs = request_obs
        self.level = 1.0
        self.stage = 0
        self.worst_resource: Optional[str] = None
        self.worst_value = 0.0
        # recovery dwell bookkeeping: ms timestamp since when the level
        # has held above the CURRENT stage's engage threshold
        self._above_since_ms: Optional[int] = None
        # bounded flip history for /debug/health + the golden ordering
        # test (oldest dropped)
        self.transitions: List[Dict] = []
        self.restore()

    # ------------------------------------------------------------- clock
    def _now_ms(self) -> int:
        clock = getattr(self.store, "clock", None)
        if callable(clock):
            return int(clock())
        return int(time.time() * 1000)

    # ----------------------------------------------------------- restore
    def restore(self) -> None:
        """Recover the journaled admission state (leader promotion /
        process restart): the dynamic-config document replayed off the
        journal IS the brownout state — re-apply its side effects so a
        leader killed mid-brownout resumes shedding at its stage."""
        doc = None
        try:
            doc = self.store.dynamic_config(CONFIG_KEY)
        except Exception:
            doc = None
        if doc:
            try:
                self.level = min(max(float(doc.get("level", 1.0)), 0.0),
                                 1.0)
                self.stage = min(max(int(doc.get("stage", 0)), 0),
                                 len(STAGE_NAMES) - 1)
            except (TypeError, ValueError):
                self.level, self.stage = 1.0, 0
        self._apply_level()
        self._apply_stage()
        self._publish()

    # ------------------------------------------------------------ decide
    def decide(self, saturation: Dict[str, float]) -> Dict:
        """One control-loop step off this sweep's saturation gauges.
        Returns the post-step state dict (tests, structured logging)."""
        if saturation:
            self.worst_resource, self.worst_value = max(
                saturation.items(), key=lambda kv: kv[1])
        else:
            self.worst_resource, self.worst_value = None, 0.0
        with tracing.span("admission.decide",
                          worst=self.worst_resource or "",
                          saturation=round(self.worst_value, 4)):
            prev_stage = self.stage
            self._step_level(self.worst_value)
            self._apply_level()
            self._step_stage()
            if self.stage != prev_stage:
                self._flip(prev_stage)
            self._publish()
        return self.state()

    def _step_level(self, worst: float) -> None:
        ac = self.ac
        if worst >= ac.engage_saturation:
            # deeper overload sheds faster, but even AT the threshold a
            # quarter-step applies — a gauge pinned exactly at engage
            # must not be a stable no-op
            span = max(1.0 - ac.engage_saturation, 1e-9)
            severity = min((worst - ac.engage_saturation) / span, 1.0)
            self.level = max(
                ac.level_floor,
                self.level - ac.decrease_step * max(severity, 0.25))
        elif worst < ac.release_saturation:
            self.level = min(1.0, self.level + ac.recover_step)
        # else: the hysteresis dead zone [release, engage) — hold

    def _apply_level(self) -> None:
        """The level IS the refill scale: every adaptive front-door
        bucket replenishes at level * configured rate (launch tokens are
        a saturation INPUT, not an output — scaling them would close a
        feedback loop through the matcher)."""
        for limiter in self._scaled_limiters():
            limiter.set_refill_scale(self.level)

    def _scaled_limiters(self):
        out = []
        rl = self.rate_limits
        if rl is not None and hasattr(rl.job_submission,
                                      "set_refill_scale"):
            out.append(rl.job_submission)
        if self.ip_limiter is not None and hasattr(self.ip_limiter,
                                                   "set_refill_scale"):
            out.append(self.ip_limiter)
        return out

    def _target_stage(self) -> int:
        ac = self.ac
        if self.level < ac.shed_writes_level:
            return 3
        if self.level < ac.stale_reads_level:
            return 2
        if self.level < ac.observability_shed_level:
            return 1
        return 0

    def _step_stage(self) -> None:
        target = self._target_stage()
        now = self._now_ms()
        if target >= self.stage:
            # escalation (or holding): immediate, dwell resets
            self.stage = target
            self._above_since_ms = None
            return
        # de-escalation: one stage per stage_hold_seconds of SUSTAINED
        # recovery — a brief dip below the overload must not whipsaw
        # the shed surface back on (that retry stampede is the exact
        # metastable trigger the ladder exists to break)
        if self._above_since_ms is None:
            self._above_since_ms = now
            return
        if now - self._above_since_ms >= self.ac.stage_hold_seconds * 1000:
            self.stage -= 1
            self._above_since_ms = now

    # ------------------------------------------------- storage escalation
    def force_shed_writes(self, reason: str) -> None:
        """Jump straight to stage 3 (shed-writes) outside the saturation
        loop — the storage plane's ENOSPC clean-abort path
        (state/store.py ``StorageFullError``).  A full disk is not a
        load problem the level feedback can see, but the remedy is the
        same shed surface: stop admitting low-priority writes before
        retries hammer a journal that cannot append.  The level is
        pinned below ``shed_writes_level`` so :meth:`_step_stage` holds
        the stage; normal dwell-gated recovery applies once appends
        succeed again (and a still-full disk re-forces on the next
        failed write).  The journaled flip is best-effort by
        construction — the disk that triggered this is the same disk
        the flip record would land on."""
        if self.stage >= 3:
            return
        prev_stage = self.stage
        self.stage = 3
        self.level = min(self.level,
                         max(self.ac.level_floor,
                             self.ac.shed_writes_level - 1e-6))
        self._above_since_ms = None
        self.worst_resource = reason
        self._apply_level()
        self._flip(prev_stage)
        self._publish()

    # -------------------------------------------------------- stage flip
    def _flip(self, prev_stage: int) -> None:
        now = self._now_ms()
        self._apply_stage()
        flip = {"from": prev_stage, "to": self.stage,
                "from_name": STAGE_NAMES[prev_stage],
                "to_name": STAGE_NAMES[self.stage],
                "level": round(self.level, 4),
                "worst": self.worst_resource,
                "ts_ms": now}
        self.transitions.append(flip)
        del self.transitions[:-64]
        # journal the flip through the dynamic-config plane: an ordinary
        # "w" record — fsynced, replicated, replayed at promotion — so
        # failover recovers the stage without a new journal record kind
        try:
            self.store.update_dynamic_config(CONFIG_KEY, {
                "stage": self.stage,
                "stage_name": STAGE_NAMES[self.stage],
                "level": round(self.level, 4),
                "changed_ms": now,
                "worst": self.worst_resource})
        except Exception:
            # a fenced/deposed leader can't journal; the in-memory stage
            # still applies locally and the NEXT leader re-derives
            pass

    def _apply_stage(self) -> None:
        """Re-apply the current stage's shed side effects (idempotent;
        also the restore path).  Stage actions are nested ``>= k``
        checks, so a multi-threshold jump engages every stage below it
        and the shed order stays monotone by construction."""
        shed_obs = self.stage >= 1
        from ..state.partition import substores
        for shard in substores(self.store):
            audit = getattr(shard, "audit", None)
            if audit is not None:
                audit.shed_advisory = shed_obs
        obs = self.request_obs
        if obs is not None:
            obs.capture = not shed_obs

    # ----------------------------------------------------------- surface
    def _publish(self) -> None:
        self.registry.gauge_set("cook_admission_level",
                                round(self.level, 4))
        self.registry.gauge_set("cook_brownout_stage", float(self.stage))

    def state(self) -> Dict:
        """The /debug/health "admission" block (also what tests poll)."""
        return {
            "enabled": bool(self.ac.enabled),
            "level": round(self.level, 4),
            "stage": self.stage,
            "stage_name": STAGE_NAMES[self.stage],
            "worst_resource": self.worst_resource,
            "worst_saturation": round(self.worst_value, 4),
            "transitions": list(self.transitions[-8:]),
        }


def stage_from_store(store) -> int:
    """The journaled brownout stage as visible in ``store`` — the
    follower-side read (the ``configs`` table replicates like any other
    entity state, so standbys see flips at replication latency)."""
    try:
        doc = store.dynamic_config(CONFIG_KEY)
    except Exception:
        return 0
    if not doc:
        return 0
    try:
        return min(max(int(doc.get("stage", 0)), 0), len(STAGE_NAMES) - 1)
    except (TypeError, ValueError):
        return 0
