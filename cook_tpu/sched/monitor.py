"""User/pool gauge sweeper.

Parity with the reference's monitor (reference: scheduler/src/cook/
monitor.clj:35-207 set-stats-counters!): per pool, compute per-user
running/waiting resource stats, derive **starved** users (waiting users
whose running usage is below their fair share on every dimension),
**waiting-under-quota** users (waiting users whose running usage is below
their quota on every dimension), **hungry** (waiting but not starved) and
**satisfied** (running and not waiting) user counts, and publish everything
as gauges — including an aggregated pseudo-user ``all`` and zeroing of
series for users that disappeared since the previous sweep
(clear-old-counters!, monitor.clj:137-156).

The sweep is also the SLO layer (config.SloConfig): per-pool pending-age
distributions vs the queue-latency objective and the flight recorder's
recent cycle durations vs the cycle-duration objective, published as
``cook_slo_objective_seconds`` / ``cook_slo_breach_ratio`` /
``cook_slo_burn_rate`` gauges plus a sampled
``cook_queue_latency_seconds`` histogram — the alerting surface every
perf PR is judged against (docs/OBSERVABILITY.md).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

from ..config import Config, SloConfig
from ..state.store import Store
from ..utils.metrics import LATENCY_BUCKETS, MetricsRegistry
from ..utils.metrics import registry as default_registry

_STAT_DIMS = ("cpus", "mem", "jobs")


def _job_stats(jobs_with_user: List[Tuple[str, float, float]]
               ) -> Dict[str, Dict[str, float]]:
    """[(user, cpus, mem)] -> user -> {cpus, mem, jobs} (reference:
    get-job-stats monitor.clj:40-57)."""
    stats: Dict[str, Dict[str, float]] = {}
    for user, cpus, mem in jobs_with_user:
        s = stats.setdefault(user, {"cpus": 0.0, "mem": 0.0, "jobs": 0.0})
        s["cpus"] += cpus
        s["mem"] += mem
        s["jobs"] += 1
    return stats


def _with_aggregate(stats: Dict[str, Dict[str, float]]
                    ) -> Dict[str, Dict[str, float]]:
    """Add the pseudo-user 'all' summing every user (add-aggregated-stats,
    monitor.clj:59-68)."""
    total = {"cpus": 0.0, "mem": 0.0, "jobs": 0.0}
    for s in stats.values():
        for k in _STAT_DIMS:
            total[k] += s.get(k, 0.0)
    out = dict(stats)
    out["all"] = total
    return out


def compute_starved_stats(store: Store, pool_name: str,
                          running: Dict[str, Dict[str, float]],
                          waiting: Dict[str, Dict[str, float]]
                          ) -> Dict[str, Dict[str, float]]:
    """Waiting users whose running usage is strictly below their share on
    every share dimension; starvation = min(waiting, share - running)
    (get-starved-job-stats, monitor.clj:70-90)."""
    out: Dict[str, Dict[str, float]] = {}
    for user in waiting:
        share = store.get_share(user, pool_name)
        used = running.get(user, {})
        promised = {k: share.get(k, float("inf")) for k in ("cpus", "mem")}
        if all(used.get(k, 0.0) < v for k, v in promised.items()):
            out[user] = {
                k: min(waiting[user].get(k, 0.0),
                       promised.get(k, float("inf")) - used.get(k, 0.0))
                for k in _STAT_DIMS if k != "jobs"}
            out[user]["jobs"] = waiting[user].get("jobs", 0.0)
    return out


def compute_waiting_under_quota_stats(store: Store, pool_name: str,
                                      running: Dict[str, Dict[str, float]],
                                      waiting: Dict[str, Dict[str, float]]
                                      ) -> Dict[str, Dict[str, float]]:
    """Waiting users whose running usage is strictly below quota on every
    quota dimension; amount = min(waiting, max(quota - running, 0))
    (get-waiting-under-quota-job-stats, monitor.clj:92-117)."""
    out: Dict[str, Dict[str, float]] = {}
    for user in waiting:
        quota = store.get_quota(user, pool_name)
        used = running.get(user, {})
        promised = {"cpus": quota.get("cpus", float("inf")),
                    "mem": quota.get("mem", float("inf")),
                    "jobs": quota.get("count", float("inf"))}
        if all(used.get(k, 0.0) < v for k, v in promised.items()):
            out[user] = {
                k: min(waiting[user].get(k, 0.0),
                       max(promised[k] - used.get(k, 0.0), 0.0))
                for k in _STAT_DIMS}
    return out


class Monitor:
    """Periodic stats sweeper publishing per-user per-pool gauges
    (start-collecting-stats, monitor.clj:209)."""

    def __init__(self, store: Store,
                 registry: Optional[MetricsRegistry] = None,
                 config: Optional[Config] = None):
        self.store = store
        self.registry = registry if registry is not None else default_registry
        self.slo: SloConfig = (config.slo if config is not None
                               else SloConfig())
        # (pool, state) -> {user -> stats} from the previous sweep, so
        # series for vanished users can be zeroed
        self._previous: Dict[Tuple[str, str], Dict[str, Dict]] = {}

    # ------------------------------------------------------------- one sweep
    def sweep(self) -> Dict[str, Dict[str, int]]:
        """Recompute and publish all gauges; returns per-pool user counts
        (total/starved/hungry/satisfied/waiting_under_quota) for tests and
        structured logging."""
        out: Dict[str, Dict[str, int]] = {}
        for pool in self.store.pools():
            out[pool.name] = self._sweep_pool(pool.name)
        self._sweep_cycle_slo()
        return out

    def _sweep_pool(self, pool_name: str) -> Dict[str, int]:
        pending = self.store.pending_jobs(pool_name)
        running_stats = _job_stats([
            (job.user, job.resources.cpus, job.resources.mem)
            for job, _inst in self.store.running_instances(pool_name)])
        waiting_stats = _job_stats([
            (job.user, job.resources.cpus, job.resources.mem)
            for job in pending])
        self._sweep_queue_slo(pool_name, pending)
        starved = compute_starved_stats(
            self.store, pool_name, running_stats, waiting_stats)
        under_quota = compute_waiting_under_quota_stats(
            self.store, pool_name, running_stats, waiting_stats)

        running_users = set(running_stats)
        waiting_users = set(waiting_stats)
        counts = {
            "total": len(running_users | waiting_users),
            "starved": len(starved),
            "waiting_under_quota": len(under_quota),
            "hungry": len(waiting_users - set(starved)),
            "satisfied": len(running_users - waiting_users),
        }
        for state, stats in (("running", running_stats),
                             ("waiting", waiting_stats),
                             ("starved", starved),
                             ("waiting-under-quota", under_quota)):
            self._publish_state(pool_name, state, stats)
        for state, value in counts.items():
            self.registry.gauge_set(
                "cook_user_state_count", float(value),
                labels={"pool": pool_name, "state": state.replace("_", "-")})
        return counts

    def _publish_state(self, pool_name: str, state: str,
                       stats: Dict[str, Dict[str, float]]) -> None:
        key = (pool_name, state)
        previous: Set[str] = set(self._previous.get(key, {}))
        with_all = _with_aggregate(stats) if stats else {
            "all": {k: 0.0 for k in _STAT_DIMS}}
        for user in previous - set(with_all):
            for dim in _STAT_DIMS:
                self.registry.gauge_set(
                    "cook_user_resource", 0.0,
                    labels={"pool": pool_name, "user": user, "state": state,
                            "resource": dim})
        self._previous[key] = dict(stats)
        for user, s in with_all.items():
            for dim in _STAT_DIMS:
                self.registry.gauge_set(
                    "cook_user_resource", float(s.get(dim, 0.0)),
                    labels={"pool": pool_name, "user": user, "state": state,
                            "resource": dim})

    # ------------------------------------------------------------------- SLO
    def _publish_slo(self, slo_name: str, objective_s: float,
                     breach_ratio: float,
                     pool: Optional[str] = None) -> None:
        labels = {"slo": slo_name}
        if pool is not None:
            labels["pool"] = pool
        self.registry.gauge_set("cook_slo_objective_seconds", objective_s,
                                labels=labels)
        self.registry.gauge_set("cook_slo_breach_ratio", breach_ratio,
                                labels=labels)
        budget = max(self.slo.error_budget, 1e-9)
        self.registry.gauge_set("cook_slo_burn_rate", breach_ratio / budget,
                                labels=labels)

    def _sweep_queue_slo(self, pool_name: str, pending) -> None:
        """Pending-age distribution vs the queue-latency objective.  Ages
        are sampled at sweep time (a job still waiting counts against the
        SLO *now*, not only once it finally launches — the launch-time
        wait histogram is observed separately by the matcher).  The age
        basis is the CURRENT wait (last_waiting_start_ms, the same basis
        the store stamps queue_time_ms from): a retried job re-enters the
        queue with a fresh clock, it does not inherit hours of prior
        runtime as instant SLO breach."""
        now_ms = self.store.clock()
        ages = [(now_ms - (j.last_waiting_start_ms or j.submit_time_ms))
                / 1000.0 for j in pending]
        self.registry.observe_many("cook_queue_age_seconds", ages,
                                   labels={"pool": pool_name},
                                   buckets=LATENCY_BUCKETS)
        obj = self.slo.queue_latency_objective_s
        breach = sum(1 for a in ages if a > obj)
        ratio = breach / len(ages) if ages else 0.0
        self._publish_slo("queue-latency", obj, ratio, pool=pool_name)

    def _sweep_cycle_slo(self) -> None:
        """Cycle-duration burn rate over the flight recorder's recent
        window (fused/match cycles only — rank/rebalance cadences have
        their own budgets and would dilute the signal)."""
        from ..utils.flight import recorder
        obj = self.slo.cycle_duration_objective_s
        # kind-filtered BEFORE the window cut: rank/rebalance records
        # interleave with the match cadence and would otherwise silently
        # shrink the configured window
        durations = recorder.recent_durations(("fused", "match"),
                                              self.slo.cycle_window)
        breach = sum(1 for d in durations if d > obj * 1000.0)
        ratio = breach / len(durations) if durations else 0.0
        self._publish_slo("cycle-duration", obj, ratio)
